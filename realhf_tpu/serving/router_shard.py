"""Sharded router plane: N-way FleetRouter shards with lease/epoch
failover (docs/serving.md "Sharded router plane").

One :class:`~realhf_tpu.serving.router.FleetRouter` is a single point
of failure and a throughput ceiling: every request funnels through its
one front socket, and ``apps.main.run_serve`` treats its loss as
fatal. This module splits the plane into N :class:`ShardedRouter`
shards that divide the rid space by consistent hash
(``serving/ring.py``) over a ring published in the
:class:`~realhf_tpu.serving.fleet.FleetRegistry`:

- **Ownership**: each rid has exactly one owning shard,
  ``Ring.owner_of(rid)`` over the live ``routers/`` subtree. A submit
  arriving at a non-owner is bounced with a ``wrong_owner`` reply
  naming the owner; :class:`ShardedRolloutClient` re-resolves and
  resubmits (never more than a bounce or two once views converge).
- **Lease/epoch**: every shard holds its own leased registration with
  a persistent fencing epoch (``FleetRegistry.register_router``,
  reusing ``register_with_epoch``). A shard whose lease lapses is
  FENCED: it flushes all undelivered state WITHOUT terminals (its
  range was re-homed; a late send would be a duplicate) and
  re-registers under a new epoch before routing again.
- **Re-home**: an admitted rid is journaled in the registry
  (``journal/<rid>`` -> owner + re-dispatch envelope, cleared on
  terminal delivery). When a shard's lease vanishes, each survivor
  adopts the journaled rids that now hash to it and re-dispatches
  them with the existing ``retried_from``/at-most-once ``_done``
  machinery. The adopted request has no client connection yet, so a
  terminal arriving first is PARKED and handed over when the client's
  resubmission re-attaches -- exactly-once delivery survives a router
  SIGKILL mid-burst (``scripts/chaos_drill.py --scenario
  router_kill``).
- **Replica-side idempotency**: a survivor may re-dispatch a rid to
  the replica that is still generating it for the dead shard;
  ``RolloutServer`` re-attaches the route to the newest submitter
  instead of double-queueing, so the work continues and the terminal
  flows to the live shard.

Every retire-from-``_requests``/``_pending`` path here is covered by
the graft-lint ``terminal`` checker (docs/static_analysis.md); the
deliberate terminal-less fence flush carries its inline disable.
"""

import base64
import collections
import pickle
import time
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np
import zmq

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics, tracing
from realhf_tpu.serving import protocol
from realhf_tpu.serving.fleet import FleetRegistry, LeaseLostError
from realhf_tpu.serving.request_queue import Priority
from realhf_tpu.serving.ring import Ring
from realhf_tpu.serving.protocol import TERMINAL_KINDS
from realhf_tpu.serving.router import FleetRouter, _RouterRequest
from realhf_tpu.serving.server import RolloutResult

logger = logging.getLogger("serving.router_shard", "system")


def encode_journal(owner: str, prompt, priority: int,
                   ttl: Optional[float], min_wv: int) -> str:
    """Journal value: ``owner|base64(pickle(envelope))``. The envelope
    carries everything a survivor needs to re-dispatch the rid; the
    ttl is the ORIGINAL budget (an adopter restarts it -- failover
    must not shrink a request's remaining time to zero)."""
    env = dict(prompt=np.asarray(prompt, np.int32).tolist(),
               priority=int(priority), ttl=ttl, min_wv=int(min_wv))
    return owner + "|" + base64.b64encode(
        pickle.dumps(env)).decode("ascii")


def decode_journal(payload: str):
    """-> (owner, envelope dict); raises ValueError on malformed."""
    owner, b64 = payload.split("|", 1)
    return owner, pickle.loads(base64.b64decode(b64))


class ShardedRouter(FleetRouter):
    """One shard of the sharded router plane (module docstring)."""

    def __init__(self, registry: FleetRegistry, *,
                 router_name: str = "router/0",
                 ring_vnodes: int = 64,
                 chaos=None,
                 clock: Callable[[], float] = time.monotonic,
                 **kw):
        # each shard publishes its rendezvous key under its OWN name
        # (the singleton key "router" belongs to unsharded mode)
        kw.setdefault("publish_name", router_name)
        self._ring = Ring([router_name], n_vnodes=ring_vnodes)
        self.ring_vnodes = ring_vnodes
        self._router_infos: Dict[str, object] = {}
        self._fenced = False
        #: fenced with no way back: a NEWER incarnation of this name
        #: registered (higher epoch) -- re-registering would start an
        #: epoch war, so this shard stays quiet forever
        self._superseded = False
        self._last_ring_poll = -1e9
        self._journal_sweep_due = True
        #: sweep the journal every Nth ring poll even without a
        #: membership change: catches stragglers a racing sweep
        #: skipped (e.g. an entry disowned by a recovering shard)
        self._sweep_every = 10
        self._ring_polls = 0
        #: terminals for adopted rids whose client has not re-attached
        #: yet: handed over on resubmission, bounded like _done
        self._parked: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self._parked_cap = 2048
        super().__init__(registry, router_name=router_name,
                         chaos=chaos, clock=clock, **kw)
        self.stats_counters.update(
            wrong_owner=0, reattached=0, adopted=0,
            parked_terminals=0, router_fences=0)
        self.router_epoch = registry.register_router(router_name,
                                                     self.address)
        self._router_lease_renewed = self._clock()
        self._refresh_ring(force=True)

    # -- lease / fencing -----------------------------------------------
    def _router_lease_upkeep(self):
        """Renew this shard's lease on a ttl/3 cadence; on loss,
        fence: flush undelivered state terminal-lessly (survivors
        adopted the range) and re-register under a fresh epoch."""
        if self._superseded:
            return  # permanently quiet: a newer incarnation owns us
        if self._chaos is not None \
                and self._chaos.partitioned(self.router_name):
            return  # registry unreachable: the lease decays
        now = self._clock()
        if not self._fenced:
            if now - self._router_lease_renewed \
                    < self.registry.lease_ttl / 3.0:
                return
            try:
                self.registry.renew_router(self.router_name)
                self._router_lease_renewed = now
                return
            except LeaseLostError:
                self._fence(protocol.WHY_LEASE_EXPIRED)
        # fenced: drop pre-fence state, then rejoin at a new epoch.
        # The post-rejoin journal sweep re-adopts any of OUR journaled
        # rids a survivor has not claimed yet, so the flush loses no
        # request for good.
        dropped = self._flush_fenced_router()
        self.router_epoch = self.registry.register_router(
            self.router_name, self.address)
        self._router_lease_renewed = self._clock()
        self._fenced = False
        self._journal_sweep_due = True
        logger.warning(
            "Router shard %s was fenced: %d request(s) dropped "
            "(re-homed by survivors); re-registered with epoch %d.",
            self.router_name, dropped, self.router_epoch)

    def _fence(self, why: str, permanent: bool = False):
        if permanent and not self._superseded:
            self._superseded = True
            # a superseded zombie never delivers again: flush now so
            # nothing lingers waiting for an upkeep that won't rejoin
            if not self._fenced:
                self._fenced = True
                self.stats_counters["router_fences"] += 1
                metrics.inc("router_shard_fenced_total",
                            router=self.router_name)
            self._flush_fenced_router()
            logger.warning("Router shard %s FENCED permanently (%s).",
                           self.router_name, why)
            return
        if self._fenced:
            return
        self._fenced = True
        self.stats_counters["router_fences"] += 1
        metrics.inc("router_shard_fenced_total",
                    router=self.router_name)
        logger.warning("Router shard %s FENCED (%s): going quiet "
                       "until re-registration.", self.router_name, why)

    def _flush_fenced_router(self) -> int:
        """Drop every tracked request WITHOUT terminal events: a
        fenced shard must deliver nothing -- its hash range was
        re-homed to survivors, and a late terminal from here would be
        a duplicate of the adopter's."""
        n = len(self._requests)
        for rep in self._replicas.values():
            rep.inflight.clear()
        # deliberate terminal-less retirement (fence flush, same
        # contract as RolloutServer._flush_fenced): the adopting
        # survivor owes the client its single terminal, not us
        self._requests.clear()  # graft-lint: disable=proto-missing-terminal
        self._pending.clear()  # graft-lint: disable=proto-missing-terminal
        metrics.inc("router_shard_fenced_dropped_total", amount=n,
                    router=self.router_name)
        return n

    # -- ring membership / adoption ------------------------------------
    def _refresh_ring(self, force: bool = False):
        now = self._clock()
        if not force and now - self._last_ring_poll \
                < self.fleet_poll_interval:
            return
        if self._chaos is not None \
                and self._chaos.partitioned(self.router_name):
            return
        self._last_ring_poll = now
        routers = self.registry.routers()
        self._router_infos = routers
        me = routers.get(self.router_name)
        if me is not None and me.epoch > self.router_epoch:
            # someone re-registered our name at a higher epoch: WE are
            # the zombie incarnation -- quiet forever, never rejoin
            self._fence("superseded by epoch %d" % me.epoch,
                        permanent=True)
            return
        names = set(routers)
        if not self._fenced:
            # our own lease may have lapsed without us noticing yet;
            # upkeep will fence us, but until then we still route
            names.add(self.router_name)
        new_ring = Ring(sorted(names), n_vnodes=self.ring_vnodes)
        if new_ring != self._ring:
            logger.info("Router shard %s: ring now %s.",
                        self.router_name, list(new_ring.names))
            self._ring = new_ring
            self._journal_sweep_due = True
        metrics.set_gauge("router_shard_ring_size",
                          len(self._ring.names))
        self._ring_polls += 1
        if not self._fenced and (
                self._journal_sweep_due
                or self._ring_polls % self._sweep_every == 0):
            self._journal_sweep_due = False
            self._adopt_orphans(set(routers))

    def _adopt_orphans(self, live_routers: set):
        """Adopt journaled rids whose recorded owner is no longer in
        the ring and whose hash range now lands here; re-dispatch them
        with the standard ``retried_from`` failover machinery. The
        client's resubmission (it re-resolves when its target leaves
        the ring) re-attaches the delivery path."""
        try:
            entries = self.registry.journal()
        except Exception as e:  # noqa: BLE001 - registry hiccups must
            # not kill the routing loop; the sweep re-arms
            logger.warning("Router shard %s: journal sweep failed: "
                           "%s", self.router_name, e)
            self._journal_sweep_due = True
            return
        now = self._clock()
        for rid, payload in sorted(entries.items()):
            try:
                owner, env = decode_journal(payload)
            except Exception:  # noqa: BLE001 - malformed entries are
                # skipped, never fatal
                continue
            if rid in self._requests or rid in self._done:
                continue  # tracked here; _finish clears the journal
            if owner != self.router_name and owner in live_routers:
                continue  # its owner is alive and serving it
            if self._ring.owner_of(rid) != self.router_name:
                if owner == self.router_name:
                    # we journaled it but fenced-flushed it, and the
                    # ring re-homed it elsewhere meanwhile: DISOWN the
                    # entry (owner "" is never live) so the ring
                    # owner's periodic sweep adopts it
                    try:
                        self.registry.journal_rid(
                            rid, "" + payload[payload.index("|"):])
                    except Exception:  # noqa: BLE001
                        pass
                continue
            ttl = env.get("ttl")
            req = _RouterRequest(
                rid=rid, ident=None,
                # a journaled prompt is a plain Python list; this is a
                # host-side conversion, not a device sync
                prompt=np.asarray(env["prompt"], np.int32),  # graft-lint: disable=purity-sync-in-loop
                priority=int(env.get("priority", 0)),
                min_weight_version=int(env.get("min_wv", 0)),
                trace=None, created_at=now,
                deadline=None if ttl is None else now + ttl,
                last_event_at=now,
                retried_from=[owner or "<disowned>"])
            self._requests[rid] = req
            self._pending.append(rid)
            self._journal(req)  # re-home the journal entry to us
            self.stats_counters["adopted"] += 1
            metrics.inc("router_shard_adopted_total",
                        router=self.router_name)
            logger.info("Router shard %s adopted rid %s from dead "
                        "shard %s.", self.router_name, rid, owner)

    def _journal(self, req: _RouterRequest):
        ttl = None if req.deadline is None \
            else max(0.05, req.deadline - req.created_at)
        try:
            self.registry.journal_rid(
                req.rid,
                encode_journal(self.router_name, req.prompt,
                               req.priority, ttl,
                               req.min_weight_version))
        except Exception as e:  # noqa: BLE001 - journaling is a
            # durability upgrade, not an admission gate
            logger.warning("Router shard %s: journal write for %s "
                           "failed: %s", self.router_name, req.rid, e)

    # -- routing loop --------------------------------------------------
    def route_step(self, poll_timeout: float = 0.0) -> int:
        self._router_lease_upkeep()
        self._refresh_ring()
        handled = super().route_step(poll_timeout)
        metrics.set_gauge("router_shard_inflight",
                          len(self._requests),
                          router=self.router_name)
        return handled

    def _handle_client(self, ident: bytes, msg: tuple):
        if self._fenced:
            return  # a fenced shard answers nothing (clients re-resolve)
        kind = msg[0]
        if kind == protocol.SUBMIT:
            rid = msg[1]
            if rid in self._done:
                parked = self._parked.pop(rid, None)
                if parked is not None:
                    # the adopted rid finished before its client
                    # re-attached: hand over the single terminal now
                    k, d = parked
                    self._send_ident(ident, k, rid, d)
                else:
                    self.stats_counters["stale_events"] += 1
                return
            req = self._requests.get(rid)
            if req is not None:
                if req.ident != ident:
                    # failover re-attach: the client re-resolved to us
                    # (we adopted the rid, or its old connection died)
                    req.ident = ident
                    self.stats_counters["reattached"] += 1
                    metrics.inc("router_shard_reattached_total",
                                router=self.router_name)
                    self._reply(ident, protocol.ACCEPTED, rid,
                                dict(reattached=True))
                return
            owner = self._ring.owner_of(rid)
            if owner is not None and owner != self.router_name:
                info = self._router_infos.get(owner)
                self.stats_counters["wrong_owner"] += 1
                metrics.inc("router_shard_wrong_owner_total",
                            router=self.router_name)
                self._reply(ident, protocol.WRONG_OWNER, rid, dict(
                    owner=owner,
                    address=getattr(info, "address", None),
                    ring=list(self._ring.names)))
                return
            super()._handle_client(ident, msg)
            accepted = self._requests.get(rid)
            if accepted is not None:
                self._journal(accepted)
            return
        super()._handle_client(ident, msg)

    # -- delivery ------------------------------------------------------
    def _send_ident(self, ident, kind: str, rid: str, data: dict):
        if self._fenced:
            return  # fenced late sends deliver NOTHING
        if ident is None:
            # adopted rid, client not re-attached yet: park terminals
            # (intermediate events are droppable; the client replays
            # from `accepted` after re-attach)
            if kind in TERMINAL_KINDS:
                self._parked[rid] = (kind, data)
                self.stats_counters["parked_terminals"] += 1
                metrics.inc("router_shard_parked_terminals_total",
                            router=self.router_name)
                while len(self._parked) > self._parked_cap:
                    self._parked.popitem(last=False)
            return
        super()._send_ident(ident, kind, rid, data)

    def _send_replica(self, rname: str, envelope: tuple) -> bool:
        if self._fenced:
            return False  # a fenced shard dispatches nothing either
        return super()._send_replica(rname, envelope)

    def _finish(self, req, kind: str, data: dict,
                from_replica: Optional[str]):
        first = req.rid not in self._done
        super()._finish(req, kind, data, from_replica)
        if first:
            self.registry.clear_rid(req.rid)

    # -- lifecycle -----------------------------------------------------
    def close(self):
        if not self._closed and not self._fenced:
            self.registry.deregister_router(self.router_name)
        super().close()

    def stats(self) -> dict:
        out = super().stats()
        out.update(router_epoch=self.router_epoch,
                   fenced=self._fenced,
                   ring=list(self._ring.names),
                   parked=len(self._parked))
        return out


# ----------------------------------------------------------------------
class _ClientRequest:
    __slots__ = ("prompt", "priority", "ttl", "min_wv", "target",
                 "target_epoch", "bounces", "submitted_at")

    def __init__(self, prompt, priority, ttl, min_wv, target, now):
        self.prompt = prompt
        self.priority = priority
        self.ttl = ttl
        self.min_wv = min_wv
        self.target = target
        #: the target shard's fencing epoch at submit time: an epoch
        #: bump means the shard fenced (flushing its in-flight state)
        #: and rejoined, so the rid must be resubmitted even though
        #: the name never left the ring
        self.target_epoch: Optional[int] = None
        self.bounces = 0
        self.submitted_at = now


class ShardedRolloutClient:
    """Client for the sharded router plane.

    Discovers router shards through the :class:`FleetRegistry`, routes
    each rid to its ring owner, follows ``wrong_owner`` bounces, and
    -- the failover path -- resubmits any in-flight rid whose target
    shard left the ring. The resubmission is idempotent end to end:
    the adopting shard re-attaches the rid (or replays its parked
    terminal), and a replica already generating it re-attaches its
    route rather than double-queueing.

    Single-threaded like :class:`RolloutClient`. The wire is
    at-least-once under fence/crash faults (a resubmission can race a
    terminal already in flight, and a restarted shard has no memory
    of pre-crash deliveries -- the bounded model checker in
    ``analysis/model.py`` derives both races), so exactly-once is
    enforced HERE, at the harvest boundary: the first terminal per
    rid wins, later ones are suppressed and counted in
    ``stats["dup_terminals"]`` where the chaos drill can see them.
    """

    def __init__(self, registry: FleetRegistry, *,
                 ring_poll_interval: float = 0.25,
                 max_bounces: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.ring_poll_interval = ring_poll_interval
        self.max_bounces = max_bounces
        self._clock = clock
        self._ctx = zmq.Context.instance()
        self._socks: Dict[str, zmq.Socket] = {}
        self._addresses: Dict[str, str] = {}
        self._epochs: Dict[str, int] = {}
        self._ring = Ring([])
        self._last_ring_poll = -1e9
        self._inflight: Dict[str, _ClientRequest] = {}
        self._events: Dict[str, List[tuple]] = {}
        #: rids whose terminal was already surfaced: late duplicates
        #: (failover regeneration) are dropped, not re-delivered
        self._closed: "collections.OrderedDict[str, bool]" = \
            collections.OrderedDict()
        self._closed_cap = 4096
        self.stats = dict(submits=0, bounces=0, resubmits=0,
                          give_ups=0, dup_terminals=0)

    # -- discovery -----------------------------------------------------
    def _refresh_ring(self, force: bool = False):
        now = self._clock()
        if not force and now - self._last_ring_poll \
                < self.ring_poll_interval:
            return
        self._last_ring_poll = now
        routers = self.registry.routers()
        for name, info in routers.items():
            if self._addresses.get(name) != info.address:
                old = self._socks.pop(name, None)
                if old is not None:
                    old.close(0)
                sock = self._ctx.socket(zmq.DEALER)
                try:
                    sock.connect(info.address)
                except BaseException:
                    sock.close(0)
                    raise
                self._socks[name] = sock
                self._addresses[name] = info.address
        for name in list(self._socks):
            if name not in routers:
                self._socks.pop(name).close(0)
                self._addresses.pop(name, None)
        self._epochs = {n: info.epoch for n, info in routers.items()}
        self._ring = Ring(sorted(routers))

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block (real time) until at least one router shard is
        registered. Returns readiness; never raises on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._refresh_ring(force=True)
            if self._ring:
                return True
            time.sleep(0.1)
        return False

    # -- submission ----------------------------------------------------
    def _send_to(self, target: str, payload: tuple) -> bool:
        sock = self._socks.get(target)
        if sock is None:
            return False
        try:
            sock.send(pickle.dumps(payload))
            return True
        except zmq.ZMQError as e:
            logger.warning("Sharded client: send to %s failed: %s",
                           target, e)
            return False

    def _submit_to(self, target: Optional[str], rid: str,
                   creq: _ClientRequest) -> bool:
        if target is None or target not in self._socks:
            target = self._ring.owner_of(rid)
        if target is None or not self._send_to(
                target, (protocol.SUBMIT, rid, creq.prompt,
                         creq.priority,
                         creq.ttl, creq.min_wv,
                         tracing.inject())):
            return False
        creq.target = target
        creq.target_epoch = self._epochs.get(target)
        return True

    def submit(self, prompt, priority: int = Priority.BATCH,
               ttl: Optional[float] = None,
               rid: Optional[str] = None,
               min_weight_version: int = 0) -> str:
        rid = rid or uuid.uuid4().hex
        self._refresh_ring()
        if not self._ring:
            self._refresh_ring(force=True)
        if not self._ring:
            raise RuntimeError(
                "ShardedRolloutClient.submit: no router shards "
                "registered (wait_ready first).")
        creq = _ClientRequest(np.asarray(prompt, np.int32),
                              int(priority), ttl,
                              int(min_weight_version), None,
                              self._clock())
        self._events.setdefault(rid, [])
        self._inflight[rid] = creq
        self.stats["submits"] += 1
        self._submit_to(self._ring.owner_of(rid), rid, creq)
        return rid

    def cancel(self, rid: str):
        creq = self._inflight.get(rid)
        target = creq.target if creq is not None else None
        self._send_to(target or next(iter(self._socks), ""),
                      (protocol.CANCEL, rid))

    # -- event pump ----------------------------------------------------
    def _on_msg(self, kind: str, rid: str, data: dict):
        if kind == protocol.WRONG_OWNER:
            self.stats["bounces"] += 1
            creq = self._inflight.get(rid)
            if creq is None:
                return
            creq.bounces += 1
            if creq.bounces > self.max_bounces:
                # ring views refuse to converge: surface a terminal
                # instead of bouncing forever
                self.stats["give_ups"] += 1
                self._inflight.pop(rid, None)
                self._events.setdefault(rid, []).append(
                    (protocol.REJECTED,
                     dict(reason=protocol.REASON_RING_UNSTABLE)))
                return
            self._refresh_ring(force=True)
            self._submit_to(data.get("owner"), rid, creq)
            return
        if rid in self._closed:
            # exactly-once at the harvest boundary: this rid already
            # surfaced its terminal; a failover resubmission raced it
            # and the fleet regenerated
            if kind in TERMINAL_KINDS:
                self.stats["dup_terminals"] += 1
            return
        self._events.setdefault(rid, []).append((kind, data))
        if kind in TERMINAL_KINDS:
            self._inflight.pop(rid, None)
            self._closed[rid] = True
            while len(self._closed) > self._closed_cap:
                self._closed.popitem(last=False)

    def _check_failover(self):
        """Resubmit in-flight rids whose target shard left the ring
        -- or fenced and rejoined under a HIGHER epoch (its in-flight
        state was flushed; the rejoined shard re-adopts the rid from
        the journal and parks its terminal until this resubmission
        re-attaches). The at-most-once machinery downstream makes the
        resubmission safe."""
        if not self._inflight:
            return
        names = set(self._ring.names)
        for rid, creq in list(self._inflight.items()):
            gone = creq.target is None or creq.target not in names
            fenced = (not gone and creq.target_epoch is not None
                      and self._epochs.get(creq.target)
                      != creq.target_epoch)
            if gone or fenced:
                if self._submit_to(self._ring.owner_of(rid), rid,
                                   creq):
                    self.stats["resubmits"] += 1

    def _pump(self, timeout: float = 0.0) -> bool:
        self._refresh_ring()
        self._check_failover()
        got = False
        waited = False
        while True:
            progressed = False
            for name, sock in list(self._socks.items()):
                try:
                    while sock.poll(0):
                        kind, rid, data = pickle.loads(sock.recv())
                        self._on_msg(kind, rid, data)
                        got = progressed = True
                except zmq.ZMQError as e:
                    logger.warning("Sharded client: recv from %s "
                                   "failed: %s", name, e)
            if progressed:
                continue
            if got or waited or timeout <= 0 or not self._socks:
                return got
            # one blocking wait across all router sockets
            poller = zmq.Poller()
            for sock in self._socks.values():
                poller.register(sock, zmq.POLLIN)
            poller.poll(timeout * 1000)
            waited = True

    # -- harvest -------------------------------------------------------
    def poll_results(self, timeout: float = 0.0) -> List[RolloutResult]:
        """Non-blocking harvest of terminal outcomes, mirroring
        ``RolloutClient.poll_results``."""
        self._pump(timeout)
        out: List[RolloutResult] = []
        for rid in list(self._events):
            terminal = next(
                ((k, d) for k, d in self._events[rid]
                 if k in TERMINAL_KINDS), None)
            if terminal is not None:
                del self._events[rid]
                out.append(RolloutResult(
                    rid=rid, status=terminal[0], data=terminal[1]))
        return out

    def result(self, rid: str, timeout: float = 60.0) -> RolloutResult:
        """Block (real time) until ``rid`` reaches a terminal state."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._pump(min(0.05, max(0.0,
                                     deadline - time.monotonic())))
            q = self._events.get(rid, [])
            terminal = next(((k, d) for k, d in q
                             if k in TERMINAL_KINDS), None)
            if terminal is not None:
                self._events.pop(rid, None)
                return RolloutResult(rid=rid, status=terminal[0],
                                     data=terminal[1])
        raise TimeoutError(
            f"No terminal for request {rid} within {timeout}s.")

    def close(self):
        for sock in self._socks.values():
            sock.close(0)
        self._socks.clear()
