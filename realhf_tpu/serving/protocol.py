"""The serving wire protocol, declared as data (graft-verify).

Every message on the rollout wire -- client to server/router and back
-- is a pickled tuple. Requests are positional: ``(kind, ...)``;
events are ``(kind, rid, data)`` with a dict payload. This module is
the single normative declaration of that protocol: the event-kind
constants, the per-kind frame schemas (allowed payload fields and
reason strings), and the three state machines the runtime implements
(per-rid client view, router-request lifecycle, shard lifecycle).

The runtime (``serving/{server,router,router_shard,scheduler}.py``)
imports its kinds and reasons from here instead of spelling string
literals; the ``wire`` checker (``analysis/wire.py``) statically
cross-checks every send site against these declarations in both
directions, and the bounded model checker (``analysis/model.py`` +
``analysis/explore.py``) exhaustively explores the declared state
machines under a fault model. docs/serving.md points here; a change
to the protocol starts in this file.

Nothing here imports anything heavier than ``dataclasses`` -- the
static-analysis stack must be able to import it without pulling in
zmq/jax.
"""

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

# ----------------------------------------------------------------------
# Request kinds (client -> server/router; positional tuples)
# ----------------------------------------------------------------------
SUBMIT = "submit"
CANCEL = "cancel"
PING = "ping"

# ----------------------------------------------------------------------
# Event kinds (server/router -> client; ``(kind, rid, data)``)
# ----------------------------------------------------------------------
ACCEPTED = "accepted"
STARTED = "started"
TOKENS = "tokens"
RETRYING = "retrying"
WRONG_OWNER = "wrong_owner"
PONG = "pong"
DONE = "done"
REJECTED = "rejected"
STALE = "stale"
EXPIRED = "expired"
CANCELLED = "cancelled"
DRAINING = "draining"

#: reply kinds that end a request's stream (the server drops its
#: client route after sending one of these; clients key their
#: harvest loops on membership here)
TERMINAL_KINDS = (DONE, REJECTED, STALE, EXPIRED, CANCELLED, DRAINING)

# ----------------------------------------------------------------------
# Reason strings (the ``reason=`` field of rejected/expired/cancelled
# and the failover ``why`` carried by ``retrying``)
# ----------------------------------------------------------------------
# admission verdicts (serving/request_queue.py)
REASON_DRAINING = "draining"
REASON_EXPIRED = "expired"
REASON_PROMPT_TOO_LONG = "prompt_too_long"
REASON_WEIGHTS_BEHIND = "weights_behind"
REASON_BACKPRESSURE = "backpressure"
# scheduler-side rejections (serving/scheduler.py)
REASON_FILL_FAILED = "fill_failed"
REASON_KV_OOM = "kv_oom"
# router-side verdicts (serving/router.py)
REASON_NO_HEALTHY_REPLICA = "no_healthy_replica"
REASON_ROUTER_DRAIN = "router_drain"
# replica drain force-fence (server.finish_drain) -- doubles as the
# failover ``why`` when the router re-shops the victim's request
REASON_DRAIN_DEADLINE = "drain_deadline"
# sharded-client give-up after too many wrong_owner bounces
REASON_RING_UNSTABLE = "ring_unstable"
# HTTP gateway admission verdicts (serving/gateway.py): per-tenant
# token-bucket exhaustion, deadline-infeasibility shedding (the
# request cannot finish before its deadline given queue depth and the
# latency p95), and brownout-ladder load shedding under sustained
# overload. All three fire BEFORE dispatch -- the router never sees
# the rid, and the HTTP error reply is the exactly-once terminal.
REASON_QUOTA = "quota"
REASON_DEADLINE_UNMEETABLE = "deadline_unmeetable"
REASON_BROWNOUT = "brownout"

# failover ``why`` strings (router._fail_assignment -> ``retrying``)
WHY_REREGISTERED = "re-registered"
WHY_LEASE_EXPIRED = "lease expired"
WHY_WATCHDOG_LOST = "watchdog LOST"
WHY_RETIRED = "retired"
WHY_DISPATCH_TIMEOUT = "dispatch timeout"
WHY_RESPONSE_TIMEOUT = "response timeout"

#: admission rejections every replica would decide identically --
#: the router forwards them instead of shopping the request around
DETERMINISTIC_REJECT_REASONS = (REASON_PROMPT_TOO_LONG, REASON_EXPIRED)

REJECT_REASONS = frozenset({
    REASON_DRAINING, REASON_EXPIRED, REASON_PROMPT_TOO_LONG,
    REASON_WEIGHTS_BEHIND, REASON_BACKPRESSURE, REASON_FILL_FAILED,
    REASON_KV_OOM, REASON_NO_HEALTHY_REPLICA, REASON_RING_UNSTABLE,
    REASON_QUOTA, REASON_DEADLINE_UNMEETABLE, REASON_BROWNOUT,
})
RETRY_REASONS = frozenset({
    WHY_REREGISTERED, WHY_LEASE_EXPIRED, WHY_WATCHDOG_LOST,
    WHY_RETIRED, WHY_DISPATCH_TIMEOUT, WHY_RESPONSE_TIMEOUT,
    REASON_DRAIN_DEADLINE,
})


# ----------------------------------------------------------------------
# Frame schemas
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Request:
    """One client->server positional frame: ``(kind, *payload)``."""
    kind: str
    #: tuple arity bounds, *including* the leading kind
    min_arity: int
    max_arity: int
    doc: str = ""


@dataclasses.dataclass(frozen=True)
class Frame:
    """One server->client event frame: ``(kind, rid, data)``."""
    kind: str
    #: every data key any emitter may set (the wire checker flags
    #: undeclared keys at literal send sites)
    fields: FrozenSet[str] = frozenset()
    #: allowed values of ``data["reason"]`` (empty = no reason field)
    reasons: FrozenSet[str] = frozenset()
    terminal: bool = False
    #: some code site must switch on this kind (``kind == X`` or a
    #: TERMINAL_KINDS membership test). False for kinds streamed to
    #: the client verbatim with no dispatch site -- intentionally
    #: undispatched, which the wire checker then does not flag.
    dispatch: bool = True
    #: carries a per-request rid (False only for pong, whose rid is
    #: empty); the FSM cross-check applies to rid-scoped kinds only
    rid_scoped: bool = True
    doc: str = ""


REQUESTS: Dict[str, Request] = {r.kind: r for r in (
    Request(SUBMIT, 6, 7,
            doc="(rid, prompt, priority, ttl, min_weight_version"
                "[, trace_ctx])"),
    Request(CANCEL, 2, 2, doc="(rid,)"),
    Request(PING, 1, 1, doc="()"),
)}

FRAMES: Dict[str, Frame] = {f.kind: f for f in (
    Frame(ACCEPTED, fields=frozenset({"reattached", "queue_depth"}),
          doc="admission ack; reattached=True on a failover/duplicate"
              " re-attach"),
    Frame(STARTED, fields=frozenset({"weight_version"}),
          doc="entered a decode slot"),
    Frame(TOKENS,
          fields=frozenset({"tokens", "logprobs", "offset"}),
          doc="incremental streaming delta"),
    Frame(RETRYING,
          fields=frozenset({"retried_from", "reason"}),
          reasons=RETRY_REASONS, dispatch=False,
          doc="failover: the token stream restarts on another "
              "replica; streamed to the client verbatim (no dispatch "
              "site -- stream consumers reset their accumulation)"),
    Frame(WRONG_OWNER,
          fields=frozenset({"owner", "address", "ring"}),
          doc="shard bounce: resubmit to the named ring owner"),
    Frame(PONG, rid_scoped=False,
          doc="health-probe reply (rid is empty)"),
    Frame(DONE, terminal=True,
          fields=frozenset({
              "tokens", "logprobs", "no_eos", "weight_version",
              "weight_version_final", "queued_secs", "serve_secs",
              "spec_proposed", "spec_accepted", "retried_from"}),
          doc="finished; data carries the FinishedRollout fields"),
    Frame(REJECTED, terminal=True,
          fields=frozenset({"reason", "retry_after", "error",
                            "retried_from"}),
          reasons=REJECT_REASONS,
          doc="refused at admission, by the backend, or by the "
              "router when no replica can take it"),
    Frame(STALE, terminal=True,
          fields=frozenset({"weight_version", "current_version",
                            "max_staleness", "retried_from"}),
          doc="finished/evicted beyond the staleness bound"),
    Frame(EXPIRED, terminal=True,
          fields=frozenset({"reason", "retried_from"}),
          reasons=frozenset({REASON_ROUTER_DRAIN}),
          doc="deadline passed (reason=router_drain when a draining "
              "router expires leftovers)"),
    Frame(CANCELLED, terminal=True,
          fields=frozenset({"reason", "retried_from"}),
          reasons=frozenset({REASON_DRAIN_DEADLINE}),
          doc="client cancel ack, or a drain past its hard deadline "
              "force-fencing in-flight work (reason=drain_deadline)"),
    Frame(DRAINING, terminal=True,
          fields=frozenset({"retried_from"}),
          doc="queued request bounced back by a draining replica"),
)}

EVENT_KINDS = tuple(FRAMES)
REQUEST_KINDS = tuple(REQUESTS)
ALL_KINDS = REQUEST_KINDS + EVENT_KINDS

assert TERMINAL_KINDS == tuple(k for k in EVENT_KINDS
                               if FRAMES[k].terminal)


def is_terminal(kind: str) -> bool:
    return kind in TERMINAL_KINDS


def frame(kind: str) -> Frame:
    return FRAMES[kind]


def validate_event(kind: str, data: dict) -> List[str]:
    """Violations of the declared schema for one event frame (empty
    list = conformant). Runtime-usable (chaos drills, tests) and the
    ground truth the wire checker enforces statically."""
    f = FRAMES.get(kind)
    if f is None:
        return [f"undeclared event kind {kind!r}"]
    errs = [f"{kind}: undeclared field {k!r}"
            for k in sorted(set(data) - f.fields)]
    reason = data.get("reason")
    if reason is not None and f.reasons and reason not in f.reasons:
        errs.append(f"{kind}: undeclared reason {reason!r}")
    return errs


# ----------------------------------------------------------------------
# State machines
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Transition:
    src: str
    dst: str
    #: the wire event kind this transition rides on ("" = internal
    #: action; ``label`` then names it)
    kind: str = ""
    label: str = ""
    guard: str = ""

    def __post_init__(self):
        if not self.kind and not self.label:
            raise ValueError(f"transition {self.src}->{self.dst} "
                             "needs a kind or a label")


@dataclasses.dataclass(frozen=True)
class StateMachine:
    name: str
    initial: str
    states: Tuple[str, ...]
    transitions: Tuple[Transition, ...]
    doc: str = ""

    def validate(self) -> List[str]:
        """Internal-consistency violations (empty = well-formed)."""
        errs = []
        if self.initial not in self.states:
            errs.append(f"{self.name}: initial state "
                        f"{self.initial!r} undeclared")
        for t in self.transitions:
            for s in (t.src, t.dst):
                if s not in self.states:
                    errs.append(f"{self.name}: transition "
                                f"{t.src}->{t.dst} uses undeclared "
                                f"state {s!r}")
            if t.kind and t.kind not in FRAMES \
                    and t.kind not in REQUESTS:
                errs.append(f"{self.name}: transition {t.src}->"
                            f"{t.dst} rides undeclared kind "
                            f"{t.kind!r}")
        return errs

    def kinds(self) -> FrozenSet[str]:
        return frozenset(t.kind for t in self.transitions if t.kind)

    def successors(self, state: str) -> List[Transition]:
        return [t for t in self.transitions if t.src == state]


def _terminal_closes(states) -> Tuple[Transition, ...]:
    """Every live state reaches ``closed`` on every terminal kind --
    terminals may arrive at any point in the stream (drain bounces,
    router expiry, failover rejections)."""
    return tuple(Transition(s, "closed", kind=k)
                 for s in states for k in TERMINAL_KINDS)


#: what one client observes for one rid, submit to terminal
CLIENT_REQUEST = StateMachine(
    name="client-request",
    initial="submitted",
    states=("submitted", "accepted", "streaming", "closed"),
    transitions=(
        Transition("submitted", "accepted", kind=ACCEPTED),
        Transition("submitted", "submitted", kind=WRONG_OWNER,
                   guard="resubmit to the named ring owner "
                         "(bounded by max_bounces)"),
        Transition("submitted", "submitted", label="resubmit",
                   guard="target shard left the ring OR its fencing "
                         "epoch bumped (PR 16)"),
        Transition("accepted", "accepted", kind=ACCEPTED,
                   guard="hedge twin / failover re-attach duplicate"),
        Transition("accepted", "streaming", kind=STARTED),
        Transition("streaming", "streaming", kind=TOKENS),
        Transition("streaming", "accepted", kind=RETRYING,
                   guard="failover: reset token accumulation; a new "
                         "started re-opens the stream"),
    ) + _terminal_closes(("submitted", "accepted", "streaming")),
    doc="Consumed by RolloutClient / ShardedRolloutClient; exactly "
        "one transition into `closed` per rid (exactly-once "
        "terminal).")

#: one _RouterRequest inside a FleetRouter / ShardedRouter shard
ROUTER_REQUEST = StateMachine(
    name="router-request",
    initial="pending",
    states=("pending", "dispatched", "accepted", "streaming",
            "finished"),
    transitions=(
        Transition("pending", "dispatched", label="dispatch",
                   guard="a healthy replica exists (least-loaded, "
                         "prefix-affinity preferred)"),
        Transition("dispatched", "accepted", kind=ACCEPTED),
        Transition("dispatched", "pending", label="fail_assignment",
                   guard="dispatch timeout / replica lost or "
                         "re-registered / retired"),
        Transition("accepted", "streaming", kind=STARTED),
        Transition("accepted", "pending", kind=REJECTED,
                   guard="transient reason (backpressure, draining, "
                         "weights_behind): shop to another replica"),
        Transition("accepted", "pending", kind=DRAINING,
                   guard="replica drain bounce: shop to a survivor"),
        Transition("streaming", "streaming", kind=TOKENS),
        Transition("streaming", "pending", label="fail_assignment",
                   guard="owner lost mid-stream; emits `retrying` to "
                         "the client"),
        Transition("pending", "finished", kind=REJECTED,
                   guard="no_healthy_replica past pending_timeout, "
                         "or deterministic reject forwarded"),
    ) + tuple(Transition(s, "finished", kind=k)
              for s in ("pending", "dispatched", "accepted",
                        "streaming")
              for k in TERMINAL_KINDS)
    + (Transition("finished", "finished", label="dedupe",
                  guard="late twin terminals count as duplicates "
                        "against _done, never delivered"),),
    doc="_finish is the ONLY path into `finished` and runs at most "
        "once per rid (at-most-once delivery); every other retire "
        "path is a fence flush carrying its lint disable.")

#: one ShardedRouter incarnation, register to retire/supersede
SHARD_LIFECYCLE = StateMachine(
    name="shard-lifecycle",
    initial="active",
    states=("active", "fenced", "superseded", "retired"),
    transitions=(
        Transition("active", "fenced", label="lease_lost",
                   guard="renew_router raised LeaseLostError, or a "
                         "chaos partition let the lease decay"),
        Transition("fenced", "active", label="re_register",
                   guard="new fencing epoch; journal sweep re-adopts "
                         "rids no survivor claimed"),
        Transition("active", "superseded", label="superseded",
                   guard="a HIGHER epoch registered under our own "
                         "name: we are the zombie, quiet forever"),
        Transition("fenced", "superseded", label="superseded"),
        Transition("active", "retired", label="drain",
                   guard="planned departure: leftovers expire with "
                         "reason=router_drain, lease released"),
    ),
    doc="A fenced shard sends NOTHING (fence flush is terminal-less "
        "by design); only `active` dispatches or delivers.")

#: one HTTP request through the gateway front door
#: (serving/gateway.py): the admission ladder either sheds it with an
#: HTTP error BEFORE dispatch (that reply is its exactly-once
#: terminal -- the router never sees the rid) or maps it onto the
#: client-request machine via a RolloutClient submit.
GATEWAY_REQUEST = StateMachine(
    name="gateway-request",
    initial="received",
    states=("received", "dispatched", "streaming", "closed"),
    transitions=(
        Transition("received", "closed", label="shed",
                   guard="admission shed before dispatch (quota / "
                         "deadline_unmeetable / brownout / "
                         "backpressure): the 4xx/5xx reply with "
                         "Retry-After is the one terminal"),
        Transition("received", "dispatched", label="dispatch",
                   guard="admitted: submitted to the router under "
                         "its SLO class's queue priority"),
        Transition("dispatched", "dispatched", kind=ACCEPTED),
        Transition("dispatched", "streaming", kind=STARTED),
        Transition("streaming", "streaming", kind=TOKENS),
        Transition("streaming", "dispatched", kind=RETRYING,
                   guard="router failover restarted the token "
                         "stream; the SSE consumer resets its "
                         "accumulation"),
    ) + tuple(Transition(s, "closed", kind=k)
              for s in ("dispatched", "streaming")
              for k in TERMINAL_KINDS),
    doc="Consumed by GatewayServer: exactly one terminal per HTTP "
        "request -- either the shed reply or the relayed wire "
        "terminal, never both.")

MACHINES: Tuple[StateMachine, ...] = (CLIENT_REQUEST, ROUTER_REQUEST,
                                      SHARD_LIFECYCLE, GATEWAY_REQUEST)


def machine(name: str) -> Optional[StateMachine]:
    for m in MACHINES:
        if m.name == name:
            return m
    return None


def declared_fsm_kinds() -> FrozenSet[str]:
    """Every wire kind some declared state machine rides on."""
    out: FrozenSet[str] = frozenset()
    for m in MACHINES:
        out |= m.kinds()
    return out


# ----------------------------------------------------------------------
# Gateway surface (serving/gateway.py): HTTP mapping of the wire
# ----------------------------------------------------------------------
#: SLO class names accepted in the gateway's ``slo`` request field,
#: mapped onto the PR 2 admission-queue priority ints
#: (``serving/request_queue.py`` Priority: INTERACTIVE=0, BATCH=1).
#: ROLLOUT (2) is trainer-internal producer traffic and is NOT
#: reachable through the front door.
GATEWAY_SLO_INTERACTIVE = "interactive"
GATEWAY_SLO_BATCH = "batch"
GATEWAY_SLO_CLASSES: Dict[str, int] = {
    GATEWAY_SLO_INTERACTIVE: 0,
    GATEWAY_SLO_BATCH: 1,
}

#: terminal kind -> HTTP status of the gateway's reply (the
#: non-streaming path's status line; the SSE path has already sent
#: 200 and carries the terminal as its last event). 499 is the
#: client-closed-request convention; 504 marks a deadline that passed
#: after admission.
GATEWAY_HTTP_STATUS: Dict[str, int] = {
    DONE: 200,
    REJECTED: 429,
    STALE: 409,
    EXPIRED: 504,
    CANCELLED: 499,
    DRAINING: 503,
}

#: reason-level overrides of the REJECTED default: client errors are
#: 400 (retrying verbatim cannot help), capacity/lifecycle refusals
#: are 503; everything else keeps 429 + Retry-After (pace yourself).
GATEWAY_REJECT_STATUS: Dict[str, int] = {
    REASON_PROMPT_TOO_LONG: 400,
    REASON_DRAINING: 503,
    REASON_NO_HEALTHY_REPLICA: 503,
    REASON_RING_UNSTABLE: 503,
}

#: statuses whose reply must carry a ``Retry-After`` header
GATEWAY_RETRYABLE_STATUS = (429, 503)

assert set(GATEWAY_HTTP_STATUS) == set(TERMINAL_KINDS)


def gateway_status(kind: str, reason: Optional[str] = None) -> int:
    """HTTP status for one terminal ``(kind, reason)`` pair."""
    if kind == REJECTED and reason in GATEWAY_REJECT_STATUS:
        return GATEWAY_REJECT_STATUS[reason]
    return GATEWAY_HTTP_STATUS.get(kind, 200)
