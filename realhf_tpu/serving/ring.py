"""Consistent-hash ring: rid ownership across router shards.

The sharded router plane (docs/serving.md "Sharded router plane")
assigns every request id to exactly one ``RouterWorker`` shard by
consistent hashing over the set of live router names published in the
:class:`~realhf_tpu.serving.fleet.FleetRegistry`. Everything here is a
PURE function of ``(rid, sorted router names)``:

- every participant (routers, clients, drills) computes the same owner
  from the same registry snapshot, with no coordination round;
- when a router dies, only the hash ranges it owned re-home -- rids
  owned by survivors never move (the classic consistent-hashing
  minimal-disruption property, asserted by a property test in
  ``tests/serving/test_ring.py``);
- re-homing is deterministic: survivors independently agree on who
  adopts each orphaned rid.

Hashing uses sha1, never Python's ``hash()``: ownership must be stable
across processes and interpreter restarts (PYTHONHASHSEED).
"""

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

#: virtual nodes per router: smooths the range split so N routers own
#: ~1/N of rid space each (stddev shrinks with sqrt of vnodes)
DEFAULT_VNODES = 64


def _point(key: str) -> int:
    """Stable 64-bit ring position for a key."""
    return int.from_bytes(
        hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")


def ring_points(names: Sequence[str],
                n_vnodes: int = DEFAULT_VNODES
                ) -> List[Tuple[int, str]]:
    """Sorted ``(point, router_name)`` vnode list for a router set."""
    pts: List[Tuple[int, str]] = []
    for name in sorted(set(names)):
        for v in range(n_vnodes):
            pts.append((_point(f"{name}#{v}"), name))
    pts.sort()
    return pts


class Ring:
    """Immutable ownership view over one registry snapshot."""

    def __init__(self, names: Sequence[str],
                 n_vnodes: int = DEFAULT_VNODES):
        self.names: Tuple[str, ...] = tuple(sorted(set(names)))
        self.n_vnodes = n_vnodes
        self._points = ring_points(self.names, n_vnodes)
        self._keys = [p for p, _ in self._points]

    def __bool__(self) -> bool:
        return bool(self.names)

    def __eq__(self, other) -> bool:
        return isinstance(other, Ring) and self.names == other.names \
            and self.n_vnodes == other.n_vnodes

    def __hash__(self):
        return hash((self.names, self.n_vnodes))

    def owner_of(self, rid: str) -> Optional[str]:
        """The router owning ``rid`` (None on an empty ring): first
        vnode clockwise from the rid's hash point, wrapping at 0."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._keys, _point(rid))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def partition(self, rids: Sequence[str]) -> Dict[str, List[str]]:
        """Group rids by owner (owners with no rids are omitted)."""
        out: Dict[str, List[str]] = {}
        for rid in rids:
            owner = self.owner_of(rid)
            if owner is not None:
                out.setdefault(owner, []).append(rid)
        return out


def rehomed(before: Sequence[str], after: Sequence[str],
            rids: Sequence[str],
            n_vnodes: int = DEFAULT_VNODES) -> Dict[str, str]:
    """``{rid: new_owner}`` for every rid whose owner changed between
    the two router sets -- the deterministic re-home plan survivors
    agree on after a membership change."""
    b, a = Ring(before, n_vnodes), Ring(after, n_vnodes)
    out: Dict[str, str] = {}
    for rid in rids:
        ob, oa = b.owner_of(rid), a.owner_of(rid)
        if oa is not None and ob != oa:
            out[rid] = oa
    return out
