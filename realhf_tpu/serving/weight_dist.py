"""Chunked, deduplicated, tree-fanned weight distribution.

Replaces ``WeightSync``'s full-copy unicast push path between a
trainer and N serving replicas (docs/serving.md "Chunked weight
distribution"):

- **Chunking**: the param tree is flattened to ``"/"``-joined leaf
  paths and greedily packed into byte-bounded chunks of whole leaves.
  Chunk identity (``cid``) is a pure function of the leaf paths it
  covers, so the same tree always chunks the same way.
- **Dedup**: each chunk carries a content digest of its RAW leaf
  bytes. The distributor remembers, per receiver, the digest last
  acknowledged for every cid and skips chunks the receiver already
  holds -- a no-op re-push transfers (almost) nothing, and a
  fine-tuning step that only touched some layers transfers only
  those chunks.
- **Encoding**: chunks may be int8-encoded on the wire (per-row
  symmetric quantization, reusing the paged-KV helpers from
  ``engine/kv_pool.py``); digests are computed pre-encoding so dedup
  is encoding-agnostic.
- **Relay tree**: receivers are arranged in a deterministic
  ``fanout``-ary heap-shaped tree derived from the registry's sorted
  receiver names. Payloads hop root -> relay -> subtree, so a full
  update reaches N replicas in O(log N) pipelined hops instead of N
  serialized unicasts; :meth:`PushReport.modeled_latency` converts
  the measured per-edge bytes into the virtual-clock completion time
  under a link-speed model (``scripts/bench_serving.py
  --weight-dist`` reports both shapes). A relay node that fails
  mid-push is routed around: its orphaned subtree is re-parented to
  the root and pushed directly.
- **Resync**: a receiver that lost state (restart, missing base)
  reports ``missing`` paths; the distributor forgets its dedup map
  and re-sends everything direct.

The receiving side (:class:`ChunkedWeightReceiver`) assembles leaves
and hands a complete tree to ``WeightSync.push(..., copy=False)`` --
decode always materializes fresh buffers, so ownership transfers
safely (see the ``owns_params`` contract there).
"""

import dataclasses
import hashlib
import time
from collections.abc import Mapping
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics
from realhf_tpu.serving.weight_sync import WeightSync

logger = logging.getLogger("serving.weight_dist", "system")

#: leaves smaller than this stay raw under int8 encoding: biases and
#: norm scales are tiny, precision-sensitive, and not worth the 4x
INT8_MIN_LEAF_ELEMS = 1024


# -- param tree <-> flat paths -----------------------------------------
def flatten_params(params) -> Dict[str, np.ndarray]:
    """Flatten a nested-Mapping param tree to ``{"a/b/c": leaf}``.

    Only nested Mappings (dicts / FrozenDicts) are supported -- the
    restriction is what lets a receiver rebuild the tree from paths
    alone, with no pickled treedef on the wire. Keys must not contain
    ``"/"``."""
    flat: Dict[str, np.ndarray] = {}

    def walk(node, prefix: str):
        if isinstance(node, Mapping):
            for k in sorted(node):
                if "/" in str(k):
                    raise ValueError(
                        f"flatten_params: key {k!r} contains '/' "
                        "(reserved as the path separator).")
                walk(node[k], f"{prefix}/{k}" if prefix else str(k))
            return
        if prefix == "":
            raise TypeError("flatten_params: root must be a Mapping.")
        flat[prefix] = np.asarray(node)

    walk(params, "")
    return flat


def unflatten_params(flat: Mapping) -> dict:
    """Inverse of :func:`flatten_params`."""
    root: dict = {}
    for path, leaf in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


# -- chunking ----------------------------------------------------------
def _leaf_nbytes(x: np.ndarray) -> int:
    return int(np.asarray(x).nbytes)


def chunk_paths(flat: Mapping, max_chunk_bytes: int
                ) -> List[Tuple[str, ...]]:
    """Greedily pack sorted leaf paths into chunks of at most
    ``max_chunk_bytes`` of raw payload (a single oversized leaf gets
    a chunk of its own). Deterministic given the tree shape."""
    groups: List[Tuple[str, ...]] = []
    cur: List[str] = []
    cur_bytes = 0
    for path in sorted(flat):
        nb = _leaf_nbytes(flat[path])
        if cur and cur_bytes + nb > max_chunk_bytes:
            groups.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(path)
        cur_bytes += nb
    if cur:
        groups.append(tuple(cur))
    return groups


def chunk_id(paths: Sequence[str]) -> str:
    """Stable chunk identity: a function of the leaf paths only (NOT
    their contents -- contents live in the digest)."""
    h = hashlib.sha1()
    for p in paths:
        h.update(p.encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


def chunk_digest(paths: Sequence[str], flat: Mapping) -> str:
    """Content digest over the RAW (pre-encoding) leaf bytes, shapes,
    and dtypes: dedup compares digests, so it is encoding-agnostic
    and survives a receiver holding an int8-decoded copy."""
    h = hashlib.sha1()
    for p in paths:
        leaf = np.ascontiguousarray(flat[p])
        h.update(p.encode("utf-8"))
        h.update(str(leaf.dtype).encode())
        h.update(str(leaf.shape).encode())
        h.update(leaf.tobytes())
    return h.hexdigest()


# -- per-leaf wire encoding --------------------------------------------
def _encode_leaf(leaf: np.ndarray, encoding: str) -> dict:
    leaf = np.ascontiguousarray(leaf)
    if (encoding == "int8" and leaf.dtype.kind == "f"
            and leaf.ndim >= 1 and leaf.size >= INT8_MIN_LEAF_ELEMS
            and leaf.shape[-1] > 1):
        # reuse the paged-KV per-row symmetric int8 helpers (PR 14):
        # rows are the leading axes, quantized along the last
        from realhf_tpu.engine.kv_pool import _quantize_rows
        import jax.numpy as jnp
        q, scale = _quantize_rows(jnp.asarray(leaf))
        return dict(enc="int8", dtype=str(leaf.dtype),
                    shape=leaf.shape,
                    q=np.asarray(q), scale=np.asarray(scale))
    return dict(enc="raw", dtype=str(leaf.dtype), shape=leaf.shape,
                data=leaf)


def _decode_leaf(enc: dict) -> np.ndarray:
    if enc["enc"] == "raw":
        # copy even when the dtype already matches: an in-process
        # transport hands over the SENDER'S array object, and the
        # receiver installs via WeightSync.push(copy=False) -- without
        # a copy here the trainer's next in-place update would corrupt
        # the installed weights (a wire transport copies incidentally;
        # the owns_params contract must not depend on the transport)
        return np.array(enc["data"], dtype=np.dtype(enc["dtype"]),
                        copy=True)
    if enc["enc"] == "int8":
        q = np.asarray(enc["q"], np.float32)
        scale = np.asarray(enc["scale"], np.float32)[..., None]
        return (q * scale).astype(np.dtype(enc["dtype"])).reshape(
            enc["shape"])
    raise ValueError(f"Unknown leaf encoding {enc['enc']!r}.")


def _encoded_nbytes(enc: dict) -> int:
    if enc["enc"] == "raw":
        return _leaf_nbytes(enc["data"])
    return _leaf_nbytes(enc["q"]) + _leaf_nbytes(enc["scale"])


@dataclasses.dataclass
class Chunk:
    cid: str
    digest: str
    paths: Tuple[str, ...]
    leaves: Dict[str, dict]   # path -> encoded leaf
    nbytes: int               # wire bytes (post-encoding)


def encode_chunk(paths: Sequence[str], flat: Mapping,
                 encoding: str = "raw") -> Chunk:
    leaves = {p: _encode_leaf(flat[p], encoding) for p in paths}
    return Chunk(cid=chunk_id(paths),
                 digest=chunk_digest(paths, flat),
                 paths=tuple(paths), leaves=leaves,
                 nbytes=sum(_encoded_nbytes(e) for e in leaves.values()))


# -- relay tree --------------------------------------------------------
def relay_tree(root: str, receivers: Sequence[str],
               fanout: int = 2) -> List[Tuple[str, str]]:
    """Deterministic ``(sender, receiver)`` edges of a heap-shaped
    ``fanout``-ary relay tree over the SORTED receiver names: position
    ``i``'s children are positions ``fanout*i+1 .. fanout*i+fanout``,
    with the root feeding positions ``0 .. fanout-1``. ``fanout <= 0``
    degenerates to unicast (root sends to everyone). Edges come out in
    BFS send order, which is also the pipelined send schedule
    :meth:`PushReport.modeled_latency` prices."""
    names = sorted(receivers)
    if fanout <= 0:
        return [(root, r) for r in names]
    edges: List[Tuple[str, str]] = []
    for i, name in enumerate(names):
        if i < fanout:
            edges.append((root, name))
        else:
            edges.append((names[(i - fanout) // fanout], name))
    # BFS order == index order for the heap layout
    return edges


@dataclasses.dataclass
class PushReport:
    """What one :meth:`WeightDistributor.push` actually moved."""
    version: int
    root: str
    chunks_total: int
    #: per-edge ``(sender, receiver, wire_bytes, n_chunks)`` in send
    #: order; dedup already applied, so bytes are what really moved
    edges: List[Tuple[str, str, int, int]]
    chunks_sent: int = 0
    dedup_hits: int = 0
    bytes_sent: int = 0
    relay_hops: int = 0          # edges whose sender is not the root
    fallback_directs: int = 0    # edges re-parented after relay death
    failed: List[str] = dataclasses.field(default_factory=list)
    resyncs: List[str] = dataclasses.field(default_factory=list)
    wall_secs: float = 0.0

    def dedup_ratio(self) -> float:
        """addressed chunks / transferred chunks (>1 once dedup ever
        skips anything; inf for a fully deduplicated no-op re-push)."""
        addressed = self.chunks_sent + self.dedup_hits
        if self.chunks_sent == 0:
            return float("inf") if addressed else 1.0
        return addressed / self.chunks_sent

    def modeled_latency(self, bytes_per_sec: float = 1e9,
                        per_send_overhead: float = 1e-3) -> float:
        """Virtual-clock completion time of this push's send schedule
        under a simple link model: each node owns one outgoing link
        and serializes its sends (in edge order); a receiver can start
        relaying only after its own payload fully arrived. Computed
        from the MEASURED post-dedup per-edge bytes, this is what
        makes the tree-vs-unicast comparison honest on a single
        machine: unicast costs ``O(N)`` serialized sends at the root,
        the relay tree pipelines to ``O(log N)`` depth."""
        ready: Dict[str, float] = {self.root: 0.0}
        link_free: Dict[str, float] = {}
        done = 0.0
        for sender, receiver, nbytes, _nc in self.edges:
            start = max(ready.get(sender, 0.0),
                        link_free.get(sender, 0.0))
            finish = start + per_send_overhead + nbytes / bytes_per_sec
            link_free[sender] = finish
            ready[receiver] = max(ready.get(receiver, 0.0), finish)
            done = max(done, finish)
        return done


class WeightDistributor:
    """Sender side: chunk, dedup, and fan a weight push out over the
    relay tree (module docstring).

    ``transport(sender, receiver, message) -> reply`` delivers one
    receiver's payload and returns its acknowledgement (``{"ok": True}``
    or ``{"ok": False, "missing": [...]}``); raising marks the
    receiver failed and re-parents its subtree to the root. The
    ``sender`` attribution is the relay schedule -- in-process
    transports (drills, benches) execute it literally, while the
    zmq/worker transport issues the sends in the same pipelined order.
    """

    def __init__(self, root: str = "trainer", *,
                 fanout: int = 2,
                 max_chunk_bytes: int = 4 << 20,
                 encoding: str = "raw",
                 clock: Callable[[], float] = time.perf_counter):
        if encoding not in ("raw", "int8"):
            raise ValueError(f"Unknown encoding {encoding!r} "
                             "(expected 'raw' or 'int8').")
        self.root = root
        self.fanout = fanout
        self.max_chunk_bytes = max_chunk_bytes
        self.encoding = encoding
        self._clock = clock
        #: receiver -> {cid: digest} last acknowledged
        self._seen: Dict[str, Dict[str, str]] = {}

    def forget(self, receiver: str):
        """Drop the dedup map for a receiver (restart / resync): the
        next push sends it everything."""
        self._seen.pop(receiver, None)

    def push(self, params, version: int, receivers: Sequence[str],
             transport: Callable[[str, str, dict], Optional[dict]],
             ) -> PushReport:
        t0 = self._clock()
        flat = flatten_params(params)
        chunks = [encode_chunk(paths, flat, self.encoding)
                  for paths in chunk_paths(flat, self.max_chunk_bytes)]
        manifest = [(c.cid, c.digest) for c in chunks]
        edges = relay_tree(self.root, receivers, self.fanout)
        report = PushReport(version=version, root=self.root,
                            chunks_total=len(chunks), edges=[])
        failed: set = set()
        for sender, receiver in edges:
            if sender in failed:
                sender = self.root  # re-parent the orphaned subtree
                report.fallback_directs += 1
                metrics.inc("weight_push_fallback_directs_total")
            seen = self._seen.setdefault(receiver, {})
            need = [c for c in chunks if seen.get(c.cid) != c.digest]
            hits = len(chunks) - len(need)
            nbytes = sum(c.nbytes for c in need)
            message = dict(version=version, manifest=manifest,
                           chunks=need, sender=sender)
            try:
                reply = transport(sender, receiver, message) or {}
            except Exception as e:  # noqa: BLE001 - a dead relay is
                # routed around, never fatal to the push
                logger.warning("Weight push: receiver %s failed (%s);"
                               " subtree falls back to direct push.",
                               receiver, e)
                failed.add(receiver)
                report.failed.append(receiver)
                self.forget(receiver)
                continue
            if not reply.get("ok", True):
                # receiver lost state (restart / missing base): wipe
                # its dedup map and re-send everything direct
                self.forget(receiver)
                seen = self._seen.setdefault(receiver, {})
                need, hits = list(chunks), 0
                nbytes = sum(c.nbytes for c in need)
                message = dict(version=version, manifest=manifest,
                               chunks=need, sender=self.root)
                report.resyncs.append(receiver)
                metrics.inc("weight_push_resyncs_total")
                try:
                    reply = transport(self.root, receiver, message) \
                        or {}
                except Exception:  # noqa: BLE001
                    failed.add(receiver)
                    report.failed.append(receiver)
                    self.forget(receiver)
                    continue
                if not reply.get("ok", True):
                    failed.add(receiver)
                    report.failed.append(receiver)
                    self.forget(receiver)
                    continue
            for c in need:
                seen[c.cid] = c.digest
            report.edges.append((sender, receiver, nbytes, len(need)))
            report.chunks_sent += len(need)
            report.dedup_hits += hits
            report.bytes_sent += nbytes
            if sender != self.root:
                report.relay_hops += 1
        report.wall_secs = max(0.0, self._clock() - t0)
        metrics.inc("weight_push_chunks_total",
                    amount=report.chunks_sent)
        metrics.inc("weight_push_dedup_hits_total",
                    amount=report.dedup_hits)
        metrics.inc("weight_push_relay_hops_total",
                    amount=report.relay_hops)
        metrics.inc("weight_push_bytes_total",
                    amount=report.bytes_sent)
        metrics.observe_hist("weight_swap_latency_seconds",
                             report.wall_secs)
        return report


class ChunkedWeightReceiver:
    """Receiver side: accumulate decoded chunks and install complete
    trees into a :class:`WeightSync` mailbox.

    Holds the last-decoded leaf set between pushes, so a dedup'd push
    (chunks skipped because this receiver already acknowledged them)
    still installs a FULL tree. When the manifest references a chunk
    this receiver never held (restart, eviction), :meth:`apply`
    answers ``ok=False`` with the missing cids and the distributor
    resyncs it."""

    def __init__(self, weight_sync: WeightSync):
        self.weight_sync = weight_sync
        self._leaves: Dict[str, np.ndarray] = {}
        #: cid -> (digest, paths) for everything currently held
        self._held: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        self.installs = 0

    def apply(self, message: dict) -> dict:
        version = int(message["version"])
        for c in message.get("chunks", []):
            self._leaves.update(
                {p: _decode_leaf(enc) for p, enc in c.leaves.items()})
            self._held[c.cid] = (c.digest, c.paths)
        missing = [cid for cid, digest in message["manifest"]
                   if self._held.get(cid, ("",))[0] != digest]
        if missing:
            return dict(ok=False, missing=missing)
        want = {cid for cid, _ in message["manifest"]}
        live_paths = set()
        for cid in want:
            live_paths.update(self._held[cid][1])
        # drop leaves/chunks the new manifest no longer references
        # (a resharded tree must not resurrect stale leaves)
        for cid in [c for c in self._held if c not in want]:
            del self._held[cid]
        for p in [p for p in self._leaves if p not in live_paths]:
            del self._leaves[p]
        params = unflatten_params(self._leaves)
        try:
            # decode materialized fresh buffers: ownership transfers
            self.weight_sync.push(params, version, copy=False)
            self.installs += 1
        except ValueError:
            # stale/duplicate version (reordered relay delivery): the
            # newer weights already won; acknowledge and move on
            logger.info("Chunked receiver: dropping stale weight "
                        "push v%d (installed v%d).", version,
                        self.weight_sync.version)
        return dict(ok=True, version=version)
