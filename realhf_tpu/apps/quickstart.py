"""Quickstart CLI: ``python -m realhf_tpu.apps.quickstart <algo> a.b=c ...``

Parity with reference ``realhf/apps/quickstart.py:22``: one subcommand
per registered experiment, configured by dotted key=value overrides
(the reference's Hydra override syntax), e.g.::

    python -m realhf_tpu.apps.quickstart sft \
        experiment_name=my-sft trial_name=t0 \
        model.path=/path/to/llama dataset.path=data.jsonl \
        dataset.train_bs_n_seqs=128 model.optimizer.lr=1e-5 \
        model.parallel.data_parallel_size=4 \
        model.parallel.tensor_parallel_size=2
"""

import argparse
import sys

from realhf_tpu.base import logging

logger = logging.getLogger("quickstart")


def parse_overrides(tokens):
    out = {}
    for t in tokens:
        if "=" not in t:
            raise ValueError(f"Override `{t}` is not of the form key=value.")
        k, v = t.split("=", 1)
        out[k] = v
    return out


def main(argv=None):
    import realhf_tpu.experiments as experiments
    from realhf_tpu.base.importing import import_usercode

    import_usercode()  # REALHF_TPU_PACKAGE_PATH custom registrations

    argv = argv if argv is not None else sys.argv[1:]
    parser = argparse.ArgumentParser("realhf_tpu quickstart")
    parser.add_argument(
        "experiment", choices=sorted(experiments.ALL_EXPERIMENT_CLASSES))
    parser.add_argument("overrides", nargs="*",
                        help="dotted key=value config overrides")
    args = parser.parse_args(argv)

    from realhf_tpu.experiments.common import apply_overrides
    cfg = experiments.ALL_EXPERIMENT_CLASSES[args.experiment]()
    apply_overrides(cfg, parse_overrides(args.overrides))

    logger.info("Running experiment %s: %s", args.experiment, cfg)
    spec = cfg.build()
    spec.n_model_workers = cfg.n_model_workers
    spec.worker_assignment = cfg.parsed_worker_assignment()
    if cfg.allocation_mode in ("heuristic", "search", "search_profiled"):
        # default_devices respects REALHF_TPU_BACKEND and never probes
        # the default (TPU) backend from the launcher process -- TPU
        # init here could block and would hold the chip the spawned
        # workers need.
        if cfg.n_devices is not None:
            n = cfg.n_devices
        elif cfg.mode == "distributed":
            raise ValueError(
                f"allocation_mode={cfg.allocation_mode} with "
                "mode=distributed requires n_devices=<per-worker chip "
                "count> (the launcher must not initialize the workers' "
                "backend).")
        else:
            from realhf_tpu.parallel.mesh import default_devices
            n = len(default_devices())
        if cfg.allocation_mode == "heuristic":
            from realhf_tpu.experiments.heuristic import (
                apply_heuristic_allocations,
            )
            apply_heuristic_allocations(spec, n)
        else:
            # C++ MCMC search over (device slice x layout) assignments
            from realhf_tpu.search import apply_searched_allocations
            cost_model = None
            if cfg.allocation_mode == "search_profiled":
                # measured calibration (reference estimate.py:323):
                # runs timed probes on THIS process's default backend,
                # so it is inline/local-mode only -- in distributed
                # mode the launcher must not claim the workers' chips.
                if cfg.mode == "distributed":
                    raise ValueError(
                        "allocation_mode=search_profiled probes the "
                        "accelerator from the launcher and cannot be "
                        "used with mode=distributed; run the profile "
                        "inline or use allocation_mode=search.")
                from realhf_tpu.search.engine import calibrate_cost_model
                cost_model = calibrate_cost_model(spec)
            res = apply_searched_allocations(spec, n,
                                             cost_model=cost_model)
            logger.info("Search: best simulated step %.3fs", res.time)
            if (cfg.mode == "distributed" and not spec.worker_assignment
                    and cfg.n_model_workers == 1
                    and res.worker_assignment):
                # realize the simulator's slice concurrency: disjoint
                # role groups become separate worker processes
                spec.worker_assignment = res.worker_assignment
                spec.n_model_workers = (
                    max(res.worker_assignment.values()) + 1)
                logger.info(
                    "Search-derived worker assignment: %s "
                    "(%d model workers)", spec.worker_assignment,
                    spec.n_model_workers)
        logger.info("%s allocations on %d devices: %s",
                    cfg.allocation_mode, n,
                    {k: str(v) for k, v in spec.allocations.items()})

    if getattr(spec, "serving", None) is not None:
        # rollout/serving deployment: no master/dataflow, just
        # GenServerWorker processes answering RolloutClient traffic
        # (docs/serving.md)
        from realhf_tpu.apps.main import run_serve
        stats = run_serve(
            spec, duration=getattr(cfg, "serve_duration_secs", None))
    elif cfg.mode == "distributed":
        # master + model-worker processes, concurrent MFCs on disjoint
        # meshes (reference multi-worker runtime)
        from realhf_tpu.apps.main import main_start
        stats = main_start(spec, recover_mode=cfg.recover_mode,
                           recover_retries=cfg.recover_retries)
    else:
        from realhf_tpu.system.inline import InlineRunner
        runner = InlineRunner(spec, recover_mode=cfg.recover_mode)
        stats = runner.run()
    logger.info("Experiment complete. Last step stats: %s", stats)
    _report_observability_artifacts()
    return stats


def _report_observability_artifacts():
    """Point the operator at what REALHF_TPU_TRACE=1 produced: the
    merged Chrome trace (written by the inline runner or the launcher
    teardown, docs/observability.md) and the per-process metrics
    JSONL directory."""
    import os

    from realhf_tpu.obs import tracing
    if not tracing.trace_env_enabled():
        return
    d = tracing.trace_dir()
    merged = os.path.join(d, tracing.MERGED_TRACE_NAME)
    if os.path.exists(merged):
        logger.info("Trace timeline: %s (load in https://ui.perfetto.dev"
                    " or chrome://tracing).", merged)
        from realhf_tpu.obs import analyze
        summary = analyze.summarize_path(merged)
        if summary:
            logger.info("%s (full report: python "
                        "scripts/analyze_trace.py %s)", summary,
                        merged)
    elif os.path.isdir(d):
        logger.info("Per-process trace shards under %s (merge with "
                    "realhf_tpu.obs.tracing.merge_traces).", d)


if __name__ == "__main__":
    main()
