"""Quickstart CLI: ``python -m realhf_tpu.apps.quickstart <algo> a.b=c ...``

Parity with reference ``realhf/apps/quickstart.py:22``: one subcommand
per registered experiment, configured by dotted key=value overrides
(the reference's Hydra override syntax), e.g.::

    python -m realhf_tpu.apps.quickstart sft \
        experiment_name=my-sft trial_name=t0 \
        model.path=/path/to/llama dataset.path=data.jsonl \
        dataset.train_bs_n_seqs=128 model.optimizer.lr=1e-5 \
        model.parallel.data_parallel_size=4 \
        model.parallel.tensor_parallel_size=2
"""

import argparse
import sys

from realhf_tpu.base import logging

logger = logging.getLogger("quickstart")


def parse_overrides(tokens):
    out = {}
    for t in tokens:
        if "=" not in t:
            raise ValueError(f"Override `{t}` is not of the form key=value.")
        k, v = t.split("=", 1)
        out[k] = v
    return out


def main(argv=None):
    import realhf_tpu.experiments as experiments

    argv = argv if argv is not None else sys.argv[1:]
    parser = argparse.ArgumentParser("realhf_tpu quickstart")
    parser.add_argument(
        "experiment", choices=sorted(experiments.ALL_EXPERIMENT_CLASSES))
    parser.add_argument("overrides", nargs="*",
                        help="dotted key=value config overrides")
    args = parser.parse_args(argv)

    from realhf_tpu.experiments.common import apply_overrides
    cfg = experiments.ALL_EXPERIMENT_CLASSES[args.experiment]()
    apply_overrides(cfg, parse_overrides(args.overrides))

    logger.info("Running experiment %s: %s", args.experiment, cfg)
    spec = cfg.build()

    from realhf_tpu.system.inline import InlineRunner
    runner = InlineRunner(spec, recover_mode=getattr(cfg, "recover_mode",
                                                     "disabled"))
    stats = runner.run()
    logger.info("Experiment complete. Last step stats: %s", stats)
    return stats


if __name__ == "__main__":
    main()
