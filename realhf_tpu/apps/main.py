"""Distributed launcher: spawn, configure, supervise, and recover the
master + model-worker fleet.

Parity with reference ``realhf/apps/main.py`` (main_start:74,
main_stop:233, auto-recover recursion :205-230) and the controller
state machine (``system/controller.py:118``): the launcher process
doubles as the controller -- it submits worker processes through a
scheduler, pushes configs over the WorkerControlPanel, starts
everyone, watches the master's experiment status, and on worker
failure relaunches the whole trial with ``recover_count + 1`` (up to
``recover_retries``) in resume mode.
"""

import os
import pickle
import sys
import time
from typing import Dict, Optional

from realhf_tpu.api.experiment import ExperimentSpec, FaultToleranceConfig
from realhf_tpu.base import constants, logging, name_resolve, names
from realhf_tpu.obs import flight, tracing
from realhf_tpu.system.pod import PodController
from realhf_tpu.system.scheduler import (
    JobException,
    JobState,
    SchedulerClient,
    make_scheduler,
)
from realhf_tpu.system.watchdog import Watchdog
from realhf_tpu.system.worker_base import (
    HEARTBEAT_INTERVAL_ENV,
    WorkerControlPanel,
    WorkerServerStatus,
)

logger = logging.getLogger("main", "benchmark")


def _worker_cmd(worker_type: str, index: int, spec: ExperimentSpec):
    return [
        sys.executable, "-m", "realhf_tpu.apps.remote", "worker",
        "--worker_type", worker_type, "--index", str(index),
        "--experiment_name", spec.experiment_name,
        "--trial_name", spec.trial_name,
    ]


def _spec_path(spec: ExperimentSpec) -> str:
    d = constants.run_log_path()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "experiment_spec.pkl")


def run_trial(spec: ExperimentSpec, recover_mode: str = "disabled",
              env: Optional[Dict[str, str]] = None,
              timeout: float = 3600.0,
              sched: Optional[SchedulerClient] = None) -> Dict:
    """One trial attempt: spawn workers, run to completion, tear down.
    Raises JobException/TimeoutError on worker failure (the caller's
    recover loop relaunches).

    ``sched`` overrides the default local subprocess scheduler -- pass
    a ``MultiHostLocalScheduler`` (``system/pod.py``) to run the trial
    across emulated pod hosts: submission then goes through the
    :class:`PodController` with per-host env namespaces, the watchdog
    aggregates losses per host (HOST_LOST), and teardown writes the
    per-host Prometheus scrape targets + merged flight dumps."""
    bad = {r: spec.workers_of_role(r) for r in spec.worker_assignment
           if not all(0 <= w < spec.n_model_workers
                      for w in spec.workers_of_role(r))}
    if bad:
        raise ValueError(
            f"worker_assignment indices out of range for "
            f"n_model_workers={spec.n_model_workers}: {bad}")
    bad_alloc = {
        name: a.workers for name in spec.allocations
        if (a := spec.alloc_of(name)) is not None
        and a.workers is not None
        and not all(0 <= w < spec.n_model_workers for w in a.workers)}
    if bad_alloc:
        raise ValueError(
            f"MFCAllocation.workers indices out of range for "
            f"n_model_workers={spec.n_model_workers}: {bad_alloc}")
    mfc_names = {n.name for n in spec.mfcs}
    unknown = sorted(set(spec.allocations) - mfc_names)
    if unknown:
        # a misspelled key would otherwise be silently ignored and the
        # MFC would run on the role's primary layout (advisor r3)
        raise ValueError(
            f"allocations keys {unknown} name no MFC in the dataflow "
            f"graph (have: {sorted(mfc_names)})")
    constants.set_experiment_trial_names(spec.experiment_name,
                                         spec.trial_name)
    path = _spec_path(spec)
    with open(path, "wb") as f:
        pickle.dump(spec, f)

    # Cross-process rendezvous: the launcher and every worker share an
    # NFS name_resolve root (reference main.py name_resolve setup).
    record_root = os.path.join(constants.run_log_path(), "name_resolve")
    name_resolve.reconfigure("nfs", record_root=record_root)
    env = dict(env or {})
    env.setdefault("REALHF_TPU_NAME_RESOLVE_ROOT", record_root)
    env.setdefault("REALHF_TPU_ROOT", constants.ROOT_DIR)
    ft = getattr(spec, "ft", None) or FaultToleranceConfig()
    env.setdefault(HEARTBEAT_INTERVAL_ENV, str(ft.heartbeat_interval))

    worker_names = ([f"model_worker/{i}"
                     for i in range(spec.n_model_workers)]
                    + ["master_worker/0"])
    if sched is None:
        sched = make_scheduler("local")
    # pod supervision layer (system/pod.py): submission with
    # retry/backoff, bring-up deadline with host-attributed errors,
    # per-host obs artifacts at teardown. Over a plain local
    # scheduler it degrades to a single synthetic host.
    controller = PodController(sched)
    # Stale keys from a previous run of the same trial (worker
    # addresses, steps_per_epoch, experiment status) must not leak
    # into this one (reference main.py:138-147 clear_subtree).
    name_resolve.clear_subtree(
        names.trial_root(spec.experiment_name, spec.trial_name))
    status_key = names.experiment_status(spec.experiment_name,
                                         spec.trial_name)

    try:
        for i in range(spec.n_model_workers):
            controller.submit(f"model_worker/{i}",
                              _worker_cmd("model_worker", i, spec),
                              env=env)
        controller.submit("master_worker/0",
                          _worker_cmd("master_worker", 0, spec),
                          env=env)
        controller.wait_ready(spec.experiment_name, spec.trial_name,
                              worker_names, deadline=120)

        panel = WorkerControlPanel(spec.experiment_name, spec.trial_name)
        panel.connect(worker_names, timeout=120)
        # Master FIRST: model workers' configure blocks waiting for the
        # master's request-reply stream address in name_resolve.
        panel.group_request(
            "configure", worker_names=["master_worker/0"],
            kwargs=dict(config=dict(spec_path=path,
                                    recover_mode=recover_mode)))
        # One send-all-then-gather round: multihost configure is a
        # cross-worker barrier (jax.distributed world join), so the
        # requests must all be in flight before any reply is awaited.
        panel.group_request_varied(
            "configure",
            {f"model_worker/{i}": dict(config=dict(
                spec_path=path, worker_index=i,
                recover_mode=recover_mode))
             for i in range(spec.n_model_workers)},
            timeout=600)
        panel.group_request("start")
        logger.info("All %d workers started.", len(worker_names))
        # live scrape targets the moment the fleet is up: every worker
        # has published its telemetry endpoint by now, so Prometheus
        # can discover real per-worker ports for the run's whole life
        # (the teardown rewrite is only the postmortem fallback)
        sd_path = controller.write_scrape_targets(
            labels=dict(experiment=spec.experiment_name,
                        trial=spec.trial_name),
            experiment_name=spec.experiment_name,
            trial_name=spec.trial_name)
        if sd_path:
            logger.info("Prometheus scrape targets written: %s "
                        "(file_sd_configs).", sd_path)

        # watchdog over the whole fleet (master included): catches
        # hung-but-not-dead workers the scheduler still reports as
        # RUNNING (the master's own watchdog covers only the model
        # workers it talks to)
        watchdog = Watchdog(
            spec.experiment_name, spec.trial_name, worker_names,
            timeout=ft.heartbeat_timeout, grace=ft.startup_grace_secs,
            poll_interval=ft.watchdog_poll_secs,
            # host failure domains: with a host-aware scheduler a
            # whole-host kill is ONE HOST_LOST attribution here too
            host_of=getattr(sched, "host_of", None),
            host_window=getattr(ft, "host_lost_window_secs", None))
        deadline = time.monotonic() + timeout
        # elastic rejoin (ft.elastic_rejoin): once a PREEMPTED model
        # worker's process exits, resubmit it; the relaunched
        # incarnation reconfigures from the same spec and the master
        # re-expands degraded nodes back onto it (system/elastic.py)
        rejoining: Dict[str, float] = {}
        while True:
            try:
                status = name_resolve.get(status_key)
            except name_resolve.NameEntryNotFoundError:
                status = None
            if status == "done":
                break
            # failure detection: a dead/errored worker fails the trial
            # (reference scheduler poll -> JobException, main.py:195)
            for w in worker_names:
                info = sched.find(w)
                wstatus = panel.get_worker_status(w)
                elastic_mw = (ft.elastic_degrade
                              and w.startswith("model_worker/"))
                exited = info.state.value not in ("RUNNING", "PENDING")
                if wstatus == WorkerServerStatus.PREEMPTED or (
                        elastic_mw and info.state.value == "FAILED"):
                    # preempted (graceful) or silently dead under
                    # elastic degradation: the master has migrated or
                    # is migrating its MFCs; optionally bring a
                    # replacement up for re-expansion
                    if ft.elastic_rejoin and w not in rejoining \
                            and w.startswith("model_worker/") and exited:
                        logger.warning(
                            "Worker %s exited (%s); resubmitting a "
                            "replacement for elastic rejoin.", w,
                            wstatus.value if wstatus else info.state)
                        # the dead incarnation's command address is
                        # stale; drop it so connect() below waits for
                        # the replacement's registration (a graceful
                        # exit may have already removed its own key)
                        try:
                            name_resolve.delete(names.worker_key(
                                spec.experiment_name, spec.trial_name, w))
                        except name_resolve.NameEntryNotFoundError:
                            pass
                        sched.resubmit(w)
                        rejoining[w] = time.monotonic()
                    continue
                if info.state.value == "FAILED":
                    raise JobException(w, info.state)
                if wstatus == WorkerServerStatus.ERROR:
                    raise JobException(w, info.state)
            for w in list(rejoining):
                try:
                    panel.connect([w], timeout=0.2)
                except Exception:  # noqa: BLE001 - still booting
                    if time.monotonic() - rejoining[w] > 300:
                        raise JobException(w, JobState.LOST)
                    continue  # retry next tick
                idx = int(w.rsplit("/", 1)[1])
                panel.group_request_varied(
                    "configure",
                    {w: dict(config=dict(spec_path=path,
                                         worker_index=idx,
                                         recover_mode=recover_mode))},
                    timeout=600)
                panel.group_request("start", worker_names=[w])
                del rejoining[w]
                logger.info("Worker %s rejoined (reconfigured + "
                            "started).", w)
            watchdog.poll()
            lost = watchdog.lost_longer_than(ft.worker_lost_fatal_secs)
            # under elastic degradation the MASTER owns the fatal
            # policy for model workers (it knows what was migrated);
            # the launcher only fatals on a lost master
            if ft.elastic_degrade:
                lost = [w for w in lost
                        if not w.startswith("model_worker/")]
            if lost:
                raise JobException(lost[0], JobState.LOST)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"Trial did not complete within {timeout}s.")
            time.sleep(0.2)

        stats = panel.group_request("stats",
                                    worker_names=["master_worker/0"])
        try:
            panel.group_request("exit", timeout=60)
            sched.wait(timeout=60, check_status=False)
        except (TimeoutError, RuntimeError) as e:
            # a worker mid-rejoin (elastic) may miss the exit
            # broadcast on its stale socket; the trial IS complete --
            # stop_all's SIGTERM/SIGKILL escalation cleans it up
            logger.warning("Exit broadcast incomplete (%s); scheduler "
                           "stop_all cleans up.", e)
        return stats["master_worker/0"]
    finally:
        sched.stop_all()
        _teardown_obs(controller)


def _teardown_obs(controller: Optional[PodController] = None):
    """Teardown observability sweep (success or failure -- the
    artifacts of a crashed trial are the ones you want most): merge
    per-process traces into one Perfetto timeline, fold per-worker
    flight-recorder dumps into one incident record, and write the
    per-host Prometheus scrape-target file. Never raises."""
    _merge_run_traces()
    try:
        merged = flight.merge_dumps()
        if merged:
            logger.info("Flight dumps merged: %s.", merged)
    except Exception as e:  # noqa: BLE001 - teardown must not mask
        # the trial's real outcome
        logger.warning("Flight-dump merge failed: %s", e)
    if controller is not None:
        path = controller.write_scrape_targets()
        if path:
            logger.info("Prometheus scrape targets written: %s "
                        "(file_sd_configs).", path)


def _merge_run_traces():
    """With ``REALHF_TPU_TRACE=1`` every worker process streamed its
    spans to ``{run_log_path}/obs/trace/<worker>.trace.jsonl``; fold
    them into ONE Perfetto-loadable Chrome trace so a PPO step renders
    as a single timeline across the master, every model worker, and
    the serving fleet. Runs in the teardown path (success or failure:
    the trace of a crashed trial is the one you want most) and never
    raises."""
    if not tracing.trace_env_enabled():
        return
    try:
        merged = tracing.merge_traces()
    except Exception as e:  # noqa: BLE001 - teardown must not mask
        # the trial's real outcome
        logger.warning("Trace merge failed: %s", e)
        return
    if merged:
        logger.info("Chrome trace written: %s (open in Perfetto / "
                    "chrome://tracing).", merged)
        # the analytic companion to the Perfetto pointer: where the
        # step wall went, the bottleneck MFC, and who straggled
        from realhf_tpu.obs import analyze
        summary = analyze.summarize_path(merged)
        if summary:
            logger.info("%s (full report: python "
                        "scripts/analyze_trace.py %s)", summary,
                        merged)


class _ServeFleetActuator:
    """:class:`~realhf_tpu.system.autoscale.ReplicaActuator` over the
    launcher's PodController + WorkerControlPanel: the production
    spawn/retire path for GenServer replicas (docs/serving.md
    "Autoscaling").

    ``spawn`` submits the worker process (with the PodController's
    retry/backoff); bring-up completes asynchronously in
    :meth:`poll_bringup`, which configures + starts each replica the
    moment its control endpoint registers and hands it to
    ``on_started`` (watchdog + membership bookkeeping). ``retire``
    first calls ``on_retiring`` (the worker must leave the watchdog
    BEFORE its planned exit can look like a death), then commands
    ``exit`` -- the worker's exit hook runs the graceful drain
    (bounce queued, harvest in-flight, force-fence past the hard
    deadline, release the lease) and the process exits COMPLETED."""

    def __init__(self, controller: PodController, panel, sched,
                 spec: ExperimentSpec, spec_path: str,
                 env: Dict[str, str], *,
                 on_started, on_retiring, reap_grace: float = 10.0):
        self._controller = controller
        self._panel = panel
        self._sched = sched
        self._spec = spec
        self._spec_path = spec_path
        self._env = env
        self._on_started = on_started
        self._on_retiring = on_retiring
        self._reap_grace = reap_grace
        #: submitted but not yet configured+started
        self.pending: Dict[str, float] = {}

    def spawn(self, name: str):
        idx = int(name.rsplit("/", 1)[-1])
        self._controller.submit(
            name, _worker_cmd("gen_server", idx, self._spec),
            env=self._env)
        self.pending[name] = time.monotonic()

    def poll_bringup(self):
        """Configure + start every submitted replica whose control
        endpoint has appeared (non-blocking probe); a replica whose
        process already died is dropped (the controller's spawn
        deadline writes it off)."""
        for name in sorted(self.pending):
            if self._sched.find(name).state == JobState.FAILED:
                logger.error("Autoscale: spawned replica %s died "
                             "before registering.", name)
                del self.pending[name]
                continue
            try:
                self._panel.connect([name], timeout=0.2)
            except Exception:  # noqa: BLE001 - still booting
                continue
            idx = int(name.rsplit("/", 1)[-1])
            self._panel.group_request_varied(
                "configure",
                {name: dict(config=dict(spec_path=self._spec_path,
                                        server_index=idx))},
                timeout=600)
            self._panel.group_request("start", worker_names=[name])
            del self.pending[name]
            self._on_started(name)
            logger.info("Autoscale: replica %s configured and "
                        "started.", name)

    def retire(self, name: str):
        self._on_retiring(name)
        # "exit" replies immediately; the drain runs in the worker's
        # exit hook and the process exits COMPLETED when done
        self._panel.group_request("exit", worker_names=[name],
                                  timeout=60)

    def gone(self, name: str) -> bool:
        if name in self.pending:
            return False
        return self._sched.find(name).state not in (JobState.RUNNING,
                                                    JobState.PENDING)

    def reap(self, name: str):
        self.pending.pop(name, None)
        self._controller.stop(name, grace=self._reap_grace)


def run_serve(spec: ExperimentSpec,
              env: Optional[Dict[str, str]] = None,
              duration: Optional[float] = None,
              timeout: float = 86400.0) -> Dict:
    """Launch ``spec.serving.n_servers`` GenServerWorker processes
    (the async rollout & serving subsystem, docs/serving.md) and
    supervise them with the same heartbeat/watchdog plumbing as a
    training trial: a hung or dead server raises JobException naming
    the worker.

    Runs until ``duration`` elapses (None = until ``timeout`` or
    KeyboardInterrupt), then drains gracefully: workers bounce queued
    requests, finish in-flight sequences, and exit COMPLETED. Returns
    the per-server stats gathered just before shutdown."""
    sv = getattr(spec, "serving", None)
    if sv is None:
        raise ValueError(
            "run_serve needs ExperimentSpec.serving (build one with "
            "the `serve` experiment, experiments/serve_exp.py).")
    if getattr(sv, "autoscale", False) \
            and not getattr(sv, "fleet_router", False):
        raise ValueError(
            "ServingSpec.autoscale needs fleet_router=True: the "
            "router is both the autoscale signal source and how "
            "clients discover spawned replicas (docs/serving.md "
            "\"Autoscaling\").")
    constants.set_experiment_trial_names(spec.experiment_name,
                                         spec.trial_name)
    path = _spec_path(spec)
    with open(path, "wb") as f:
        pickle.dump(spec, f)
    record_root = os.path.join(constants.run_log_path(), "name_resolve")
    name_resolve.reconfigure("nfs", record_root=record_root)
    env = dict(env or {})
    env.setdefault("REALHF_TPU_NAME_RESOLVE_ROOT", record_root)
    env.setdefault("REALHF_TPU_ROOT", constants.ROOT_DIR)
    ft = getattr(spec, "ft", None) or FaultToleranceConfig()
    env.setdefault(HEARTBEAT_INTERVAL_ENV, str(ft.heartbeat_interval))

    gen_names = [f"gen_server/{i}" for i in range(sv.n_servers)]
    # fleet mode (docs/serving.md "Fleet, failover & circuit
    # breakers"): a RouterWorker fronts the replicas; clients talk to
    # it (server_name="router") and individual replica deaths are
    # tolerated -- the router fails their in-flight work over -- as
    # long as the router itself and at least one replica survive.
    fleet = bool(getattr(sv, "fleet_router", False))
    # sharded router plane (docs/serving.md "Sharded router plane"):
    # n_routers > 1 runs that many RouterWorker shards splitting rid
    # space by consistent hash; a single shard keeps the classic
    # singleton router (and its loss stays fatal)
    n_routers = max(1, int(getattr(sv, "n_routers", 1))) if fleet else 0
    router_names = [f"router/{i}" for i in range(n_routers)]
    # HTTP front door (docs/serving.md "Front door"): a GatewayWorker
    # exposing /v1/completions over SSE ahead of the router plane.
    # A singleton like the classic router: its loss is fatal.
    gateway_names = ["gateway/0"] if getattr(sv, "gateway", False) \
        else []
    worker_names = gen_names + router_names + gateway_names
    sched = make_scheduler("local")
    controller = PodController(sched)
    name_resolve.clear_subtree(
        names.trial_root(spec.experiment_name, spec.trial_name))
    try:
        for i in range(sv.n_servers):
            controller.submit(f"gen_server/{i}",
                              _worker_cmd("gen_server", i, spec),
                              env=env)
        for i, rname in enumerate(router_names):
            controller.submit(rname, _worker_cmd("router", i, spec),
                              env=env)
        for i, gname in enumerate(gateway_names):
            controller.submit(gname, _worker_cmd("gateway", i, spec),
                              env=env)
        panel = WorkerControlPanel(spec.experiment_name, spec.trial_name)
        panel.connect(worker_names, timeout=120)
        configs = {f"gen_server/{i}": dict(config=dict(
            spec_path=path, server_index=i))
            for i in range(sv.n_servers)}
        for rname in router_names + gateway_names:
            configs[rname] = dict(config=dict(spec_path=path))
        out = panel.group_request_varied("configure", configs,
                                         timeout=600)
        panel.group_request("start")
        logger.info("All %d serving workers started: %s.",
                    len(worker_names),
                    {w: r.get("address") for w, r in out.items()
                     if isinstance(r, dict)})
        sd_path = controller.write_scrape_targets(
            labels=dict(experiment=spec.experiment_name,
                        trial=spec.trial_name),
            experiment_name=spec.experiment_name,
            trial_name=spec.trial_name)
        if sd_path:
            logger.info("Prometheus scrape targets written: %s "
                        "(file_sd_configs).", sd_path)

        watchdog = Watchdog(
            spec.experiment_name, spec.trial_name, worker_names,
            timeout=ft.heartbeat_timeout, grace=ft.startup_grace_secs,
            poll_interval=ft.watchdog_poll_secs)
        end = None if duration is None else time.monotonic() + duration
        deadline = time.monotonic() + timeout
        dead_servers = set()
        dead_routers = set()
        autoscaler = None

        def _tolerable(w: str) -> bool:
            # in fleet mode a replica death is survivable until the
            # last replica goes. With a SHARDED router plane (N > 1)
            # a router shard death is survivable too -- survivors
            # adopt its hash range -- until the last shard goes; a
            # singleton router's loss stays fatal.
            if fleet and w in router_names:
                if n_routers < 2:
                    return False
                if w not in dead_routers:
                    dead_routers.add(w)
                    logger.warning(
                        "Router shard %s died; ring re-homes to %d "
                        "surviving shard(s).", w,
                        n_routers - len(dead_routers))
                return len(dead_routers) < n_routers
            if not (fleet and w in gen_names):
                return False
            if w not in dead_servers:
                dead_servers.add(w)
                if autoscaler is not None:
                    # capacity accounting must track reality: the
                    # policy re-fires a scale-up if load needs it
                    autoscaler.forget(w)
                logger.warning(
                    "Serving replica %s died; fleet continues on %d "
                    "survivor(s) (failover at the router).", w,
                    len(gen_names) - len(dead_servers))
            return len(dead_servers) < len(gen_names)

        # -- closed-loop autoscaling (docs/serving.md "Autoscaling"):
        # an AutoscaleController in THIS supervision loop turns live
        # router signals into replica spawns/retires
        if getattr(sv, "autoscale", False):
            from realhf_tpu.serving.fleet import FleetRegistry
            from realhf_tpu.system.autoscale import AutoscaleController
            from realhf_tpu.system.elastic import (
                AutoscalePolicy,
                AutoscaleSignals,
            )

            def _member_add(w: str):
                if w not in gen_names:
                    gen_names.append(w)
                if w not in worker_names:
                    worker_names.append(w)
                watchdog.add_workers([w])

            def _member_remove(w: str):
                # BEFORE the exit command: a planned departure must
                # not read as a death in the failure loop
                watchdog.remove_workers([w])
                if w in worker_names:
                    worker_names.remove(w)
                if w in gen_names:
                    gen_names.remove(w)
                dead_servers.discard(w)

            registry = FleetRegistry(spec.experiment_name,
                                     spec.trial_name,
                                     lease_ttl=sv.lease_ttl_secs)
            actuator = _ServeFleetActuator(
                controller, panel, sched, spec, path, env,
                on_started=_member_add, on_retiring=_member_remove,
                reap_grace=sv.drain_timeout_secs + 10)
            autoscaler = AutoscaleController(
                AutoscalePolicy(
                    min_replicas=sv.autoscale_min_replicas,
                    max_replicas=sv.autoscale_max_replicas,
                    up_queue_per_replica=(
                        sv.autoscale_up_queue_per_replica),
                    up_latency_secs=sv.autoscale_up_latency_secs,
                    consecutive_up=sv.autoscale_consecutive_up,
                    consecutive_down=sv.autoscale_consecutive_down,
                    down_idle_per_replica=(
                        sv.autoscale_down_idle_per_replica),
                    cooldown_secs=sv.autoscale_cooldown_secs),
                actuator, registry, initial=list(gen_names),
                spawn_deadline_secs=sv.autoscale_spawn_deadline_secs,
                retire_deadline_secs=sv.drain_timeout_secs + 60)
            _last_rej = [0]
            _next_obs = [time.monotonic()
                         + sv.autoscale_interval_secs]
            signal_source = getattr(sv, "autoscale_signal_source",
                                    "zmq")
            latency_signal = getattr(sv, "autoscale_latency_signal",
                                     "ewma")

            def _live_routers():
                return [r for r in router_names
                        if r not in dead_routers]

            def _merge_router_stats(shards):
                """Aggregate per-shard router stats into one fleet
                view: load figures SUM across shards, latency takes
                the worst shard (the autoscale policy keys on the
                tail, and a single hot shard is real pressure)."""
                shards = [s for s in shards if isinstance(s, dict)]
                if not shards:
                    raise RuntimeError("no router stats available")
                out = dict(
                    pending=sum(int(s.get("pending") or 0)
                                for s in shards),
                    inflight=sum(int(s.get("inflight") or 0)
                                 for s in shards),
                    rejections=sum(int(s.get("rejections") or 0)
                                   for s in shards))
                for k in ("latency_ewma_secs", "latency_p50",
                          "latency_p95"):
                    vals = [s.get(k) for s in shards
                            if s.get(k) is not None]
                    out[k] = max(vals) if vals else None
                return out

            def _router_stats_zmq():
                live = _live_routers()
                replies = panel.group_request(
                    "stats", worker_names=live, timeout=30)
                return _merge_router_stats(
                    [replies.get(r) for r in live])

            def _router_stats_http():
                """Poll each router shard's /metrics telemetry
                endpoint -- the same Prometheus text a real scraper
                sees (docs/observability.md "Scraping the fleet") --
                resolved through names.telemetry, then aggregate."""
                import urllib.request

                from realhf_tpu.obs import http as obs_http
                shards = []
                for rname in _live_routers():
                    addr = name_resolve.get(names.telemetry(
                        spec.experiment_name, spec.trial_name, rname))
                    with urllib.request.urlopen(
                            f"http://{addr}/metrics", timeout=10) as r:
                        fams = obs_http.parse_prometheus_text(
                            r.read().decode("utf-8", "replace"))
                    shards.append(dict(
                        pending=obs_http.prom_scalar(
                            fams, "router_pending", agg="last"),
                        inflight=obs_http.prom_scalar(
                            fams, "router_inflight", agg="last"),
                        rejections=obs_http.prom_scalar(
                            fams, "router_rejections_total"),
                        latency_ewma_secs=obs_http.prom_scalar(
                            fams, "router_latency_ewma_secs",
                            agg="last"),
                        latency_p50=obs_http.prom_histogram_quantile(
                            fams, "router_latency_seconds", 0.5),
                        latency_p95=obs_http.prom_histogram_quantile(
                            fams, "router_latency_seconds", 0.95)))
                return _merge_router_stats(shards)

            def _autoscale_tick():
                actuator.poll_bringup()
                now = time.monotonic()
                if now < _next_obs[0]:
                    return
                _next_obs[0] = now + sv.autoscale_interval_secs
                try:
                    if signal_source == "http":
                        try:
                            st = _router_stats_http()
                        except Exception as e:  # noqa: BLE001 - the
                            # ZMQ stats command stays the fallback
                            logger.warning(
                                "Autoscale: router /metrics scrape "
                                "failed (%s); falling back to zmq "
                                "stats.", e)
                            st = _router_stats_zmq()
                    else:
                        st = _router_stats_zmq()
                except Exception as e:  # noqa: BLE001 - a missed
                    # observation must not kill supervision
                    logger.warning("Autoscale: router stats "
                                   "unavailable this tick: %s", e)
                    return
                rej = int(st.get("rejections", 0))
                pending = int(st.get("pending", 0))
                if latency_signal in ("p50", "p95"):
                    # tail latency from the router_latency_seconds
                    # histogram (None until the first completion)
                    lat = st.get(f"latency_{latency_signal}")
                    if lat is None:
                        lat = st.get("latency_ewma_secs")
                else:
                    lat = st.get("latency_ewma_secs")
                sig = AutoscaleSignals(
                    queue_depth=pending,
                    inflight=max(0, int(st.get("inflight", 0))
                                 - pending),
                    rejections=max(0, rej - _last_rej[0]),
                    latency_secs=float(lat or 0.0))
                _last_rej[0] = rej
                autoscaler.step(sig, source="run_serve")

        while True:
            for w in list(worker_names):
                info = sched.find(w)
                failed = (info.state.value == "FAILED"
                          or panel.get_worker_status(w)
                          == WorkerServerStatus.ERROR)
                if failed and not _tolerable(w):
                    raise JobException(w, info.state)
            watchdog.poll()
            for w in watchdog.lost_longer_than(ft.worker_lost_fatal_secs):
                if not _tolerable(w):
                    raise JobException(w, JobState.LOST)
            if autoscaler is not None:
                _autoscale_tick()
            if end is not None and time.monotonic() > end:
                break
            if time.monotonic() > deadline:
                break
            time.sleep(0.2)

        alive = [w for w in worker_names if w not in dead_servers]
        stats = panel.group_request("stats", worker_names=alive)
        if autoscaler is not None:
            import dataclasses as _dc
            stats["autoscale_events"] = [
                _dc.asdict(e) for e in autoscaler.events]
        # exit drains each server (GenServerWorker._exit_hook) before
        # the COMPLETED status lands
        panel.group_request("exit", worker_names=alive,
                            timeout=sv.drain_timeout_secs + 60)
        sched.wait(timeout=sv.drain_timeout_secs + 60,
                   check_status=False)
        return stats
    finally:
        sched.stop_all(grace=sv.drain_timeout_secs + 10)
        _teardown_obs(controller)


def main_start(spec: ExperimentSpec, recover_mode: str = "disabled",
               recover_retries: int = 1,
               env: Optional[Dict[str, str]] = None,
               timeout: float = 3600.0) -> Dict:
    """Launch with the auto-recover loop (reference main.py:205-230):
    recover_mode=auto relaunches a failed trial in resume mode up to
    ``recover_retries`` times."""
    attempt_mode = recover_mode if recover_mode in ("resume", "save") \
        else ("save" if recover_mode == "auto" else "disabled")
    recover_count = 0
    while True:
        try:
            return run_trial(spec, recover_mode=attempt_mode, env=env,
                             timeout=timeout)
        except (JobException, TimeoutError) as e:
            recover_count += 1
            if recover_mode != "auto" or recover_count > recover_retries:
                raise
            logger.warning(
                "Trial failed (%s); auto-recover relaunch %d/%d in "
                "resume mode.", e, recover_count, recover_retries)
            attempt_mode = "resume"
            time.sleep(2)


def pod_manifest_main(argv: Optional[list] = None) -> int:
    """``python -m realhf_tpu.apps.main pod-manifest ...`` (also
    wrapped by ``scripts/gen_pod_manifest.py``): generate the
    deterministic per-host launch manifest (docs/distributed.md "Pod
    deployment"). The output round-trips through
    ``MultiHostLocalScheduler(manifest=...)`` for single-box
    emulation, or drives a GKE/xmanager template for a real pod."""
    import argparse

    from realhf_tpu.system import pod

    parser = argparse.ArgumentParser(
        "realhf_tpu pod-manifest",
        description="Generate a deterministic pod launch manifest.")
    parser.add_argument("--experiment_name", required=True)
    parser.add_argument("--trial_name", required=True)
    parser.add_argument("--n_hosts", type=int, required=True)
    parser.add_argument("--n_model_workers", type=int, required=True)
    parser.add_argument("--n_chips_per_host", type=int, default=None)
    parser.add_argument("--base_scrape_port", type=int,
                        default=pod.DEFAULT_SCRAPE_BASE_PORT)
    parser.add_argument("--no_master", action="store_true",
                        help="omit master_worker/0 (serving-only pod)")
    parser.add_argument("--out", default="-",
                        help="output path ('-' = stdout)")
    parser.add_argument("--scrape_out", default=None,
                        help="also write the Prometheus file_sd "
                             "scrape-target file here")
    args = parser.parse_args(argv)
    manifest = pod.build_pod_manifest(
        args.experiment_name, args.trial_name,
        n_hosts=args.n_hosts, n_model_workers=args.n_model_workers,
        include_master=not args.no_master,
        n_chips_per_host=args.n_chips_per_host,
        base_scrape_port=args.base_scrape_port)
    text = manifest.to_json()
    if args.out == "-":
        sys.stdout.write(text)
    else:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
        logger.info("Pod manifest written: %s (%d hosts, %d workers).",
                    args.out, manifest.n_hosts, len(manifest.workers))
    if args.scrape_out:
        pod.write_scrape_targets(
            manifest.hosts, args.scrape_out,
            labels=dict(experiment=args.experiment_name,
                        trial=args.trial_name))
    return 0


def _cli(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "pod-manifest":
        return pod_manifest_main(argv[1:])
    sys.stderr.write(
        "usage: python -m realhf_tpu.apps.main pod-manifest ...\n"
        "(training launches go through run_trial/main_start; see "
        "docs/distributed.md)\n")
    return 2


def main_stop(experiment_name: str, trial_name: str):
    """Best-effort teardown of a running trial (reference
    main_stop:233): ask every registered worker to exit."""
    panel = WorkerControlPanel(experiment_name, trial_name)
    # find_subtree returns KEYS (get_subtree returns values)
    keys = name_resolve.find_subtree(
        names.worker_root(experiment_name, trial_name))
    workers = [k.rsplit("/status/", 1)[-1] for k in keys] if keys else []
    if not workers:
        logger.info("No live workers found for %s/%s.", experiment_name,
                    trial_name)
        return
    try:
        panel.connect(workers, timeout=5)
        panel.group_request("exit", timeout=10)
    except Exception as e:  # noqa: BLE001 - best effort
        logger.warning("main_stop: %s", e)


if __name__ == "__main__":
    sys.exit(_cli())
