"""Worker bootstrap entrypoint (reference ``realhf/apps/remote.py``):
the scheduler launches ``python -m realhf_tpu.apps.remote worker
--worker_type {master_worker|model_worker} --index I ...`` processes;
each runs its Worker poll loop until the controller sends exit.
"""

import argparse
import os


def main_worker(args):
    # Backend selection must happen before jax initializes. Workers in
    # CPU tests are spawned with REALHF_TPU_BACKEND=cpu.
    if os.environ.get("REALHF_TPU_BACKEND") == "cpu":
        from realhf_tpu.base.backend import force_cpu_backend
        force_cpu_backend()

    from realhf_tpu.base import cluster, logging, name_resolve
    from realhf_tpu.base.importing import import_usercode

    import_usercode()  # custom interfaces must register in workers too

    if os.environ.get("REALHF_TPU_NAME_RESOLVE_ROOT"):
        name_resolve.reconfigure(
            "nfs", record_root=os.environ["REALHF_TPU_NAME_RESOLVE_ROOT"])

    host = cluster.current_host_id()
    if host:
        # pod launch (system/pod.py): name the failure domain up front
        # so a host-grouped postmortem can match launcher/orchestrator
        # logs against worker boots
        logging.getLogger("remote").info(
            "Worker %s/%d booting on pod host %s (pid %d).",
            args.worker_type, args.index, host, os.getpid())

    if args.worker_type == "model_worker":
        from realhf_tpu.system.model_worker import ModelWorker
        cls = ModelWorker
        name = f"model_worker/{args.index}"
    elif args.worker_type == "master_worker":
        from realhf_tpu.system.master_worker import MasterWorker
        cls = MasterWorker
        name = "master_worker/0"
    elif args.worker_type == "gen_server":
        from realhf_tpu.serving.worker import GenServerWorker
        cls = GenServerWorker
        name = f"gen_server/{args.index}"
    elif args.worker_type == "router":
        from realhf_tpu.serving.worker import RouterWorker
        cls = RouterWorker
        name = f"router/{args.index}"
    elif args.worker_type == "gateway":
        from realhf_tpu.serving.worker import GatewayWorker
        cls = GatewayWorker
        name = f"gateway/{args.index}"
    else:
        raise ValueError(args.worker_type)
    cls(args.experiment_name, args.trial_name, name).run()


def main():
    parser = argparse.ArgumentParser("realhf_tpu remote entry")
    sub = parser.add_subparsers(dest="cmd", required=True)
    w = sub.add_parser("worker")
    w.add_argument("--worker_type", required=True,
                   choices=["model_worker", "master_worker",
                            "gen_server", "router", "gateway"])
    w.add_argument("--index", type=int, default=0)
    w.add_argument("--experiment_name", required=True)
    w.add_argument("--trial_name", required=True)
    args = parser.parse_args()
    if args.cmd == "worker":
        main_worker(args)


if __name__ == "__main__":
    main()
