"""Shared testing utilities (reference ``realhf/base/testing.py``).

Kept inside the package (not tests/) so objects defined here are
picklable across OS processes -- multi-worker tests ship an
ExperimentSpec containing the tokenizer to spawned workers.
"""


class IntegerTokenizer:
    """Deterministic word-hash tokenizer for tests and mock/profile
    runs (no network: HF hub is unreachable in CI)."""

    pad_token_id = 0
    eos_token_id = 1
    eos_token = " zEOSz"
    padding_side = "left"

    def __init__(self, vocab_size: int = 1000):
        self.vocab_size = vocab_size

    def __call__(self, texts, truncation=False, max_length=None,
                 padding=False, return_length=False,
                 return_attention_mask=False, **kw):
        ids = [[2 + (sum(map(ord, w)) % self.vocab_size)
                for w in t.split()] for t in texts]
        if truncation and max_length:
            ids = [x[:max_length] for x in ids]
        out = {"input_ids": ids}
        if return_length:
            out["length"] = [len(x) for x in ids]
        return out

    def decode(self, ids, **kw):
        return " ".join(map(str, ids))
