"""Shared testing utilities (reference ``realhf/base/testing.py``).

Kept inside the package (not tests/) so objects defined here are
picklable across OS processes -- multi-worker tests ship an
ExperimentSpec containing the tokenizer to spawned workers.
"""


class IntegerTokenizer:
    """Deterministic word-hash tokenizer for tests and mock/profile
    runs (no network: HF hub is unreachable in CI)."""

    pad_token_id = 0
    eos_token_id = 1
    eos_token = " zEOSz"
    padding_side = "left"

    def __init__(self, vocab_size: int = 1000):
        self.vocab_size = vocab_size

    def __call__(self, texts, truncation=False, max_length=None,
                 padding=False, return_length=False,
                 return_attention_mask=False, **kw):
        ids = [[2 + (sum(map(ord, w)) % self.vocab_size)
                for w in t.split()] for t in texts]
        if truncation and max_length:
            ids = [x[:max_length] for x in ids]
        out = {"input_ids": ids}
        if return_length:
            out["length"] = [len(x) for x in ids]
        return out

    def decode(self, ids, **kw):
        return " ".join(map(str, ids))


class FakeSlotBackend:
    """Deterministic slot backend implementing the
    ``engine.inflight.InflightBatchingGenerator`` step API without a
    model: ``prompt[0]`` encodes how many tokens the sequence needs,
    and every ``decode_chunk`` advances each live slot by up to
    ``chunk`` tokens. Used by scheduler unit tests and the
    chaos-drill harness (scripts/chaos_drill.py), where thousands of
    serve iterations must run in milliseconds.

    With ``prefix_capable=True`` it also implements the prefix-cache
    extensions (``supports_prefix_fill`` / ``fill_slot(cached_len,
    prefix_kv)`` / ``harvest(export_kv=True)``): exported KV blocks
    are tiny ``[1, 1, seq_len, 1]`` float32 arrays (4 bytes per
    token+layer-head), enough to drive radix-tree byte accounting
    without a model.

    With ``kv_pool=`` (a ``KVPool.host_only(...)``) it grows the
    PAGED surface the scheduler admission/OOM path keys on --
    ``kv_pool_stats`` / ``admission_blocks_needed`` /
    ``fill_slot(cached_blocks=...)`` / ``harvest(export_blocks=True)``
    -- with the REAL allocator arithmetic (alloc at fill, lazy growth
    per decode chunk raising ``KVPoolOOM``, refcounted aliasing,
    free at release), so scheduler and chaos suites exercise pool
    backpressure without a model."""

    def __init__(self, n_slots: int = 2, chunk: int = 4,
                 max_prompt_len: int = 64,
                 prefix_capable: bool = False, kv_pool=None):
        self.n_slots = n_slots
        self.chunk = chunk
        self.max_prompt_len = max_prompt_len
        self.supports_prefix_fill = prefix_capable
        self.kv_pool = kv_pool
        self.params = "v0"
        self._slots = {}  # slot -> [int_id, need, got]
        self._prompts = {}  # slot -> prompt copy (prefix mode)
        self._blocks = {}  # slot -> block id list (pool mode)
        self._plens = {}  # slot -> prompt length (pool mode)
        self.fills = []  # (slot, int_id, cached_len) fill audit trail

    def free_slots(self):
        return [s for s in range(self.n_slots) if s not in self._slots]

    def fill_slot(self, slot, int_id, prompt, cached_len=0,
                  prefix_kv=None, cached_blocks=None):
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} > {self.max_prompt_len}")
        if self.kv_pool is not None:
            pool = self.kv_pool
            c = max(0, min(int(cached_len), len(prompt) - 1))
            c -= c % pool.block_len
            n_alias = c // pool.block_len
            own = pool.alloc(pool.blocks_for_rows(len(prompt))
                             - n_alias)  # may raise KVPoolOOM
            try:
                alias = [int(b)
                         for b in (cached_blocks or [])[:n_alias]]
                if alias:
                    pool.incref(alias)
            except BaseException:
                # mirror the real backend: a bad alias chain must not
                # leak the fresh blocks (nothing references them yet)
                pool.free(own)
                raise
            self._blocks[slot] = alias + own
            self._plens[slot] = len(prompt)
        self._slots[slot] = [int_id, int(prompt[0]), 0]
        if self.supports_prefix_fill or self.kv_pool is not None:
            import numpy as np
            self._prompts[slot] = np.asarray(prompt).copy()
        self.fills.append((slot, int_id, int(cached_len)))

    def decode_chunk(self, key):
        if self.kv_pool is not None:
            pool = self.kv_pool
            for slot, (_, need, got) in self._slots.items():
                rows = self._plens[slot] + min(need, got + self.chunk)
                grow = pool.blocks_for_rows(rows) \
                    - len(self._blocks[slot])
                if grow > 0:
                    self._blocks[slot].extend(
                        pool.alloc(grow))  # may raise KVPoolOOM
        for v in self._slots.values():
            v[2] = min(v[1], v[2] + self.chunk)

    def harvest(self, export_kv=False, export_blocks=False):
        import numpy as np

        from realhf_tpu.engine.inflight import FinishedSequence
        out = []
        for slot, (i, need, got) in list(self._slots.items()):
            if got >= need:
                fs = FinishedSequence(
                    request_id=i, tokens=np.arange(got),
                    logprobs=np.zeros(got), no_eos=True)
                if export_kv and self.supports_prefix_fill:
                    n = len(self._prompts[slot]) + got
                    fs.kv = (np.zeros((1, 1, n, 1), np.float32),
                             np.zeros((1, 1, n, 1), np.float32))
                if export_blocks and self.kv_pool is not None:
                    blocks = tuple(self._blocks[slot])
                    self.kv_pool.incref(blocks)
                    fs.blocks = blocks
                    fs.n_rows = self._plens[slot] + got
                out.append(fs)
                self.release_slot(slot)
        return out

    def release_slot(self, slot):
        self._slots.pop(slot, None)
        self._prompts.pop(slot, None)
        self._plens.pop(slot, None)
        if self.kv_pool is not None and slot in self._blocks:
            self.kv_pool.free(self._blocks.pop(slot))

    def kv_pool_stats(self):
        s = self.kv_pool.stats()
        s["rows_in_use"] = sum(
            self._plens[slot] + got
            for slot, (_, _, got) in self._slots.items())
        return s

    def admission_blocks_needed(self, prompt_len, cached_len=0):
        pool = self.kv_pool
        c = max(0, min(int(cached_len), int(prompt_len) - 1))
        c -= c % pool.block_len
        return (pool.blocks_for_rows(prompt_len)
                - c // pool.block_len + 1)

    def swap_params(self, p):
        self.params = p

    def snapshot_slot(self, slot):
        import numpy as np
        _, _, got = self._slots[slot]
        return np.arange(got), np.zeros(got)

    @property
    def n_live(self):
        return len(self._slots)
