"""Sequence-packing arithmetic: balanced contiguous partitioning and
batch reordering.

Behavioral parity with reference ``realhf/base/datapack.py``:
- ``min_abs_diff_partition(lens, k, min_size)``: split a 1D array of
  sequence lengths into k contiguous, non-empty chunks with balanced
  token sums (used for DP dispatch of packed batches).
- ``reorder_to_balanced_batches``: greedy longest-first binning so that
  consecutive fixed-size batches have near-equal token counts.
- ``flat2d``: flatten a list of lists.

Implementation is NumPy-vectorized dynamic programming (the reference
uses numba; numba is not assumed here).
"""

import itertools
from typing import Any, List, Sequence, Tuple, Union

import numpy as np


def flat2d(arr: Sequence[Sequence[Any]]) -> List[Any]:
    return list(itertools.chain(*arr))


def partition_balanced(nums: np.ndarray, k: int, min_size: int = 1) -> List[int]:
    """Contiguously partition ``nums`` into ``k`` chunks minimizing the
    maximum chunk sum, each chunk containing >= min_size elements.

    Returns k+1 boundary indices including 0 and len(nums). Minimizing
    the max chunk sum also produces small max-min spread, matching the
    balancing contract of the reference DP (``datapack.py:13``).
    """
    nums = np.asarray(nums, dtype=np.int64)
    n = len(nums)
    assert n >= k * min_size, (n, k, min_size)
    prefix = np.concatenate([[0], np.cumsum(nums)])

    INF = np.int64(1 << 60)
    # dp[j, i]: minimal max-chunk-sum partitioning nums[:i] into j chunks.
    dp = np.full((k + 1, n + 1), INF, dtype=np.int64)
    split = np.zeros((k + 1, n + 1), dtype=np.int64)
    for i in range(min_size, n + 1):
        dp[1, i] = prefix[i]
    for j in range(2, k + 1):
        lo = (j - 1) * min_size  # minimal split point
        for i in range(j * min_size, n + 1):
            x = np.arange(lo, i - min_size + 1)
            # cost = max(best of first j-1 chunks over nums[:x], sum of nums[x:i])
            cost = np.maximum(dp[j - 1, lo:i - min_size + 1], prefix[i] - prefix[lo:i - min_size + 1])
            b = int(np.argmin(cost))
            dp[j, i] = cost[b]
            split[j, i] = x[b]
    bounds = [n]
    idx = n
    for j in range(k, 1, -1):
        idx = int(split[j, idx])
        bounds.append(idx)
    bounds.append(0)
    return bounds[::-1]


def partition_balanced_tuples(nums: np.ndarray, k: int,
                              min_size: int = 1) -> List[Tuple[int, int]]:
    b = partition_balanced(nums, k, min_size)
    return [(b[i], b[i + 1]) for i in range(k)]


def min_abs_diff_partition(arr: Union[np.ndarray, List[int]], k: int,
                           min_size: int = 1) -> List[Tuple[int, int]]:
    """Validated balanced partition (reference ``datapack.py:76``)."""
    if isinstance(arr, list):
        arr = np.array(arr)
    if arr.ndim != 1:
        raise ValueError(f"The array to be partitioned must be 1D, got shape {arr.shape}.")
    if len(arr) < k:
        raise ValueError(f"Array length {len(arr)} < number of partitions {k}.")
    if len(arr) < k * min_size:
        raise ValueError(
            f"Array length {len(arr)} < k * min_size = {k} * {min_size}.")
    partitions = partition_balanced_tuples(arr, k, min_size)
    last_end = 0
    for start, end in partitions:
        if start != last_end or end <= start:
            raise ValueError(
                f"Invalid partition {partitions} of lengths {arr} into k={k}.")
        last_end = end
    return partitions


def reorder_to_balanced_batches(seqlens: np.ndarray,
                                n_seqs_per_batch: int) -> Tuple[np.ndarray, int]:
    """Greedy longest-first binning into ceil(n / n_seqs_per_batch) bins
    balanced by token count; bins emitted largest-total first
    (reference ``datapack.py:116``). Returns (reordered indices, max
    pairwise bin token-count difference)."""
    seqlens = np.asarray(seqlens)
    n_bins = (len(seqlens) + n_seqs_per_batch - 1) // n_seqs_per_batch
    bins: List[List[int]] = [[] for _ in range(n_bins)]
    bin_counts = np.zeros(n_bins, dtype=np.int64)
    bin_tokens = np.zeros(n_bins, dtype=np.int64)
    for i in np.argsort(seqlens)[::-1]:
        eligible = np.where(bin_counts < n_seqs_per_batch, bin_tokens,
                            np.iinfo(np.int64).max)
        b = int(eligible.argmin())
        bins[b].append(int(i))
        bin_counts[b] += 1
        bin_tokens[b] += seqlens[i]
    max_diff = int(bin_tokens.max() - bin_tokens.min()) if n_bins > 1 else 0
    order = []
    for b in np.argsort(bin_tokens)[::-1]:
        order.extend(bins[b])
    return np.array(order, dtype=np.int64), max_diff


def ffd_allocate(values: Sequence[int], capacity: int,
                 min_groups: int = 1) -> List[List[int]]:
    """First-fit-decreasing bin packing of ``values`` into bins of
    ``capacity``; returns index groups. Used to build packed microbatches
    bounded by a token budget."""
    order = np.argsort(values)[::-1]
    groups: List[List[int]] = []
    sums: List[int] = []
    for i in order:
        v = values[i]
        placed = False
        for g, s in enumerate(sums):
            if s + v <= capacity:
                groups[g].append(int(i))
                sums[g] += v
                placed = True
                break
        if not placed:
            groups.append([int(i)])
            sums.append(int(v))
    while len(groups) < min_groups:
        # Split the largest group to reach the minimum count.
        g = int(np.argmax([len(g) for g in groups]))
        if len(groups[g]) <= 1:
            raise ValueError("Cannot split further to reach min_groups.")
        half = len(groups[g]) // 2
        groups.append(groups[g][half:])
        groups[g] = groups[g][:half]
    return groups
