"""Frequency control and wall-clock utilities.

Parity with reference ``realhf/base/timeutil.py``: `FrequencyControl`
(trigger every N steps / T seconds) and `EpochStepTimeFreqCtl`
combining epoch-, step-, and time-frequency triggers for save/eval
scheduling in the master worker.
"""

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class FrequencyControl:
    """Triggers when either the step count or elapsed seconds exceeds
    its configured frequency (reference ``timeutil.py:11``).

    frequency_steps=None disables step triggering; frequency_seconds=None
    disables time triggering. If both are None, `check()` never fires
    unless initial_value was True for the first call.
    """

    frequency_steps: Optional[int] = None
    frequency_seconds: Optional[float] = None
    initial_value: bool = False

    def __post_init__(self):
        self._last_time = time.monotonic()
        self._steps = 0
        self._first = True
        self.total_checks = 0

    def check(self, steps: int = 1) -> bool:
        self.total_checks += 1
        self._steps += steps
        now = time.monotonic()
        if self._first and self.initial_value:
            self._first = False
            self._last_time = now
            self._steps = 0
            return True
        self._first = False
        hit = False
        if self.frequency_steps is not None and self._steps >= self.frequency_steps:
            hit = True
        if (self.frequency_seconds is not None
                and now - self._last_time >= self.frequency_seconds):
            hit = True
        if hit:
            self._last_time = now
            self._steps = 0
        return hit


@dataclasses.dataclass
class EpochStepTimeFreqCtl:
    """Composite control over epoch boundaries, global steps, and time
    (reference ``timeutil.py:98``), used for save/eval triggers."""

    freq_epoch: Optional[int] = None
    freq_step: Optional[int] = None
    freq_sec: Optional[float] = None

    def __post_init__(self):
        self._epoch_ctl = FrequencyControl(frequency_steps=self.freq_epoch)
        self._step_ctl = FrequencyControl(frequency_steps=self.freq_step)
        self._time_ctl = FrequencyControl(frequency_seconds=self.freq_sec)

    def check(self, epochs: int, steps: int) -> bool:
        # Evaluate all three so their internal counters advance together.
        e = self._epoch_ctl.check(epochs) if self.freq_epoch is not None else False
        s = self._step_ctl.check(steps) if self.freq_step is not None else False
        t = self._time_ctl.check() if self.freq_sec is not None else False
        return e or s or t


class Timer:
    """Context-manager wall-clock timer."""

    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.monotonic() - self.start
        return False
