"""Retry with exponential backoff + jitter for control-plane calls.

The distributed runtime retries transient control-plane failures
(reply timeouts, scheduler submission hiccups) instead of dying on the
first one. Policies are small value objects so every call site can
tune attempts/delays independently; randomness and sleeping are
injectable for deterministic tests.
"""

import dataclasses
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from realhf_tpu.base import logging

logger = logging.getLogger("retry")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay_i = min(base * factor**i, max_delay),
    plus uniform jitter in [0, jitter * delay_i] so a fleet of
    retriers never thunders in lockstep.

    ``max_elapsed`` caps the TOTAL wall clock of one retry_call --
    attempts plus sleeps -- regardless of how many attempts remain.
    Stacked retries during a degradation event (every control-plane
    call backing off at once) must not exceed the watchdog grace
    window, or they mask a real worker loss as transient slowness.
    None = attempts alone bound the call."""
    max_attempts: int = 3
    base_delay: float = 0.5
    factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.5
    max_elapsed: Optional[float] = None


def backoff_delays(policy: RetryPolicy,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Delays to sleep BETWEEN attempts (max_attempts - 1 of them)."""
    rng = rng or random
    for i in range(max(0, policy.max_attempts - 1)):
        d = min(policy.base_delay * policy.factor ** i, policy.max_delay)
        yield d + rng.uniform(0.0, policy.jitter * d)


def retry_call(fn: Callable, policy: Optional[RetryPolicy] = None,
               retry_on: Tuple[Type[BaseException], ...] = (TimeoutError,),
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               clock: Callable[[], float] = time.monotonic,
               what: str = ""):
    """Call ``fn()`` up to ``policy.max_attempts`` times, sleeping a
    backoff-with-jitter delay between attempts. Only exceptions listed
    in ``retry_on`` are retried; anything else propagates immediately,
    as does the final matching failure. ``on_retry(attempt, exc)`` is
    invoked before each re-attempt (attempt counts from 1).

    With ``policy.max_elapsed`` set, a re-attempt is abandoned -- and
    the last failure re-raised -- once the total-deadline budget is
    spent or the upcoming sleep would overrun it. ``clock`` is the
    monotonic time source (injectable for tests)."""
    policy = policy or RetryPolicy()
    delays = backoff_delays(policy, rng=rng)
    start = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:
            try:
                delay = next(delays)
            except StopIteration:
                raise e  # attempts exhausted: surface the last failure
            if policy.max_elapsed is not None and \
                    clock() - start + delay > policy.max_elapsed:
                logger.warning(
                    "Not retrying %s: total deadline budget "
                    "max_elapsed=%.1fs would be exceeded (%.1fs spent "
                    "+ %.1fs backoff).", what or getattr(
                        fn, "__name__", "call"), policy.max_elapsed,
                    clock() - start, delay)
                raise e
            logger.warning("Retrying %s (attempt %d/%d) after %s; "
                           "sleeping %.2fs.", what or getattr(
                               fn, "__name__", "call"), attempt,
                           policy.max_attempts, e, delay)
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)
