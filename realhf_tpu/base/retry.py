"""Retry with exponential backoff + jitter for control-plane calls.

The distributed runtime retries transient control-plane failures
(reply timeouts, scheduler submission hiccups) instead of dying on the
first one. Policies are small value objects so every call site can
tune attempts/delays independently; randomness and sleeping are
injectable for deterministic tests.
"""

import dataclasses
import queue as queue_mod
import random
import threading
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from realhf_tpu.base import logging

logger = logging.getLogger("retry")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay_i = min(base * factor**i, max_delay),
    plus uniform jitter in [0, jitter * delay_i] so a fleet of
    retriers never thunders in lockstep.

    ``max_elapsed`` caps the TOTAL wall clock of one retry_call --
    attempts plus sleeps -- regardless of how many attempts remain.
    Stacked retries during a degradation event (every control-plane
    call backing off at once) must not exceed the watchdog grace
    window, or they mask a real worker loss as transient slowness.
    None = attempts alone bound the call."""
    max_attempts: int = 3
    base_delay: float = 0.5
    factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.5
    max_elapsed: Optional[float] = None


def backoff_delays(policy: RetryPolicy,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Delays to sleep BETWEEN attempts (max_attempts - 1 of them)."""
    rng = rng or random
    for i in range(max(0, policy.max_attempts - 1)):
        d = min(policy.base_delay * policy.factor ** i, policy.max_delay)
        yield d + rng.uniform(0.0, policy.jitter * d)


def retry_call(fn: Callable, policy: Optional[RetryPolicy] = None,
               retry_on: Tuple[Type[BaseException], ...] = (TimeoutError,),
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               clock: Callable[[], float] = time.monotonic,
               what: str = ""):
    """Call ``fn()`` up to ``policy.max_attempts`` times, sleeping a
    backoff-with-jitter delay between attempts. Only exceptions listed
    in ``retry_on`` are retried; anything else propagates immediately,
    as does the final matching failure. ``on_retry(attempt, exc)`` is
    invoked before each re-attempt (attempt counts from 1).

    With ``policy.max_elapsed`` set, a re-attempt is abandoned -- and
    the last failure re-raised -- once the total-deadline budget is
    spent or the upcoming sleep would overrun it. ``clock`` is the
    monotonic time source (injectable for tests)."""
    policy = policy or RetryPolicy()
    delays = backoff_delays(policy, rng=rng)
    start = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:
            try:
                delay = next(delays)
            except StopIteration:
                raise e  # attempts exhausted: surface the last failure
            if policy.max_elapsed is not None and \
                    clock() - start + delay > policy.max_elapsed:
                logger.warning(
                    "Not retrying %s: total deadline budget "
                    "max_elapsed=%.1fs would be exceeded (%.1fs spent "
                    "+ %.1fs backoff).", what or getattr(
                        fn, "__name__", "call"), policy.max_elapsed,
                    clock() - start, delay)
                raise e
            logger.warning("Retrying %s (attempt %d/%d) after %s; "
                           "sleeping %.2fs.", what or getattr(
                               fn, "__name__", "call"), attempt,
                           policy.max_attempts, e, delay)
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


# ----------------------------------------------------------------------
@dataclasses.dataclass
class HedgeAttempt:
    """Handed to every hedged call: ``index`` is the launch order (0 =
    the primary), ``cancelled`` is set the moment another attempt wins
    (poll it between blocking slices -- cancellation is cooperative),
    ``deadline`` is the ABSOLUTE total deadline on the caller's clock
    (from ``max_elapsed``), propagated so the call can bound its own
    blocking primitives instead of overrunning the budget."""
    index: int
    cancelled: threading.Event
    deadline: Optional[float] = None


def hedged(call: Callable[[HedgeAttempt], object], delay: float,
           max_hedges: int = 1, *,
           max_elapsed: Optional[float] = None,
           retry_on: Tuple[Type[BaseException], ...] = (Exception,),
           clock: Callable[[], float] = time.monotonic,
           what: str = ""):
    """First-success-wins hedging (tail-latency insurance for
    idempotent calls: the serving router's replica health probes, a
    client racing two replicas).

    Launches ``call(attempt)`` in a worker thread; whenever no attempt
    has returned after another ``delay`` seconds, launches one more
    (at most ``1 + max_hedges`` in total). The first attempt to RETURN
    wins: its value is returned and every other attempt's
    ``attempt.cancelled`` event is set. An attempt raising one of
    ``retry_on`` merely drops out of the race -- and, when every
    launched attempt has failed, triggers the next hedge immediately
    rather than waiting out the stagger. The last failure re-raises
    only once ALL ``1 + max_hedges`` attempts have failed.

    ``max_elapsed`` is the total wall-clock budget across all hedges
    (the ``RetryPolicy.max_elapsed`` deadline discipline): each
    attempt sees the absolute deadline via ``attempt.deadline``, and
    on expiry everything is cancelled and TimeoutError raises.

    Loser threads are daemons: a loser ignoring its cancelled event
    can only leak until its own call returns, never hang shutdown.
    """
    if delay < 0:
        raise ValueError(f"hedge delay must be >= 0, got {delay}")
    results: "queue_mod.Queue" = queue_mod.Queue()
    start = clock()
    deadline = None if max_elapsed is None else start + max_elapsed
    attempts: list = []

    def _runner(att: HedgeAttempt):
        try:
            results.put((att, True, call(att)))
        except retry_on as e:  # a losing attempt, not a verdict
            results.put((att, False, e))
        except BaseException as e:  # noqa: BLE001 - NOT hedgeable:
            # propagate to the caller instead of vanishing in the
            # thread (which would strand the waiter forever)
            results.put((att, "fatal", e))

    def _launch():
        att = HedgeAttempt(index=len(attempts),
                           cancelled=threading.Event(),
                           deadline=deadline)
        attempts.append(att)
        threading.Thread(
            target=_runner, args=(att,), daemon=True,
            name=f"hedge-{what or 'call'}-{att.index}").start()
        if att.index:
            logger.info("Hedging %s: attempt #%d launched after "
                        "%.2fs.", what or "call", att.index,
                        clock() - start)

    _launch()
    failures = 0
    last_exc: Optional[BaseException] = None
    while True:
        now = clock()
        waits = []
        if len(attempts) < 1 + max_hedges:
            waits.append(max(0.0, start + delay * len(attempts) - now))
        if deadline is not None:
            waits.append(max(0.0, deadline - now))
        try:
            att, ok, val = results.get(
                timeout=min(waits) if waits else None)
        except queue_mod.Empty:
            if deadline is not None and clock() >= deadline:
                for a in attempts:
                    a.cancelled.set()
                raise TimeoutError(
                    f"hedged {what or 'call'}: no attempt of "
                    f"{len(attempts)} succeeded within max_elapsed="
                    f"{max_elapsed:.2f}s") from last_exc
            if (len(attempts) < 1 + max_hedges
                    and clock() >= start + delay * len(attempts)):
                _launch()
            continue
        if ok == "fatal":
            for a in attempts:
                a.cancelled.set()
            raise val
        if ok:
            for a in attempts:
                if a is not att:
                    a.cancelled.set()
            return val
        failures += 1
        last_exc = val
        if failures >= 1 + max_hedges:
            raise val
        if failures == len(attempts) and len(attempts) < 1 + max_hedges:
            _launch()  # everyone in flight failed: hedge immediately
