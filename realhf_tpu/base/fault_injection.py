"""Deterministic fault injection for the distributed runtime.

Env/config-driven faults let tier-1 tests prove every fault-tolerance
path without races: a worker crashes while handling its Nth matching
request, silently drops a reply, or delays one. Spec strings live in
``REALHF_TPU_FAULTS`` (``;``-separated)::

    kind:worker:handle:nth[:seconds]

    crash:model_worker/0:train_step:2      # raise on the 2nd train_step
    die:model_worker/0:train_step:2        # os._exit: silent death
    drop_reply:*:inference:1               # execute, never reply, once
    delay_reply:model_worker/1:*:3:2.5     # 3rd request sleeps 2.5s
    preempt:model_worker/1:*:2:5.0         # SIGTERM-equivalent notice,
                                           # 5s grace window
    corrupt_ckpt:model_worker/0:ckpt_commit:1  # flip bytes in the
                                           # just-committed shard

``crash`` raises (the worker reports an error payload and exits with
ERROR status -- the attributed-error path); ``die`` hard-exits the
process mid-request with no goodbye (the heartbeat-loss path the
watchdog must catch); ``preempt`` delivers a preemption notice with a
grace window (``seconds``) -- the worker announces it, finishes
in-flight work, runs its emergency hooks, and exits PREEMPTED, the
elastic-degradation path (docs/distributed.md); ``corrupt_ckpt``
flips bytes in a shard of the checkpoint that was just committed
(``ckpt_manager.CheckpointManager`` feeds it ``ckpt_commit`` events),
proving the checksum-verify + fallback-to-previous-manifest load
path.

``worker`` and ``handle`` are fnmatch patterns (``*`` = any). Faults
are one-shot: each fires exactly once per matching spec. For
crash-then-recover tests the injector persists fired fault ids to
``REALHF_TPU_FAULTS_STATE`` (a plain text file, one id per line) so a
relaunched worker does not re-fire the same fault and crash-loop.
"""

import dataclasses
import fnmatch
import os
from typing import Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("fault_injection")

KINDS = ("crash", "die", "drop_reply", "delay_reply", "preempt",
         "corrupt_ckpt")

FAULTS_ENV = "REALHF_TPU_FAULTS"
FAULTS_STATE_ENV = "REALHF_TPU_FAULTS_STATE"


class FaultInjected(RuntimeError):
    """Raised by a worker executing an injected ``crash`` fault."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str            # one of KINDS
    worker: str = "*"    # fnmatch pattern on the worker name
    handle: str = "*"    # fnmatch pattern on the request handle_name
    nth: int = 1         # fire on the Nth matching event (1-based)
    seconds: float = 0.0  # delay_reply sleep / preempt grace window

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"Unknown fault kind {self.kind!r} "
                             f"(known: {KINDS})")
        if self.nth < 1:
            raise ValueError(f"Fault nth must be >= 1, got {self.nth}")

    @property
    def fault_id(self) -> str:
        return (f"{self.kind}:{self.worker}:{self.handle}:{self.nth}"
                f":{self.seconds}")

    def matches(self, worker: str, handle: str) -> bool:
        return (fnmatch.fnmatchcase(worker, self.worker)
                and fnmatch.fnmatchcase(handle, self.handle))


def parse_faults(spec: str) -> List[FaultSpec]:
    """Parse a ``;``-separated fault spec string (see module doc)."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        # worker names contain "/" but never ":"; rejoin is not needed
        if len(fields) < 4 or len(fields) > 5:
            raise ValueError(
                f"Bad fault spec {part!r}: want "
                "kind:worker:handle:nth[:seconds]")
        kind, worker, handle, nth = fields[:4]
        seconds = float(fields[4]) if len(fields) == 5 else 0.0
        out.append(FaultSpec(kind=kind, worker=worker, handle=handle,
                             nth=int(nth), seconds=seconds))
    return out


def flip_bytes(path: str, n: int = 16, offset: int = 0):
    """In-place byte corruption of a file (the ``corrupt_ckpt``
    payload): XOR-flips ``n`` bytes starting at ``offset``. The file
    keeps its size -- a durability layer relying on size alone would
    miss this; checksums must catch it."""
    size = os.path.getsize(path)
    if size == 0:
        return
    offset = min(offset, size - 1)
    n = min(n, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(n)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
        f.flush()
        os.fsync(f.fileno())


class FaultInjector:
    """Counts (worker, handle) events against each spec and reports
    which fault (if any) an event should trigger. Each spec fires at
    most once per injector lifetime AND -- when ``state_path`` is set
    -- at most once across process relaunches."""

    def __init__(self, specs: List[FaultSpec],
                 state_path: Optional[str] = None):
        self.specs = list(specs)
        self.state_path = state_path
        self._counts: Dict[str, int] = {s.fault_id: 0 for s in self.specs}
        self._fired = self._load_state()

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultInjector"]:
        env = os.environ if env is None else env
        raw = env.get(FAULTS_ENV)
        if not raw:
            return None
        return cls(parse_faults(raw), state_path=env.get(FAULTS_STATE_ENV))

    def _load_state(self) -> set:
        if not self.state_path or not os.path.isfile(self.state_path):
            return set()
        with open(self.state_path, "r") as f:
            return {line.strip() for line in f if line.strip()}

    def _record_fired(self, fid: str):
        self._fired.add(fid)
        if self.state_path:
            # append-only: concurrent workers each add their own lines
            with open(self.state_path, "a") as f:
                f.write(fid + "\n")
                f.flush()
                os.fsync(f.fileno())

    def on_event(self, worker: str, handle: str) -> Optional[FaultSpec]:
        """Record one (worker, handle) event; return the fault to
        execute now, or None. Counters advance per matching spec, so
        ``nth`` is deterministic regardless of other specs firing."""
        for s in self.specs:
            if not s.matches(worker, handle):
                continue
            self._counts[s.fault_id] += 1
            if (self._counts[s.fault_id] == s.nth
                    and s.fault_id not in self._fired):
                self._record_fired(s.fault_id)
                logger.warning("Fault injection firing %s for %s/%s "
                               "(event %d).", s.fault_id, worker, handle,
                               s.nth)
                return s
        return None
