"""Deterministic fault injection for the distributed runtime.

Env/config-driven faults let tier-1 tests prove every fault-tolerance
path without races: a worker crashes while handling its Nth matching
request, silently drops a reply, or delays one. Spec strings live in
``REALHF_TPU_FAULTS`` (``;``-separated)::

    kind:worker:handle:nth[:seconds]

    crash:model_worker/0:train_step:2      # raise on the 2nd train_step
    die:model_worker/0:train_step:2        # os._exit: silent death
    drop_reply:*:inference:1               # execute, never reply, once
    delay_reply:model_worker/1:*:3:2.5     # 3rd request sleeps 2.5s
    preempt:model_worker/1:*:2:5.0         # SIGTERM-equivalent notice,
                                           # 5s grace window
    corrupt_ckpt:model_worker/0:ckpt_commit:1  # flip bytes in the
                                           # just-committed shard

``crash`` raises (the worker reports an error payload and exits with
ERROR status -- the attributed-error path); ``die`` hard-exits the
process mid-request with no goodbye (the heartbeat-loss path the
watchdog must catch); ``preempt`` delivers a preemption notice with a
grace window (``seconds``) -- the worker announces it, finishes
in-flight work, runs its emergency hooks, and exits PREEMPTED, the
elastic-degradation path (docs/distributed.md); ``corrupt_ckpt``
flips bytes in a shard of the checkpoint that was just committed
(``ckpt_manager.CheckpointManager`` feeds it ``ckpt_commit`` events),
proving the checksum-verify + fallback-to-previous-manifest load
path.

Network chaos (PR 7, docs/serving.md "Chaos drills") rides on the
same spec grammar but fires at the ZMQ send/recv shims
(``serving/server.py``, ``serving/router.py``,
``system/request_reply_stream.py``) instead of the request handler::

    net_drop:gen_server/1:send\\:done:1     # discard ONE outgoing done
    net_delay:gen_server/0:recv:2:0.5      # 2nd inbound msg +0.5s
    partition:gen_server/2:*:1:6.0         # 6s window: ALL of this
                                           # worker's traffic drops AND
                                           # its name_resolve lease
                                           # renewals fail (visibility
                                           # partition)

For net faults ``handle`` matches a CHANNEL string (``send:<kind>``,
``recv``, ``post:<handle_name>``, ``reply:<handle_name>``) rather
than an MFC handle. ``partition`` opens a time window on its matching
worker; the window outlives the one-shot firing.

``worker`` and ``handle`` are fnmatch patterns (``*`` = any). Faults
are one-shot: each fires exactly once per matching spec. For
crash-then-recover tests the injector persists fired fault ids to
``REALHF_TPU_FAULTS_STATE`` (a plain text file, one id per line) so a
relaunched worker does not re-fire the same fault and crash-loop;
``net_*`` specs share the same state file, so a recovered process
does not re-drop the same message.
"""

import dataclasses
import fnmatch
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("fault_injection")

#: network-level kinds, executed by the wire shims (NetChaos) -- never
#: by a worker's request handler
NET_KINDS = ("net_drop", "net_delay", "partition")

KINDS = ("crash", "die", "drop_reply", "delay_reply", "preempt",
         "corrupt_ckpt") + NET_KINDS

FAULTS_ENV = "REALHF_TPU_FAULTS"
FAULTS_STATE_ENV = "REALHF_TPU_FAULTS_STATE"


class FaultInjected(RuntimeError):
    """Raised by a worker executing an injected ``crash`` fault."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str            # one of KINDS
    worker: str = "*"    # fnmatch pattern on the worker name
    handle: str = "*"    # fnmatch pattern on the request handle_name
    nth: int = 1         # fire on the Nth matching event (1-based)
    seconds: float = 0.0  # delay_reply sleep / preempt grace window

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"Unknown fault kind {self.kind!r} "
                             f"(known: {KINDS})")
        if self.nth < 1:
            raise ValueError(f"Fault nth must be >= 1, got {self.nth}")
        # net kinds get actionable validation: a silently-zero window
        # or delay would make a chaos drill pass without testing
        # anything
        if self.kind in ("net_delay", "partition") and self.seconds <= 0:
            what = ("delay" if self.kind == "net_delay"
                    else "partition window length")
            raise ValueError(
                f"Fault kind {self.kind!r} needs a positive seconds "
                f"field (the {what}): write "
                f"{self.kind}:{self.worker}:{self.handle}:{self.nth}"
                f":<seconds>, got seconds={self.seconds}")
        if self.kind == "net_drop" and self.seconds:
            raise ValueError(
                "Fault kind 'net_drop' discards exactly one matching "
                "message and takes no seconds field (got "
                f"seconds={self.seconds}); use net_delay for delays "
                "or partition for time windows")

    @property
    def fault_id(self) -> str:
        return (f"{self.kind}:{self.worker}:{self.handle}:{self.nth}"
                f":{self.seconds}")

    def matches(self, worker: str, handle: str) -> bool:
        return (fnmatch.fnmatchcase(worker, self.worker)
                and fnmatch.fnmatchcase(handle, self.handle))


def parse_faults(spec: str) -> List[FaultSpec]:
    """Parse a ``;``-separated fault spec string (see module doc)."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        # worker names contain "/" but never ":"; rejoin is not needed
        if len(fields) < 4 or len(fields) > 5:
            raise ValueError(
                f"Bad fault spec {part!r}: want "
                "kind:worker:handle:nth[:seconds]")
        kind, worker, handle, nth = fields[:4]
        seconds = float(fields[4]) if len(fields) == 5 else 0.0
        out.append(FaultSpec(kind=kind, worker=worker, handle=handle,
                             nth=int(nth), seconds=seconds))
    return out


def flip_bytes(path: str, n: int = 16, offset: int = 0):
    """In-place byte corruption of a file (the ``corrupt_ckpt``
    payload): XOR-flips ``n`` bytes starting at ``offset``. The file
    keeps its size -- a durability layer relying on size alone would
    miss this; checksums must catch it."""
    size = os.path.getsize(path)
    if size == 0:
        return
    offset = min(offset, size - 1)
    n = min(n, size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(n)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
        f.flush()
        os.fsync(f.fileno())


class FaultInjector:
    """Counts (worker, handle) events against each spec and reports
    which fault (if any) an event should trigger. Each spec fires at
    most once per injector lifetime AND -- when ``state_path`` is set
    -- at most once across process relaunches."""

    def __init__(self, specs: List[FaultSpec],
                 state_path: Optional[str] = None):
        self.specs = list(specs)
        self.state_path = state_path
        self._counts: Dict[str, int] = {s.fault_id: 0 for s in self.specs}
        self._fired = self._load_state()

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultInjector"]:
        """Injector over the request-handler kinds. ``net_*`` specs in
        the same env var are EXCLUDED here -- they execute at the wire
        shims (:func:`default_net_chaos`), not in a request handler
        (a ``net_drop:*:*:1`` spec must never be consumed -- and
        silently ignored -- by a model worker's Nth train_step)."""
        env = os.environ if env is None else env
        raw = env.get(FAULTS_ENV)
        if not raw:
            return None
        specs = [s for s in parse_faults(raw) if s.kind not in NET_KINDS]
        if not specs:
            return None
        return cls(specs, state_path=env.get(FAULTS_STATE_ENV))

    def _load_state(self) -> set:
        if not self.state_path or not os.path.isfile(self.state_path):
            return set()
        with open(self.state_path, "r") as f:
            return {line.strip() for line in f if line.strip()}

    def _record_fired(self, fid: str):
        self._fired.add(fid)
        if self.state_path:
            # append-only: concurrent workers each add their own lines
            with open(self.state_path, "a") as f:
                f.write(fid + "\n")
                f.flush()
                os.fsync(f.fileno())

    def on_event(self, worker: str, handle: str) -> Optional[FaultSpec]:
        """Record one (worker, handle) event; return the fault to
        execute now, or None. Counters advance per matching spec, so
        ``nth`` is deterministic regardless of other specs firing."""
        for s in self.specs:
            if not s.matches(worker, handle):
                continue
            self._counts[s.fault_id] += 1
            if (self._counts[s.fault_id] == s.nth
                    and s.fault_id not in self._fired):
                self._record_fired(s.fault_id)
                logger.warning("Fault injection firing %s for %s/%s "
                               "(event %d).", s.fault_id, worker, handle,
                               s.nth)
                return s
        return None


class NetChaos:
    """Network-level chaos, applied at the ZMQ send/recv shims.

    One instance per process (or per in-process drill fleet); the
    shims call :meth:`check` for every message with the local worker
    name and a channel string. Deterministic: faults fire by event
    COUNT (the spec's ``nth``), not wall time -- only partition window
    LENGTH uses the clock, which is injectable.

    - ``net_drop``: the nth matching message is discarded (one-shot).
    - ``net_delay``: the nth matching message is delivered after an
      inline sleep of ``seconds`` (one-shot).
    - ``partition``: the nth matching event opens a window of
      ``seconds`` during which EVERY message of any matching worker is
      dropped and :meth:`partitioned` reports True -- the lease-renewal
      paths consult it, so a partitioned replica also loses
      name_resolve visibility and its fleet lease expires.

    Thread-safe: shims in the serve loop and a worker's command thread
    may consult it concurrently.
    """

    def __init__(self, specs: List[FaultSpec],
                 state_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        specs = [s for s in specs if s.kind in NET_KINDS]
        self._inj = FaultInjector(specs, state_path=state_path)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        #: active partition windows: (spec, end-time)
        self._windows: List[tuple] = []
        self.stats = dict(dropped=0, delayed=0, partitions=0)

    @classmethod
    def from_env(cls, env=None, **kwargs) -> Optional["NetChaos"]:
        env = os.environ if env is None else env
        raw = env.get(FAULTS_ENV)
        if not raw:
            return None
        specs = [s for s in parse_faults(raw) if s.kind in NET_KINDS]
        if not specs:
            return None
        return cls(specs, state_path=env.get(FAULTS_STATE_ENV),
                   **kwargs)

    # ------------------------------------------------------------------
    def _prune_windows(self, now: float):
        """Caller holds the lock."""
        self._windows = [(s, e) for (s, e) in self._windows if e > now]

    def open_partition(self, worker_pattern: str, seconds: float):
        """Programmatically open a partition window (the chaos-drill
        runner schedules partitions at exact drill ticks this way;
        env-driven runs open them via ``partition`` specs)."""
        with self._lock:
            self.stats["partitions"] += 1
            self._windows.append((
                FaultSpec(kind="partition", worker=worker_pattern,
                          seconds=seconds),
                self._clock() + seconds))
        logger.warning("Partition opened for worker %r: %.1fs.",
                       worker_pattern, seconds)

    def partitioned(self, worker: str) -> bool:
        """Is ``worker`` inside an active partition window? Gates
        name_resolve visibility (lease renewal/registration) as well
        as the socket shims."""
        with self._lock:
            self._prune_windows(self._clock())
            return any(fnmatch.fnmatchcase(worker, s.worker)
                       for s, _ in self._windows)

    def check(self, worker: str, channel: str) -> Optional[str]:
        """Consult chaos for one message on (worker, channel).
        Returns ``"drop"`` when the shim must discard the message;
        sleeps inline for a firing ``net_delay``; None = deliver."""
        delay = None
        with self._lock:
            now = self._clock()
            self._prune_windows(now)
            spec = self._inj.on_event(worker, channel)
            if spec is not None:
                if spec.kind == "net_drop":
                    self.stats["dropped"] += 1
                    return "drop"
                if spec.kind == "net_delay":
                    self.stats["delayed"] += 1
                    delay = spec.seconds
                elif spec.kind == "partition":
                    self.stats["partitions"] += 1
                    self._windows.append((spec, now + spec.seconds))
            # an active window drops ALL of a matching worker's
            # traffic, including the very message that opened it
            for s, _ in self._windows:
                if fnmatch.fnmatchcase(worker, s.worker):
                    self.stats["dropped"] += 1
                    return "drop"
        if delay is not None:
            # sleep OUTSIDE the lock: delaying one message must not
            # stall other threads' chaos checks
            self._sleep(delay)
        return None


# Process-wide NetChaos singleton, lazily built from REALHF_TPU_FAULTS
# (the wire shims consult it so env-driven chaos needs no plumbing);
# tests and in-process drills install their own via set_net_chaos.
_net_chaos: Optional[NetChaos] = None
_net_chaos_loaded = False
_net_chaos_lock = threading.Lock()


def default_net_chaos() -> Optional[NetChaos]:
    global _net_chaos, _net_chaos_loaded
    with _net_chaos_lock:
        if not _net_chaos_loaded:
            _net_chaos = NetChaos.from_env()
            _net_chaos_loaded = True
        return _net_chaos


def set_net_chaos(chaos: Optional[NetChaos]) -> Optional[NetChaos]:
    """Install (or clear, with None) the process-wide NetChaos;
    returns the previous one so tests can restore it."""
    global _net_chaos, _net_chaos_loaded
    with _net_chaos_lock:
        prev = _net_chaos if _net_chaos_loaded else None
        _net_chaos = chaos
        _net_chaos_loaded = True
        return prev
