"""FLOPs accounting, timing marks, and device memory statistics.

Parity with reference ``realhf/base/monitor.py``: the FLOP formulas
(:277-353) used by the master to log per-step TFLOP/s, a lightweight
span-timing facility (the reference uses CUDA events; here spans wrap
blocking host calls since XLA dispatch is async -- callers must
`jax.block_until_ready` the result inside the span for true timings),
and accelerator memory stats via JAX device APIs.
"""

import os
import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Dict, List


def attn_flops(q_len: int, kv_len: int, n_q_heads: int, head_dim: int,
               causal: bool = True) -> int:
    """FLOPs of QK^T + PV for one sequence (forward)."""
    full = 4 * q_len * kv_len * n_q_heads * head_dim
    return full // 2 if causal else full


def transformer_forward_flops(
    n_layers: int,
    hidden_dim: int,
    n_q_heads: int,
    n_kv_heads: int,
    head_dim: int,
    intermediate_dim: int,
    vocab_size: int,
    seqlens: List[int],
    gated_mlp: bool = True,
) -> int:
    """Dense-transformer forward FLOPs over packed sequences.

    Mirrors the accounting of reference ``base/monitor.py:277-353``
    (per-projection matmul FLOPs + causal attention + head).
    """
    T = sum(seqlens)
    sum_sq = sum(l * l for l in seqlens)
    qkv = 2 * T * hidden_dim * (n_q_heads + 2 * n_kv_heads) * head_dim
    attn_o = 2 * T * n_q_heads * head_dim * hidden_dim
    attn = 2 * sum_sq * n_q_heads * head_dim  # QK^T + PV with causal 1/2 factor
    n_mlp_mats = 3 if gated_mlp else 2
    mlp = 2 * T * hidden_dim * intermediate_dim * n_mlp_mats
    per_layer = qkv + attn_o + attn + mlp
    head = 2 * T * hidden_dim * vocab_size
    return n_layers * per_layer + head


def transformer_train_flops(**kw) -> int:
    """Backward is ~2x forward; total train step ~3x forward."""
    return 3 * transformer_forward_flops(**kw)


def generation_flops(
    n_layers: int,
    hidden_dim: int,
    n_q_heads: int,
    n_kv_heads: int,
    head_dim: int,
    intermediate_dim: int,
    vocab_size: int,
    prompt_lens: List[int],
    gen_len: int,
    gated_mlp: bool = True,
) -> int:
    """Prefill + decode FLOPs for a generation MFC."""
    prefill = transformer_forward_flops(
        n_layers=n_layers, hidden_dim=hidden_dim, n_q_heads=n_q_heads,
        n_kv_heads=n_kv_heads, head_dim=head_dim,
        intermediate_dim=intermediate_dim, vocab_size=vocab_size,
        seqlens=prompt_lens, gated_mlp=gated_mlp)
    decode = 0
    for pl in prompt_lens:
        # Each decoded token attends to the whole prefix.
        dense = transformer_forward_flops(
            n_layers=n_layers, hidden_dim=hidden_dim, n_q_heads=n_q_heads,
            n_kv_heads=n_kv_heads, head_dim=head_dim,
            intermediate_dim=intermediate_dim, vocab_size=vocab_size,
            seqlens=[1] * gen_len, gated_mlp=gated_mlp)
        kv_attn = sum(2 * 2 * (pl + t) * n_q_heads * head_dim
                      for t in range(gen_len))
        decode += dense + kv_attn
    return prefill + decode


@dataclasses.dataclass
class TimeMark:
    name: str
    start: float
    end: float

    @property
    def elapsed(self):
        return self.end - self.start


class TimeMarkDB:
    """Process-local span recorder (reference cuda_tmark, :375-427)."""

    def __init__(self):
        self.marks: Dict[str, List[TimeMark]] = defaultdict(list)

    @contextlib.contextmanager
    def mark(self, name: str):
        st = time.monotonic()
        try:
            yield
        finally:
            self.marks[name].append(TimeMark(name, st, time.monotonic()))

    def total(self, name: str) -> float:
        return sum(m.elapsed for m in self.marks[name])

    def summary(self) -> Dict[str, float]:
        return {k: self.total(k) for k in self.marks}

    def clear(self):
        self.marks.clear()


_tmark_db = TimeMarkDB()


def tmark(name: str):
    return _tmark_db.mark(name)


def tmark_db() -> TimeMarkDB:
    return _tmark_db


def device_memory_stats(device=None) -> Dict[str, int]:
    """Per-chip HBM stats (replaces nvml polling, reference :255)."""
    import jax
    d = device or jax.local_devices()[0]
    stats = d.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
    }


# ----------------------------------------------------------------------
# Profiling / tracing (reference model_worker.py:664-721 per-MFC
# profiler + REAL_DUMP_TRACE/REAL_DUMP_MEMORY, monitor.py:375-427)
# ----------------------------------------------------------------------
DUMP_TRACE_ENV = "REALHF_TPU_DUMP_TRACE"
DUMP_MEMORY_ENV = "REALHF_TPU_DUMP_MEMORY"


def trace_dir(sub: str = "") -> str:
    from realhf_tpu.base import constants
    d = os.path.join(constants.run_log_path(), "trace", sub)
    os.makedirs(d, exist_ok=True)
    return d


@contextlib.contextmanager
def mfc_profile_region(name: str):
    """Wrap one MFC execution:

    - always: a wall-clock span in the TimeMarkDB and an XLA trace
      annotation (shows up as a named region in any enclosing profile);
    - REALHF_TPU_DUMP_TRACE=1: a full ``jax.profiler.trace`` dumped to
      ``{log}/trace/{name}/`` (TensorBoard/perfetto-readable -- the
      reference's per-MFC chrome traces);
    - REALHF_TPU_DUMP_MEMORY=1: a device-memory profile (pprof) saved
      after the MFC completes (the reference's CUDA memory snapshots).
    """
    import jax

    dump_trace = os.environ.get(DUMP_TRACE_ENV, "") == "1"
    dump_memory = os.environ.get(DUMP_MEMORY_ENV, "") == "1"
    safe = name.replace("/", "_")
    ctx = contextlib.ExitStack()
    with ctx:
        if dump_trace:
            ctx.enter_context(jax.profiler.trace(trace_dir(safe)))
        ctx.enter_context(jax.profiler.TraceAnnotation(f"mfc:{name}"))
        ctx.enter_context(_tmark_db.mark(f"mfc/{name}"))
        yield
    if dump_memory:
        path = os.path.join(trace_dir(safe),
                            f"memory_{int(time.time())}.prof")
        try:
            jax.profiler.save_device_memory_profile(path)
        except Exception:  # noqa: BLE001 - profiling must never kill a run
            pass


# ----------------------------------------------------------------------
# Kernel-time classification from profiler traces (reference
# kernelStatFromTrace + CUDAKernelTimeStat, base/monitor.py:517-699)
# ----------------------------------------------------------------------
#: substring -> category, first match wins (XLA kernel naming)
KERNEL_CATEGORIES = (
    ("all-reduce", "comm"), ("all-gather", "comm"),
    ("reduce-scatter", "comm"), ("all-to-all", "comm"),
    ("collective", "comm"), ("permute", "comm"), ("send", "comm"),
    ("recv", "comm"),
    ("copy", "mem"), ("transpose", "mem"), ("bitcast", "mem"),
    ("reshape", "mem"), ("broadcast", "mem"), ("slice", "mem"),
    ("concatenate", "mem"), ("pad", "mem"),
    ("fusion", "compute"), ("dot", "compute"), ("conv", "compute"),
    ("matmul", "compute"), ("custom-call", "compute"),
    ("scatter", "compute"), ("gather", "compute"),
    ("reduce", "compute"), ("rng", "compute"), ("cholesky", "compute"),
    ("sort", "compute"), ("iota", "compute"),
)


def classify_kernel(name: str) -> str:
    n = name.lower()
    for sub, cat in KERNEL_CATEGORIES:
        if sub in n:
            return cat
    return "misc"


def kernel_stats_from_trace(trace_path: str) -> Dict[str, float]:
    """Aggregate device-kernel time by category from a profiler dump.

    ``trace_path`` is a chrome-trace ``*.trace.json(.gz)`` file or a
    directory (the newest trace under it is used -- e.g. the dir that
    ``mfc_profile_region`` wrote with REALHF_TPU_DUMP_TRACE=1).
    Returns seconds per category (compute/comm/mem/misc) plus
    ``total_busy`` and ``span`` (first-event to last-event extent of
    the device tracks), the inputs of the reference's
    compute/comm/idle breakdown.
    """
    import glob
    import gzip
    import json

    if os.path.isdir(trace_path):
        cands = sorted(glob.glob(
            os.path.join(trace_path, "**", "*.trace.json.gz"),
            recursive=True))
        if not cands:
            raise FileNotFoundError(
                f"No *.trace.json.gz under {trace_path}")
        trace_path = cands[-1]
    opener = gzip.open if trace_path.endswith(".gz") else open
    with opener(trace_path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])

    # pid -> process name from metadata events; device tracks only
    proc_names = {e.get("pid"): str(e.get("args", {}).get("name", ""))
                  for e in events
                  if e.get("ph") == "M" and e.get("name") == "process_name"}

    def is_device(pid) -> bool:
        n = proc_names.get(pid, "").lower()
        return any(s in n for s in ("tpu", "gpu", "/device", "xla"))

    out = {"compute": 0.0, "comm": 0.0, "mem": 0.0, "misc": 0.0}
    t_lo, t_hi = None, None
    for e in events:
        if e.get("ph") != "X" or not is_device(e.get("pid")):
            continue
        dur = float(e.get("dur", 0.0)) * 1e-6  # us -> s
        ts = float(e.get("ts", 0.0)) * 1e-6
        out[classify_kernel(str(e.get("name", "")))] += dur
        t_lo = ts if t_lo is None else min(t_lo, ts)
        t_hi = ts + dur if t_hi is None else max(t_hi, ts + dur)
    out["total_busy"] = sum(
        out[k] for k in ("compute", "comm", "mem", "misc"))
    out["span"] = (t_hi - t_lo) if t_lo is not None else 0.0
    return out
