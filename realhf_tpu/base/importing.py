"""User-code injection (reference ``base/importing.py`` +
``apps/remote.py:25-45`` _patch_external_impl): the env var
``REALHF_TPU_PACKAGE_PATH`` names one or more Python files or package
directories (colon-separated) imported at startup by the quickstart
CLI and every spawned worker, so custom datasets / interfaces /
experiments register themselves into the framework registries without
forking the repo."""

import importlib.util
import os
import sys
from typing import List

from realhf_tpu.base import logging

logger = logging.getLogger("importing")

PACKAGE_PATH_ENV = "REALHF_TPU_PACKAGE_PATH"


def import_module_from_path(path: str):
    """Import a .py file or a package directory by filesystem path."""
    path = os.path.abspath(path)
    if os.path.isdir(path):
        init = os.path.join(path, "__init__.py")
        if not os.path.exists(init):
            raise FileNotFoundError(
                f"{path} is a directory without __init__.py")
        base = os.path.basename(path.rstrip("/"))
        target = init
    else:
        base = os.path.splitext(os.path.basename(path))[0]
        target = path
    # mangled module name: a user file called logging.py/redis.py must
    # not shadow stdlib/installed modules in sys.modules
    name = f"realhf_tpu_usercode_{base}"
    spec = importlib.util.spec_from_file_location(name, target)
    if spec is None or spec.loader is None:
        raise ImportError(f"Cannot import user code from {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    logger.info("Imported user code %s from %s", name, path)
    return mod


def import_usercode() -> List[str]:
    """Import everything named by REALHF_TPU_PACKAGE_PATH; returns the
    list of imported paths (empty when unset)."""
    raw = os.environ.get(PACKAGE_PATH_ENV, "")
    out = []
    for path in filter(None, raw.split(":")):
        import_module_from_path(path)
        out.append(path)
    return out
