"""Logging setup with colored console output and benchmark loggers.

Behavioral parity with reference ``realhf/base/logging.py``: named
loggers, a separate "benchmark" log level namespace used by the master
worker for per-step metrics, and environment-controlled verbosity.
No external colorlog dependency; ANSI codes are emitted directly when
the stream is a TTY.
"""

import logging as _logging
import os
import sys
from typing import Optional

LOG_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",  # cyan
    "INFO": "\033[32m",  # green
    "WARNING": "\033[33m",  # yellow
    "ERROR": "\033[31m",  # red
    "CRITICAL": "\033[41m",  # red background
}
_RESET = "\033[0m"


class _ColorFormatter(_logging.Formatter):

    def format(self, record):
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname)
            if color:
                msg = f"{color}{msg}{_RESET}"
        return msg


_configured = False


def _configure_root():
    global _configured
    if _configured:
        return
    level = os.environ.get("REALHF_TPU_LOG_LEVEL", "INFO").upper()
    handler = _logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(_ColorFormatter(fmt=LOG_FORMAT, datefmt=DATE_FORMAT))
    root = _logging.getLogger("realhf_tpu")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _configured = True


def getLogger(name: Optional[str] = None,
              type_: Optional[str] = None) -> _logging.Logger:
    """Get a logger under the framework namespace.

    ``type_`` may be "benchmark" or "system"; benchmark loggers can be
    silenced separately via REALHF_TPU_SILENCE_BENCHMARK=1 (mirrors the
    reference's benchmark logger split).
    """
    _configure_root()
    if name is None:
        return _logging.getLogger("realhf_tpu")
    logger = _logging.getLogger(f"realhf_tpu.{name}")
    if type_ == "benchmark" and os.environ.get("REALHF_TPU_SILENCE_BENCHMARK") == "1":
        logger.setLevel(_logging.WARNING)
    return logger
