"""Key schema for the distributed name-resolve store.

Parity with reference ``realhf/base/names.py:7-59``: a single place
defining the hierarchical key layout so that master, workers, and the
launcher agree on rendezvous paths.
"""

USER_NAMESPACE = "realhf_tpu"


def _root(experiment_name: str, trial_name: str) -> str:
    return f"{USER_NAMESPACE}/{experiment_name}/{trial_name}"


def trial_registry(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/trial_registry"


def trial_root(experiment_name: str, trial_name: str) -> str:
    return _root(experiment_name, trial_name)


def worker_status(experiment_name: str, trial_name: str, worker_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/status/{worker_name}"


def worker_root(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/status/"


def worker_key(experiment_name: str, trial_name: str, key: str) -> str:
    return f"{_root(experiment_name, trial_name)}/worker_key/{key}"


def worker_heartbeat(experiment_name: str, trial_name: str,
                     worker_name: str) -> str:
    """Liveness beacon: the worker re-publishes a wall-clock timestamp
    here every heartbeat interval; the watchdog marks it LOST when the
    entry expires (TTL backends) or the timestamp goes stale."""
    return f"{_root(experiment_name, trial_name)}/heartbeat/{worker_name}"


def heartbeat_root(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/heartbeat/"


def worker_host(experiment_name: str, trial_name: str,
                worker_name: str) -> str:
    """Host-domain membership: each worker publishes the pod host id
    it runs on (``REALHF_TPU_HOST_ID``, injected by the pod manifest /
    MultiHostLocalScheduler) so the master-side watchdog can aggregate
    per-host -- a whole host going stale is ONE ``HOST_LOST``, not N
    independent worker losses."""
    return f"{_root(experiment_name, trial_name)}/host/{worker_name}"


def host_root(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/host/"


def telemetry(experiment_name: str, trial_name: str,
              worker_name: str) -> str:
    """HTTP telemetry endpoint: each worker (and the inline runner)
    publishes the ``host:port`` its ``TelemetryServer`` bound
    (``obs/http.py`` -- /metrics, /healthz, /flight, /statusz) so the
    pod controller can emit LIVE per-worker Prometheus scrape targets
    instead of dead per-host ports (``system/pod.py``)."""
    return f"{_root(experiment_name, trial_name)}/telemetry/{worker_name}"


def telemetry_root(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/telemetry/"


def train_progress(experiment_name: str, trial_name: str) -> str:
    """Master-published global step (updated per finished batch): the
    pod controller / harnesses can watch trial progress without a
    control-panel socket."""
    return f"{_root(experiment_name, trial_name)}/train_progress"


def worker_preempt(experiment_name: str, trial_name: str,
                   worker_name: str) -> str:
    """Preemption notice: the worker publishes ``"<ts>:<grace>"``
    (wall-clock notice time + grace-window seconds) when it receives a
    SIGTERM-equivalent preemption signal, then drains and exits
    PREEMPTED within the window. The master reads it to trigger
    elastic degradation BEFORE the heartbeat goes stale; a relaunched
    worker clears its own stale notice at startup."""
    return f"{_root(experiment_name, trial_name)}/preempt/{worker_name}"


def preempt_root(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/preempt/"


def request_reply_stream(experiment_name: str, trial_name: str, stream_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/request_reply_stream/{stream_name}"


def distributed_peer(experiment_name: str, trial_name: str, model_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/distributed_peer/{model_name}"


def distributed_master(experiment_name: str, trial_name: str, model_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/distributed_master/{model_name}"


def model_version(experiment_name: str, trial_name: str, model_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/model_version/{model_name}"


def experiment_status(experiment_name: str, trial_name: str) -> str:
    return f"{_root(experiment_name, trial_name)}/experiment_status"
