"""Deterministic seeding (reference ``realhf/base/seeding.py``).

JAX is functional: randomness flows through explicit `jax.random` keys.
This module derives per-component keys from one experiment-level seed so
every worker/model derives reproducible, non-colliding streams.
"""

import hashlib
import random

import numpy as np

_base_seed = None
_shared_seed = None


def set_random_seed(seed: int):
    global _base_seed
    _base_seed = int(seed)
    random.seed(seed)
    np.random.seed(seed % (2 ** 32))


def set_shared_seed(seed: int):
    """Experiment-level seed shared by EVERY worker process.

    Worker processes offset their ambient seed by worker index (so
    dataset shuffles etc. differ), but randomness feeding SPMD
    computations (generation sampling keys) must be identical on all
    members of a multi-process mesh -- it derives from this value."""
    global _shared_seed
    _shared_seed = int(seed)


def get_shared_seed() -> int:
    """The experiment seed if set, else the ambient process seed."""
    if _shared_seed is not None:
        return _shared_seed
    return get_seed()


def get_seed() -> int:
    if _base_seed is None:
        raise RuntimeError("Seed not set; call set_random_seed first.")
    return _base_seed


def derive_seed(*names: str) -> int:
    """Derive a stable 63-bit seed for a named component, e.g.
    ``derive_seed('model_worker', 'actor', '3')``."""
    h = hashlib.sha256(("/".join(map(str, names)) + f"@{get_seed()}").encode())
    return int.from_bytes(h.digest()[:8], "little") & ((1 << 63) - 1)


def derive_key(*names: str):
    import jax
    return jax.random.PRNGKey(derive_seed(*names) % (2 ** 31))


def derive_seed_from(base_seed: int, *names: str) -> int:
    """Like derive_seed but from an EXPLICIT base seed instead of the
    process-global one. Use for values that must agree across worker
    processes (e.g. model init on a multi-host mesh) even though each
    worker's ambient seed is offset by its index."""
    h = hashlib.sha256(
        ("/".join(map(str, names)) + f"@{int(base_seed)}").encode())
    return int.from_bytes(h.digest()[:8], "little") & ((1 << 63) - 1)


def derive_key_from(base_seed: int, *names: str):
    import jax
    return jax.random.PRNGKey(
        derive_seed_from(base_seed, *names) % (2 ** 31))
