"""Deterministic seeding (reference ``realhf/base/seeding.py``).

JAX is functional: randomness flows through explicit `jax.random` keys.
This module derives per-component keys from one experiment-level seed so
every worker/model derives reproducible, non-colliding streams.
"""

import hashlib
import random

import numpy as np

_base_seed = None


def set_random_seed(seed: int):
    global _base_seed
    _base_seed = int(seed)
    random.seed(seed)
    np.random.seed(seed % (2 ** 32))


def get_seed() -> int:
    if _base_seed is None:
        raise RuntimeError("Seed not set; call set_random_seed first.")
    return _base_seed


def derive_seed(*names: str) -> int:
    """Derive a stable 63-bit seed for a named component, e.g.
    ``derive_seed('model_worker', 'actor', '3')``."""
    h = hashlib.sha256(("/".join(map(str, names)) + f"@{get_seed()}").encode())
    return int.from_bytes(h.digest()[:8], "little") & ((1 << 63) - 1)


def derive_key(*names: str):
    import jax
    return jax.random.PRNGKey(derive_seed(*names) % (2 ** 31))
