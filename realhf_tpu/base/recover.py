"""Recovery bookkeeping (reference ``realhf/base/recover.py``).

The master dumps a ``RecoverInfo`` (schema-versioned) so a restarted
run resumes instead of starting over: epoch/step counters, the data
ids already consumed this epoch, the SequenceBuffer's in-flight state,
and dataloader epoch accounting. Model weights are recovered from the
latest checkpoint separately.

Dumps are atomic (tmp + fsync + rename) and loads are tolerant: a
corrupt, truncated, or future-versioned file degrades to a fresh
start (``load_safe`` returns None) rather than crashing the resumed
trial. Pre-versioning pickles (schema v1, counters + consumed ids
only) are upgraded in place on load.
"""

import dataclasses
import os
import pickle
from typing import Any, Dict, Hashable, List, Optional

from realhf_tpu.base import constants, logging

logger = logging.getLogger("recover")

#: Schema history -- bump when RecoverInfo grows fields:
#:   1: recover_start/last_step_info/hash_vals_to_ignore (implicit,
#:      pre-versioning pickles)
#:   2: + version, buffer_state (SequenceBuffer in-flight snapshot),
#:      dataloader_state (epoch accounting)
#:   3: + ckpt_manifests (role -> committed durable-checkpoint
#:      manifest path, system/ckpt_manager.py)
#:   4: buffer_state switches to the PER-SAMPLE SequenceBuffer
#:      snapshot (schema key "batches" with per-sample completion
#:      records; the v3-era per-batch "entries" form is upgraded in
#:      place by SequenceBuffer.load_state_dict). No dataclass fields
#:      changed -- the bump marks the nested-payload schema so a
#:      FUTURE v4 dump is never misread by v3 code.
RECOVER_INFO_VERSION = 4


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0


@dataclasses.dataclass
class RecoverInfo:
    version: int = RECOVER_INFO_VERSION
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    hash_vals_to_ignore: List[Hashable] = dataclasses.field(default_factory=list)
    # SequenceBuffer.state_dict() of batches fetched but unfinished at
    # dump time: their ids are deliberately NOT in hash_vals_to_ignore
    # (the relaunched trial refetches them); the snapshot preserves
    # batch-id monotonicity and exposes what was in flight.
    buffer_state: Optional[Dict[str, Any]] = None
    # dataloader epoch accounting: {"epoch", "epoch_step",
    # "epochs_fetched"} -- whichever the dumping runtime tracks.
    dataloader_state: Optional[Dict[str, Any]] = None
    # v3: role -> manifest.json path of the last COMMITTED durable
    # checkpoint covering this dump (system/ckpt_manager.py). The
    # resumed trial restores weights/optimizer state from these after
    # checksum verification, falling back to the previous committed
    # manifest when a shard fails to verify.
    ckpt_manifests: Optional[Dict[str, str]] = None


def dump_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    return os.path.join(constants.recover_root(experiment, trial), "recover_info.pkl")


def dump(info: RecoverInfo, experiment: Optional[str] = None,
         trial: Optional[str] = None):
    """Atomic versioned dump: a crash mid-write must never leave a
    torn file where the previous valid one stood."""
    path = dump_path(experiment, trial)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(info, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _upgrade(info: RecoverInfo) -> RecoverInfo:
    """Fill fields missing from older-schema pickles (pickle restores
    __dict__ verbatim, so v1 instances lack the v2 attributes)."""
    # NB: membership in __dict__, not hasattr -- dataclass simple
    # defaults exist as CLASS attributes, so hasattr is always True
    had_version = "version" in info.__dict__
    for f in dataclasses.fields(RecoverInfo):
        if f.name not in info.__dict__:
            default = (f.default_factory() if f.default_factory
                       is not dataclasses.MISSING else f.default)
            setattr(info, f.name, default)
    if not had_version:
        info.version = 1
    return info


def load(experiment: Optional[str] = None,
         trial: Optional[str] = None) -> RecoverInfo:
    """Strict load: raises on missing/corrupt files. Prefer
    ``load_safe`` in resume paths."""
    with open(dump_path(experiment, trial), "rb") as f:
        info = pickle.load(f)
    if not isinstance(info, RecoverInfo):
        raise ValueError(f"recover_info.pkl holds {type(info)!r}, "
                         "not RecoverInfo")
    return _upgrade(info)


def load_safe(experiment: Optional[str] = None,
              trial: Optional[str] = None) -> Optional[RecoverInfo]:
    """Tolerant load for resume: None (-> fresh start) when the file
    is absent, truncated, corrupt, of an unknown future schema, or
    not a RecoverInfo at all. A bad recover file must downgrade the
    restart, never kill it."""
    path = dump_path(experiment, trial)
    if not os.path.isfile(path):
        return None
    try:
        info = load(experiment, trial)
    except Exception as e:  # noqa: BLE001 - any corruption -> fresh
        logger.warning("Ignoring unreadable recover info at %s (%s); "
                       "starting fresh.", path, e)
        return None
    if info.version > RECOVER_INFO_VERSION:
        logger.warning(
            "Recover info at %s has schema v%d > supported v%d "
            "(written by newer code); starting fresh.", path,
            info.version, RECOVER_INFO_VERSION)
        return None
    return info


def exists(experiment: Optional[str] = None, trial: Optional[str] = None) -> bool:
    return os.path.isfile(dump_path(experiment, trial))
