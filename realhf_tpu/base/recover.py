"""Recovery bookkeeping (reference ``realhf/base/recover.py``).

The master dumps a small ``RecoverInfo`` (epoch/step counters + data ids
already consumed this epoch) so a restarted run can skip processed data
and resume step accounting. Model weights are recovered from the latest
checkpoint separately.
"""

import dataclasses
import os
import pickle
from typing import Hashable, List, Optional

from realhf_tpu.base import constants


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    hash_vals_to_ignore: List[Hashable] = dataclasses.field(default_factory=list)


def dump_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    return os.path.join(constants.recover_root(experiment, trial), "recover_info.pkl")


def dump(info: RecoverInfo, experiment: Optional[str] = None,
         trial: Optional[str] = None):
    path = dump_path(experiment, trial)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(info, f)
    os.replace(tmp, path)


def load(experiment: Optional[str] = None,
         trial: Optional[str] = None) -> RecoverInfo:
    with open(dump_path(experiment, trial), "rb") as f:
        return pickle.load(f)


def exists(experiment: Optional[str] = None, trial: Optional[str] = None) -> bool:
    return os.path.isfile(dump_path(experiment, trial))
