"""Host networking helpers (reference ``realhf/base/network.py``)."""

import socket


def gethostip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"

