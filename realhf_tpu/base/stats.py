"""Side-channel statistics tracker.

Parity with reference ``realhf/base/constants.py:479-513``: modules deep
inside the model (e.g. MoE aux losses) record scalars here; the
algorithm interface drains them after each step and merges them into
returned stats. In JAX these are traced scalars returned from jitted
functions, so the tracker stores host-side values post-step.
"""

import threading
from collections import defaultdict
from typing import Dict, List


class StatsTracker:

    def __init__(self):
        self._stats: Dict[str, List[float]] = defaultdict(list)
        self._lock = threading.Lock()

    def record(self, **kwargs: float):
        with self._lock:
            for k, v in kwargs.items():
                self._stats[k].append(float(v))

    def export(self, clear: bool = True) -> Dict[str, float]:
        with self._lock:
            out = {k: sum(v) / len(v) for k, v in self._stats.items() if v}
            if clear:
                self._stats.clear()
        return out


_tracker = StatsTracker()


def record(**kwargs):
    _tracker.record(**kwargs)


def export(clear: bool = True):
    return _tracker.export(clear=clear)
