"""Side-channel statistics tracker.

Parity with reference ``realhf/base/constants.py:479-513``: modules deep
inside the model (e.g. MoE aux losses) record scalars here; the
algorithm interface drains them after each step and merges them into
returned stats. In JAX these are traced scalars returned from jitted
functions, so the tracker stores host-side values post-step.

Absorbed by the observability layer (``realhf_tpu/obs/metrics.py``):
accumulation runs on the same :class:`~realhf_tpu.obs.metrics.Accum`
engine the metrics registry uses, and ``export`` now reports
count/min/max/mean per key instead of a bare mean. The export swaps
the accumulator map out under the lock and summarizes OUTSIDE it, so
values recorded concurrently during a clearing export land in the
fresh map for the next export instead of being dropped mid-clear.
"""

import threading
from typing import Dict

from realhf_tpu.obs.metrics import Accum


class StatsTracker:

    def __init__(self):
        self._stats: Dict[str, Accum] = {}
        self._lock = threading.Lock()

    def record(self, **kwargs: float):
        with self._lock:
            for k, v in kwargs.items():
                acc = self._stats.get(k)
                if acc is None:
                    acc = self._stats[k] = Accum()
                acc.add(float(v))

    def export(self, clear: bool = True) -> Dict[str, Dict[str, float]]:
        """Per-key ``{count, sum, min, max, mean}``. With ``clear``
        the internal map is atomically replaced, so a concurrent
        ``record`` either lands before the swap (in this export) or
        after it (in the next one) -- never in a dict mid-``clear``."""
        with self._lock:
            if clear:
                taken, self._stats = self._stats, {}
            else:
                import dataclasses
                taken = {k: dataclasses.replace(v)
                         for k, v in self._stats.items()}
        return {k: acc.as_dict() for k, acc in taken.items()
                if acc.count}


_tracker = StatsTracker()


def record(**kwargs):
    _tracker.record(**kwargs)


def export(clear: bool = True):
    return _tracker.export(clear=clear)
