"""Backend selection helpers.

The ordering contract, in one place: a TPU PJRT plugin may be
registered PROGRAMMATICALLY at interpreter startup (sitecustomize), in
which case the ``JAX_PLATFORMS`` env var alone cannot exclude it --
merely requesting ``jax.devices("cpu")`` still initializes the TPU
plugin first and can block indefinitely when the chip is unavailable
or held by another client. Forcing the CPU backend therefore requires
flipping ``jax.config``'s ``jax_platforms`` BEFORE any backend
initialization, and the virtual-device XLA flag must be in the
environment before the CPU backend first initializes.
"""

import os
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Pin this process's JAX to the CPU backend, optionally with
    ``n_devices`` virtual devices. Call before any jax computation;
    safe to call if jax is already imported, best-effort if a backend
    was already initialized."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backends already up; env set
        pass
