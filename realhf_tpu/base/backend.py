"""Backend selection helpers.

The ordering contract, in one place: a TPU PJRT plugin may be
registered PROGRAMMATICALLY at interpreter startup (sitecustomize), in
which case the ``JAX_PLATFORMS`` env var alone cannot exclude it --
merely requesting ``jax.devices("cpu")`` still initializes the TPU
plugin first and can block indefinitely when the chip is unavailable
or held by another client. Forcing the CPU backend therefore requires
flipping ``jax.config``'s ``jax_platforms`` BEFORE any backend
initialization, and the virtual-device XLA flag must be in the
environment before the CPU backend first initializes.
"""

import os
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Pin this process's JAX to the CPU backend, optionally with
    ``n_devices`` virtual devices. Call before any jax computation;
    safe to call if jax is already imported, best-effort if a backend
    was already initialized."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backends already up; env set
        pass


def pallas_enabled() -> bool:
    """Whether the Pallas kernel paths (flash attention, flash decode,
    their shard_map wrappers) should engage: a real TPU backend, or
    ``REALHF_TPU_FORCE_PALLAS=1`` -- the test hook that runs the SAME
    wiring with interpret-mode kernels on CPU (callers then execute
    under ``pltpu.force_tpu_interpret_mode()``), so the kernel
    plumbing is exercised in CI instead of only on hardware.

    The flag is read at TRACE time: set it before building engines /
    tracing jits, and do not expect a mid-process flip to invalidate
    already-compiled programs (the env var is not part of any jit
    cache key). Forcing the flag on a non-TPU backend OUTSIDE the
    interpret-mode context raises here -- the bare kernels would
    otherwise die deep in Mosaic lowering with an opaque error."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    if os.environ.get("REALHF_TPU_FORCE_PALLAS") != "1":
        return False
    try:
        from jax._src import config as _jcfg
        in_interpret = (_jcfg.pallas_tpu_interpret_mode_context_manager
                        .value is not None)
    except Exception:  # noqa: BLE001 - jax internals moved: don't block
        in_interpret = True
    if not in_interpret:
        raise RuntimeError(
            "REALHF_TPU_FORCE_PALLAS=1 on a non-TPU backend requires "
            "running under pltpu.force_tpu_interpret_mode() (the bare "
            "Pallas kernels cannot lower for CPU); wrap the "
            "computation in that context or unset the flag.")
    return True
