"""Backend selection helpers.

The ordering contract, in one place: a TPU PJRT plugin may be
registered PROGRAMMATICALLY at interpreter startup (sitecustomize), in
which case the ``JAX_PLATFORMS`` env var alone cannot exclude it --
merely requesting ``jax.devices("cpu")`` still initializes the TPU
plugin first and can block indefinitely when the chip is unavailable
or held by another client. Forcing the CPU backend therefore requires
flipping ``jax.config``'s ``jax_platforms`` BEFORE any backend
initialization, and the virtual-device XLA flag must be in the
environment before the CPU backend first initializes.
"""

import os
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Pin this process's JAX to the CPU backend, optionally with
    ``n_devices`` virtual devices. Call before any jax computation;
    safe to call if jax is already imported, best-effort if a backend
    was already initialized."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backends already up; env set
        pass


def enable_persistent_compilation_cache(path: Optional[str] = None) -> None:
    """Turn on JAX's on-disk executable cache so compiles survive
    process crashes.

    On the tunneled axon platform every compile is a remote round-trip
    (http ``/remote_compile``) and a relay drop mid-run loses all of
    them; with the cache, each attempt banks the programs it managed
    to compile and the next attempt resumes from there. No-entry-size
    floor: the tunnel makes even tiny compiles expensive. Best-effort
    -- if the backend's executables don't support serialization JAX
    logs a warning per miss and runs uncached, which is the status quo.

    Default location is ``.jax_cache`` under the current directory
    (bench/scripts run from the repo root), overridable via
    ``REALHF_TPU_COMPILE_CACHE``; set it to ``0``/empty to disable.
    """
    if path is None:
        path = os.environ.get("REALHF_TPU_COMPILE_CACHE",
                              os.path.join(os.getcwd(), ".jax_cache"))
    if not path or path == "0":
        return
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 - read-only fs or ancient jax
        return
    # Independent knobs, each best-effort: a jax that knows the cache
    # dir but not a floor knob should still cache what it can.
    for knob, val in (("jax_persistent_cache_min_entry_size_bytes", 0),
                      ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001
            pass


def pallas_enabled() -> bool:
    """Whether the Pallas kernel paths (flash attention, flash decode,
    their shard_map wrappers) should engage: a real TPU backend, or
    ``REALHF_TPU_FORCE_PALLAS=1`` -- the test hook that runs the SAME
    wiring with interpret-mode kernels on CPU (callers then execute
    under ``pltpu.force_tpu_interpret_mode()``), so the kernel
    plumbing is exercised in CI instead of only on hardware.

    The flag is read at TRACE time: set it before building engines /
    tracing jits, and do not expect a mid-process flip to invalidate
    already-compiled programs (the env var is not part of any jit
    cache key). Forcing the flag on a non-TPU backend OUTSIDE the
    interpret-mode context raises here -- the bare kernels would
    otherwise die deep in Mosaic lowering with an opaque error."""
    import jax

    # Escape hatch / A-B rig: force the GSPMD/XLA fallback paths even
    # on a real TPU (profile_decode --no-pallas sets this to compare
    # the handwritten kernels against XLA on silicon).
    if os.environ.get("REALHF_TPU_DISABLE_PALLAS") == "1":
        return False
    if jax.default_backend() == "tpu":
        return True
    if os.environ.get("REALHF_TPU_FORCE_PALLAS") != "1":
        return False
    try:
        from jax._src import config as _jcfg
        in_interpret = (_jcfg.pallas_tpu_interpret_mode_context_manager
                        .value is not None)
    except Exception:  # noqa: BLE001 - jax internals moved: don't block
        in_interpret = True
    if not in_interpret:
        raise RuntimeError(
            "REALHF_TPU_FORCE_PALLAS=1 on a non-TPU backend requires "
            "running under pltpu.force_tpu_interpret_mode() (the bare "
            "Pallas kernels cannot lower for CPU); wrap the "
            "computation in that context or unset the flag.")
    return True
