"""Distributed key-value rendezvous store ("name resolve").

Parity with reference ``realhf/base/name_resolve.py``: an abstract
add/get/delete/wait/get_subtree API with in-memory and shared-filesystem
(NFS) backends. Workers publish addresses/status under keys from
``realhf_tpu.base.names``; peers poll or wait on them. The NFS backend
is the default for multi-host TPU pods (any shared FS works); the
memory backend serves single-process tests and the inline runner.
"""

import os
import shutil
import threading
import time
import uuid
from abc import ABC, abstractmethod
from typing import Callable, Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository(ABC):

    @abstractmethod
    def add(self, name: str, value: str, delete_on_exit: bool = True,
            keepalive_ttl: Optional[float] = None, replace: bool = False):
        """Add a key-value entry. Raises NameEntryExistsError unless replace."""

    @abstractmethod
    def delete(self, name: str):
        """Delete an entry; raises NameEntryNotFoundError if absent."""

    @abstractmethod
    def clear_subtree(self, name_root: str):
        """Delete all entries under the given prefix."""

    @abstractmethod
    def get(self, name: str) -> str:
        """Get the value of an entry; raises NameEntryNotFoundError."""

    @abstractmethod
    def get_subtree(self, name_root: str) -> List[str]:
        """Values of all entries under the prefix (sorted by key)."""

    @abstractmethod
    def find_subtree(self, name_root: str) -> List[str]:
        """Keys of all entries under the prefix (sorted)."""

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        """Add an entry with a random unique suffix under ``name``."""
        sub = f"{name}/{uuid.uuid4().hex[:8]}"
        self.add(sub, value, **kwargs)
        return sub

    def wait(self, name: str, timeout: Optional[float] = None,
             poll_frequency: float = 0.1) -> str:
        """Block until the entry exists, then return its value."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"Timeout waiting for name_resolve key: {name}")
                time.sleep(poll_frequency)

    def watch_names(self, names: List[str], call_back: Callable[[], None],
                    poll_frequency: float = 5.0, wait_timeout: float = 60.0):
        """Spawn a daemon thread invoking ``call_back`` once any of the
        names disappears (used for peer-death detection)."""
        names = list(names)

        def _watch():
            for n in names:
                self.wait(n, timeout=wait_timeout)
            while True:
                try:
                    for n in names:
                        self.get(n)
                except NameEntryNotFoundError:
                    call_back()
                    return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self):
        """Delete every entry this repository instance created."""

    def __del__(self):
        try:
            self.reset()
        except Exception:
            pass


class MemoryNameRecordRepository(NameRecordRepository):
    """Single-process in-memory backend (reference :181)."""

    def __init__(self):
        self.__store: Dict[str, str] = {}
        self.__lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        with self.__lock:
            if name in self.__store and not replace:
                raise NameEntryExistsError(name)
            self.__store[name] = str(value)

    def delete(self, name):
        with self.__lock:
            if name not in self.__store:
                raise NameEntryNotFoundError(name)
            del self.__store[name]

    def clear_subtree(self, name_root):
        with self.__lock:
            for k in [k for k in self.__store if k.startswith(name_root)]:
                del self.__store[k]

    def get(self, name):
        name = name.rstrip("/")
        with self.__lock:
            if name not in self.__store:
                raise NameEntryNotFoundError(name)
            return self.__store[name]

    def get_subtree(self, name_root):
        with self.__lock:
            return [v for k, v in sorted(self.__store.items())
                    if k.startswith(name_root)]

    def find_subtree(self, name_root):
        with self.__lock:
            return sorted(k for k in self.__store if k.startswith(name_root))

    def reset(self):
        self.__store = {}


class NfsNameRecordRepository(NameRecordRepository):
    """Shared-filesystem backend (reference :265): one file per key.

    Works on any POSIX FS visible to all hosts (NFS, GCS-fuse, local FS
    for single-host runs).
    """

    def __init__(self, record_root: Optional[str] = None):
        from realhf_tpu.base import constants
        self.record_root = record_root or os.path.join(constants.ROOT_DIR, "name_resolve")
        self.__to_delete = set()

    def __dir_path(self, name: str) -> str:
        return os.path.join(self.record_root, name)

    def __file_path(self, name: str) -> str:
        return os.path.join(self.__dir_path(name), "ENTRY")

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        path = self.__file_path(name)
        if os.path.isfile(path) and not replace:
            raise NameEntryExistsError(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)  # atomic on POSIX
        if delete_on_exit:
            self.__to_delete.add(name)

    def delete(self, name):
        path = self.__file_path(name)
        if not os.path.isfile(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        self.__to_delete.discard(name)
        # Prune now-empty parent dirs for tidiness.
        d = os.path.dirname(path)
        while d != self.record_root and os.path.isdir(d) and not os.listdir(d):
            os.rmdir(d)
            d = os.path.dirname(d)

    def clear_subtree(self, name_root):
        d = self.__dir_path(name_root)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    def get(self, name):
        name = name.rstrip("/")
        path = self.__file_path(name)
        try:
            with open(path, "r") as f:
                return f.read()
        except FileNotFoundError:
            raise NameEntryNotFoundError(name)

    def _walk_entries(self, name_root):
        d = self.__dir_path(name_root)
        out = []
        if not os.path.isdir(d):
            return out
        for root, _, files in os.walk(d):
            if "ENTRY" in files:
                key = os.path.relpath(root, self.record_root)
                out.append(key)
        return sorted(out)

    def get_subtree(self, name_root):
        return [self.get(k) for k in self._walk_entries(name_root)]

    def find_subtree(self, name_root):
        return self._walk_entries(name_root)

    def reset(self):
        for name in list(self.__to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self.__to_delete = set()


DEFAULT_REPOSITORY_TYPE = os.environ.get("REALHF_TPU_NAME_RESOLVE", "nfs")


class RedisNameRecordRepository(NameRecordRepository):
    """Redis backend (reference :357): keys with a TTL refreshed by a
    keepalive thread, so entries of dead processes expire on their own
    (the liveness signal NFS cannot give).

    The ``redis`` package is not part of the base image; pass a
    constructed ``client`` (any object with the used subset of the
    redis-py API -- get/set/delete/scan_iter/expire) or install redis.
    """

    KEEPALIVE_POLL_FREQUENCY = 2.0

    def __init__(self, host: str = "localhost", port: int = 6379,
                 db: int = 0, password: Optional[str] = None,
                 client=None):
        if client is None:
            try:
                import redis
            except ImportError as e:
                raise RuntimeError(
                    "name_resolve type 'redis' needs the redis package "
                    "(not in this image) or an injected client=..."
                ) from e
            client = redis.Redis(host=host, port=port, db=db,
                                 password=password,
                                 decode_responses=True)
        self.__client = client
        self.__to_delete = set()
        self.__keepalive_ttl: Dict[str, float] = {}
        self.__stop = threading.Event()
        self.__wake = threading.Event()
        self.__keepalive_thread = threading.Thread(
            target=self.__keepalive_loop, daemon=True)
        self.__keepalive_thread.start()

    def __keepalive_loop(self):
        # refresh TTLs so only live processes keep their entries
        # (reference keepalive thread, name_resolve.py:476); poll at
        # least 3x faster than the shortest TTL or the entry would
        # expire before its first refresh
        while True:
            ttls = list(self.__keepalive_ttl.values())
            poll = min([self.KEEPALIVE_POLL_FREQUENCY]
                       + [t / 3.0 for t in ttls])
            # add() sets __wake so a new short-TTL key re-times the
            # loop immediately instead of after an in-flight long sleep
            self.__wake.wait(timeout=max(0.05, poll))
            self.__wake.clear()
            if self.__stop.is_set():
                return
            for name, ttl in list(self.__keepalive_ttl.items()):
                try:
                    self.__client.expire(name, int(max(1, ttl)))
                except Exception:  # noqa: BLE001 - retry next tick
                    pass

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        ex = None if keepalive_ttl is None else int(max(1, keepalive_ttl))
        if replace:
            self.__client.set(name, str(value), ex=ex)
        else:
            # atomic create (SET NX): a get-then-set race would let two
            # processes both claim the same rendezvous key
            if not self.__client.set(name, str(value), ex=ex, nx=True):
                raise NameEntryExistsError(name)
        if keepalive_ttl is not None:
            self.__keepalive_ttl[name] = keepalive_ttl
            self.__wake.set()
        else:
            # re-registering without a TTL must stop the keepalive
            # thread from re-arming expiry on the now-persistent entry
            self.__keepalive_ttl.pop(name, None)
        if delete_on_exit:
            self.__to_delete.add(name)

    def delete(self, name):
        if self.__client.delete(name) == 0:
            raise NameEntryNotFoundError(name)
        self.__to_delete.discard(name)
        self.__keepalive_ttl.pop(name, None)

    def clear_subtree(self, name_root):
        for key in list(self.__client.scan_iter(
                match=name_root.rstrip("/") + "/*")):
            self.__client.delete(key)
            self.__keepalive_ttl.pop(key, None)

    def get(self, name):
        v = self.__client.get(name.rstrip("/"))
        if v is None:
            raise NameEntryNotFoundError(name)
        return v

    def find_subtree(self, name_root):
        return sorted(self.__client.scan_iter(
            match=name_root.rstrip("/") + "/*"))

    def get_subtree(self, name_root):
        # keys may TTL-expire between scan and get (that auto-expiry
        # of dead workers is the point of this backend): skip them
        out = []
        for k in self.find_subtree(name_root):
            v = self.__client.get(k)
            if v is not None:
                out.append(v)
        return out

    def reset(self):
        self.__stop.set()
        self.__wake.set()
        for name in list(self.__to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self.__to_delete = set()


def make_repository(type_: Optional[str] = None, **kwargs) -> NameRecordRepository:
    type_ = type_ or DEFAULT_REPOSITORY_TYPE
    if type_ == "memory":
        return MemoryNameRecordRepository(**kwargs)
    if type_ == "nfs":
        return NfsNameRecordRepository(**kwargs)
    if type_ == "redis":
        return RedisNameRecordRepository(**kwargs)
    raise NotImplementedError(f"Unknown name_resolve repository type: {type_}")


# Module-level default instance mirroring the reference's module API.
_default: Optional[NameRecordRepository] = None
_default_lock = threading.Lock()


def default() -> NameRecordRepository:
    global _default
    with _default_lock:
        if _default is None:
            _default = make_repository()
        return _default


def reconfigure(type_: Optional[str] = None, **kwargs):
    global _default
    with _default_lock:
        if _default is not None:
            _default.reset()
        _default = make_repository(type_, **kwargs)


def add(name, value, **kwargs):
    return default().add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return default().add_subentry(name, value, **kwargs)


def delete(name):
    return default().delete(name)


def clear_subtree(name_root):
    return default().clear_subtree(name_root)


def get(name):
    return default().get(name)


def get_subtree(name_root):
    return default().get_subtree(name_root)


def find_subtree(name_root):
    return default().find_subtree(name_root)


def wait(name, **kwargs):
    return default().wait(name, **kwargs)


def watch_names(names, call_back, **kwargs):
    return default().watch_names(names, call_back, **kwargs)


def reset():
    return default().reset()
