"""Distributed key-value rendezvous store ("name resolve").

Parity with reference ``realhf/base/name_resolve.py``: an abstract
add/get/delete/wait/get_subtree API with in-memory and shared-filesystem
(NFS) backends. Workers publish addresses/status under keys from
``realhf_tpu.base.names``; peers poll or wait on them. The NFS backend
is the default for multi-host TPU pods (any shared FS works); the
memory backend serves single-process tests and the inline runner.

Lease semantics: ``add(..., keepalive_ttl=N)`` creates an entry that
EXPIRES -- reads treat it as absent once ``N`` seconds pass without a
refresh (``touch`` or a replacing ``add``). The memory and NFS
backends enforce this lazily at read time (no reaper thread); the
Redis backend uses native key TTLs. On top of leases,
``register_with_epoch`` keeps a monotonically increasing *fencing
epoch* per name: every (re-)registration bumps it, so a consumer that
remembers the epoch it rendezvoused at can reject a zombie holder
that re-appears after its lease expired (docs/serving.md "Fleet,
failover & circuit breakers").
"""

import os
import shutil
import threading
import time
import uuid
from abc import ABC, abstractmethod
from typing import Callable, Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository(ABC):

    @abstractmethod
    def add(self, name: str, value: str, delete_on_exit: bool = True,
            keepalive_ttl: Optional[float] = None, replace: bool = False):
        """Add a key-value entry. Raises NameEntryExistsError unless replace."""

    @abstractmethod
    def delete(self, name: str):
        """Delete an entry; raises NameEntryNotFoundError if absent."""

    @abstractmethod
    def clear_subtree(self, name_root: str):
        """Delete all entries under the given prefix."""

    @abstractmethod
    def get(self, name: str) -> str:
        """Get the value of an entry; raises NameEntryNotFoundError."""

    @abstractmethod
    def get_subtree(self, name_root: str) -> List[str]:
        """Values of all entries under the prefix (sorted by key)."""

    @abstractmethod
    def find_subtree(self, name_root: str) -> List[str]:
        """Keys of all entries under the prefix (sorted)."""

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        """Add an entry with a random unique suffix under ``name``."""
        sub = f"{name}/{uuid.uuid4().hex[:8]}"
        self.add(sub, value, **kwargs)
        return sub

    def touch(self, name: str):
        """Refresh the lease of a TTL'd entry (keepalive) without
        rewriting its value. Raises NameEntryNotFoundError when the
        entry is absent -- including when its lease already expired:
        the holder must then re-register (and, if it used
        ``register_with_epoch``, gets a NEW fencing epoch)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support touch()")

    def register_with_epoch(self, name: str, value,
                            epoch_name: Optional[str] = None,
                            keepalive_ttl: Optional[float] = None) -> int:
        """Register ``name`` under a lease and bump its fencing epoch.

        The epoch is a monotonically increasing counter stored at
        ``epoch_name`` (default ``name + ".fencing_epoch"``) that
        survives lease expiry: every call returns ``previous + 1``.
        ``value`` may be a callable taking the new epoch (so the
        stored value can embed it, e.g. ``f"{epoch}:{address}"``).

        Not atomic across racing registrants -- two concurrent callers
        may observe the same previous epoch. For the intended use (one
        replica process re-registering itself after losing its lease)
        the bump itself is what fences: consumers pin the HIGHEST
        epoch they have seen and reject anything older.
        """
        epoch_name = epoch_name or name + ".fencing_epoch"
        try:
            epoch = int(self.get(epoch_name)) + 1
        except (NameEntryNotFoundError, ValueError):
            epoch = 1
        self.add(epoch_name, str(epoch), replace=True,
                 delete_on_exit=False)
        v = value(epoch) if callable(value) else value
        self.add(name, str(v), replace=True, keepalive_ttl=keepalive_ttl)
        return epoch

    def wait(self, name: str, timeout: Optional[float] = None,
             poll_frequency: float = 0.1) -> str:
        """Block until the entry exists, then return its value."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"Timeout waiting for name_resolve key: {name}")
                time.sleep(poll_frequency)

    def watch_names(self, names: List[str], call_back: Callable[[], None],
                    poll_frequency: float = 5.0, wait_timeout: float = 60.0):
        """Spawn a daemon thread invoking ``call_back`` once any of the
        names disappears (used for peer-death detection)."""
        names = list(names)

        def _watch():
            for n in names:
                self.wait(n, timeout=wait_timeout)
            while True:
                try:
                    for n in names:
                        self.get(n)
                except NameEntryNotFoundError:
                    call_back()
                    return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self):
        """Delete every entry this repository instance created."""

    def __del__(self):
        try:
            self.reset()
        except Exception:
            pass


class MemoryNameRecordRepository(NameRecordRepository):
    """Single-process in-memory backend (reference :181).

    Lease-aware: entries added with ``keepalive_ttl`` expire (reads
    treat them as absent) unless refreshed with ``touch`` or a
    replacing ``add``. ``clock`` is injectable so lease expiry is
    deterministic in tests and chaos drills."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        # name -> (value, expiry-or-None, ttl-or-None)
        self.__store: Dict[str, tuple] = {}
        self.__lock = threading.Lock()
        self.__clock = clock

    def __alive(self, name) -> bool:
        """Caller holds the lock. Lazily drops expired entries."""
        ent = self.__store.get(name)
        if ent is None:
            return False
        if ent[1] is not None and self.__clock() >= ent[1]:
            del self.__store[name]
            return False
        return True

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        with self.__lock:
            if self.__alive(name) and not replace:
                raise NameEntryExistsError(name)
            expiry = (None if keepalive_ttl is None
                      else self.__clock() + keepalive_ttl)
            self.__store[name] = (str(value), expiry, keepalive_ttl)

    def touch(self, name):
        name = name.rstrip("/")
        with self.__lock:
            if not self.__alive(name):
                raise NameEntryNotFoundError(name)
            value, _, ttl = self.__store[name]
            expiry = None if ttl is None else self.__clock() + ttl
            self.__store[name] = (value, expiry, ttl)

    def delete(self, name):
        with self.__lock:
            if not self.__alive(name):
                raise NameEntryNotFoundError(name)
            del self.__store[name]

    def clear_subtree(self, name_root):
        with self.__lock:
            for k in [k for k in self.__store if k.startswith(name_root)]:
                del self.__store[k]

    def get(self, name):
        name = name.rstrip("/")
        with self.__lock:
            if not self.__alive(name):
                raise NameEntryNotFoundError(name)
            return self.__store[name][0]

    def get_subtree(self, name_root):
        with self.__lock:
            return [self.__store[k][0]
                    for k in sorted(self.__store)
                    if k.startswith(name_root) and self.__alive(k)]

    def find_subtree(self, name_root):
        with self.__lock:
            return sorted(k for k in list(self.__store)
                          if k.startswith(name_root) and self.__alive(k))

    def reset(self):
        self.__store = {}


class NfsNameRecordRepository(NameRecordRepository):
    """Shared-filesystem backend (reference :265): one file per key.

    Works on any POSIX FS visible to all hosts (NFS, GCS-fuse, local FS
    for single-host runs).

    Leases: an entry with ``keepalive_ttl`` carries a ``TTL`` sidecar
    file; the entry counts as expired once ``ENTRY``'s mtime plus the
    TTL passes (wall clock -- the FS is shared across hosts, so keep
    them NTP-disciplined as for heartbeats). ``touch`` refreshes the
    mtime. Expiry is enforced lazily at read time.
    """

    def __init__(self, record_root: Optional[str] = None):
        from realhf_tpu.base import constants
        self.record_root = record_root or os.path.join(constants.ROOT_DIR, "name_resolve")
        self.__to_delete = set()

    def __dir_path(self, name: str) -> str:
        return os.path.join(self.record_root, name)

    def __file_path(self, name: str) -> str:
        return os.path.join(self.__dir_path(name), "ENTRY")

    def __ttl_path(self, name: str) -> str:
        return os.path.join(self.__dir_path(name), "TTL")

    def __expired(self, name: str) -> bool:
        try:
            with open(self.__ttl_path(name), "r") as f:
                ttl = float(f.read())
        except (FileNotFoundError, ValueError):
            return False  # no lease: persistent entry
        try:
            mtime = os.path.getmtime(self.__file_path(name))
        except FileNotFoundError:
            return True
        return time.time() >= mtime + ttl

    def __alive(self, name: str) -> bool:
        if not os.path.isfile(self.__file_path(name)):
            return False
        if self.__expired(name):
            # lazy reap so the dead entry stops shadowing re-adds and
            # subtree walks (best effort: a concurrent reaper is fine)
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
            return False
        return True

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        path = self.__file_path(name)
        if self.__alive(name) and not replace:
            raise NameEntryExistsError(name)
        ttl_path = self.__ttl_path(name)
        # retried: a concurrent delete() of a SIBLING key may prune
        # the freshly-created parent dir between makedirs and open
        # (registries share subtree roots across workers)
        for attempt in range(8):
            tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
            try:
                # makedirs itself can lose the race: a concurrent
                # prune may remove an intermediate dir mid-creation
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(tmp, "w") as f:
                    f.write(str(value))
                if keepalive_ttl is not None:
                    with open(ttl_path + ".tmp", "w") as f:
                        f.write(str(float(keepalive_ttl)))
                    os.replace(ttl_path + ".tmp", ttl_path)
                else:
                    # re-registering without a TTL makes the entry
                    # persistent
                    try:
                        os.remove(ttl_path)
                    except FileNotFoundError:
                        pass
                # atomic on POSIX; mtime starts the lease
                os.replace(tmp, path)
                break
            except FileNotFoundError:
                if attempt == 7:
                    raise
        if delete_on_exit:
            self.__to_delete.add(name)

    def touch(self, name):
        name = name.rstrip("/")
        if not self.__alive(name):
            raise NameEntryNotFoundError(name)
        os.utime(self.__file_path(name), None)

    def delete(self, name):
        path = self.__file_path(name)
        try:
            os.remove(self.__ttl_path(name))
        except FileNotFoundError:
            pass
        try:
            os.remove(path)
        except FileNotFoundError:
            raise NameEntryNotFoundError(name) from None
        self.__to_delete.discard(name)
        # Deliberately NO parent-dir pruning: concurrent writers share
        # subtree roots (fleet registries, heartbeats), and an rmdir
        # here races every sibling's makedirs+create. Empty dirs cost
        # nothing and vanish with clear_subtree/reset.

    def clear_subtree(self, name_root):
        d = self.__dir_path(name_root)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    def get(self, name):
        name = name.rstrip("/")
        if not self.__alive(name):
            raise NameEntryNotFoundError(name)
        try:
            with open(self.__file_path(name), "r") as f:
                return f.read()
        except FileNotFoundError:
            raise NameEntryNotFoundError(name)

    def _walk_entries(self, name_root):
        d = self.__dir_path(name_root)
        out = []
        if not os.path.isdir(d):
            return out
        for root, _, files in os.walk(d):
            if "ENTRY" in files:
                key = os.path.relpath(root, self.record_root)
                if not self.__expired(key):
                    out.append(key)
        return sorted(out)

    def get_subtree(self, name_root):
        out = []
        for k in self._walk_entries(name_root):
            # entries may expire between walk and read: skip them
            try:
                out.append(self.get(k))
            except NameEntryNotFoundError:
                pass
        return out

    def find_subtree(self, name_root):
        return self._walk_entries(name_root)

    def reset(self):
        for name in list(self.__to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self.__to_delete = set()


DEFAULT_REPOSITORY_TYPE = os.environ.get("REALHF_TPU_NAME_RESOLVE", "nfs")


class RedisNameRecordRepository(NameRecordRepository):
    """Redis backend (reference :357): keys with a TTL refreshed by a
    keepalive thread, so entries of dead processes expire on their own
    (the liveness signal NFS cannot give).

    The ``redis`` package is not part of the base image; pass a
    constructed ``client`` (any object with the used subset of the
    redis-py API -- get/set/delete/scan_iter/expire) or install redis.
    """

    KEEPALIVE_POLL_FREQUENCY = 2.0

    def __init__(self, host: str = "localhost", port: int = 6379,
                 db: int = 0, password: Optional[str] = None,
                 client=None):
        if client is None:
            try:
                import redis
            except ImportError as e:
                raise RuntimeError(
                    "name_resolve type 'redis' needs the redis package "
                    "(not in this image) or an injected client=..."
                ) from e
            client = redis.Redis(host=host, port=port, db=db,
                                 password=password,
                                 decode_responses=True)
        self.__client = client
        self.__to_delete = set()
        self.__keepalive_ttl: Dict[str, float] = {}
        self.__stop = threading.Event()
        self.__wake = threading.Event()
        self.__keepalive_thread = threading.Thread(
            target=self.__keepalive_loop, daemon=True)
        self.__keepalive_thread.start()

    def __keepalive_loop(self):
        # refresh TTLs so only live processes keep their entries
        # (reference keepalive thread, name_resolve.py:476); poll at
        # least 3x faster than the shortest TTL or the entry would
        # expire before its first refresh
        while True:
            ttls = list(self.__keepalive_ttl.values())
            poll = min([self.KEEPALIVE_POLL_FREQUENCY]
                       + [t / 3.0 for t in ttls])
            # add() sets __wake so a new short-TTL key re-times the
            # loop immediately instead of after an in-flight long sleep
            self.__wake.wait(timeout=max(0.05, poll))
            self.__wake.clear()
            if self.__stop.is_set():
                return
            for name, ttl in list(self.__keepalive_ttl.items()):
                try:
                    self.__client.expire(name, int(max(1, ttl)))
                except Exception:  # noqa: BLE001 - retry next tick
                    pass

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        ex = None if keepalive_ttl is None else int(max(1, keepalive_ttl))
        if replace:
            self.__client.set(name, str(value), ex=ex)
        else:
            # atomic create (SET NX): a get-then-set race would let two
            # processes both claim the same rendezvous key
            if not self.__client.set(name, str(value), ex=ex, nx=True):
                raise NameEntryExistsError(name)
        if keepalive_ttl is not None:
            self.__keepalive_ttl[name] = keepalive_ttl
            self.__wake.set()
        else:
            # re-registering without a TTL must stop the keepalive
            # thread from re-arming expiry on the now-persistent entry
            self.__keepalive_ttl.pop(name, None)
        if delete_on_exit:
            self.__to_delete.add(name)

    def touch(self, name):
        name = name.rstrip("/")
        if self.__client.get(name) is None:
            raise NameEntryNotFoundError(name)
        ttl = self.__keepalive_ttl.get(name)
        if ttl is not None:
            self.__client.expire(name, int(max(1, ttl)))

    def delete(self, name):
        if self.__client.delete(name) == 0:
            raise NameEntryNotFoundError(name)
        self.__to_delete.discard(name)
        self.__keepalive_ttl.pop(name, None)

    def clear_subtree(self, name_root):
        for key in list(self.__client.scan_iter(
                match=name_root.rstrip("/") + "/*")):
            self.__client.delete(key)
            self.__keepalive_ttl.pop(key, None)

    def get(self, name):
        v = self.__client.get(name.rstrip("/"))
        if v is None:
            raise NameEntryNotFoundError(name)
        return v

    def find_subtree(self, name_root):
        return sorted(self.__client.scan_iter(
            match=name_root.rstrip("/") + "/*"))

    def get_subtree(self, name_root):
        # keys may TTL-expire between scan and get (that auto-expiry
        # of dead workers is the point of this backend): skip them
        out = []
        for k in self.find_subtree(name_root):
            v = self.__client.get(k)
            if v is not None:
                out.append(v)
        return out

    def reset(self):
        self.__stop.set()
        self.__wake.set()
        for name in list(self.__to_delete):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self.__to_delete = set()


def make_repository(type_: Optional[str] = None, **kwargs) -> NameRecordRepository:
    type_ = type_ or DEFAULT_REPOSITORY_TYPE
    if type_ == "memory":
        return MemoryNameRecordRepository(**kwargs)
    if type_ == "nfs":
        return NfsNameRecordRepository(**kwargs)
    if type_ == "redis":
        return RedisNameRecordRepository(**kwargs)
    raise NotImplementedError(f"Unknown name_resolve repository type: {type_}")


# Module-level default instance mirroring the reference's module API.
_default: Optional[NameRecordRepository] = None
_default_lock = threading.Lock()


def default() -> NameRecordRepository:
    global _default
    with _default_lock:
        if _default is None:
            _default = make_repository()
        return _default


def reconfigure(type_: Optional[str] = None, **kwargs):
    global _default
    with _default_lock:
        if _default is not None:
            _default.reset()
        _default = make_repository(type_, **kwargs)


def add(name, value, **kwargs):
    return default().add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return default().add_subentry(name, value, **kwargs)


def delete(name):
    return default().delete(name)


def clear_subtree(name_root):
    return default().clear_subtree(name_root)


def get(name):
    return default().get(name)


def get_subtree(name_root):
    return default().get_subtree(name_root)


def find_subtree(name_root):
    return default().find_subtree(name_root)


def touch(name):
    return default().touch(name)


def register_with_epoch(name, value, **kwargs):
    return default().register_with_epoch(name, value, **kwargs)


def wait(name, **kwargs):
    return default().wait(name, **kwargs)


def watch_names(names, call_back, **kwargs):
    return default().watch_names(names, call_back, **kwargs)


def reset():
    return default().reset()
