"""Cluster specification (reference ``realhf/base/cluster.py:17-121``).

Describes the TPU fleet: hosts, chips per host, slice topology, and
filesystem roots, loaded from a JSON file pointed to by
``CLUSTER_SPEC_PATH`` or constructed for a local single-host run.
"""

import dataclasses
import json
import os
from typing import Dict, Optional

#: Host identity of this process inside a pod launch. Injected per
#: host by the pod manifest / MultiHostLocalScheduler
#: (``system/pod.py``); every worker on one TPU VM shares the value,
#: so the runtime can treat the VM -- the real preemption granularity
#: -- as a failure domain (``HOST_LOST`` attribution, host-level
#: exclusion backoff, per-host obs artifacts).
HOST_ID_ENV = "REALHF_TPU_HOST_ID"


def current_host_id() -> Optional[str]:
    """This process's pod host id, or None outside a pod launch."""
    return os.environ.get(HOST_ID_ENV) or None


@dataclasses.dataclass
class ClusterSpec:
    cluster_type: str = "local"  # local | tpu_pod | slurm
    cluster_name: str = "local"
    n_hosts: int = 1
    n_chips_per_host: int = 1
    # ICI topology of one slice, e.g. "4x4" for v5e-16; informational.
    slice_topology: Optional[str] = None
    fileroot: str = ""
    node_type_from_node_name: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def n_chips(self) -> int:
        return self.n_hosts * self.n_chips_per_host

    @classmethod
    def from_json(cls, path: str) -> "ClusterSpec":
        with open(path) as f:
            d = json.load(f)
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})

    @classmethod
    def for_pod(cls, n_hosts: int, n_chips_per_host: int,
                cluster_name: str = "pod",
                slice_topology: Optional[str] = None) -> "ClusterSpec":
        """The fleet a pod manifest (``system/pod.py``) describes:
        one process per host, ``n_chips_per_host`` local chips each."""
        return cls(cluster_type="tpu_pod", cluster_name=cluster_name,
                   n_hosts=n_hosts, n_chips_per_host=n_chips_per_host,
                   slice_topology=slice_topology)

    @classmethod
    def local(cls) -> "ClusterSpec":
        import jax
        return cls(cluster_type="local", n_hosts=1,
                   n_chips_per_host=jax.local_device_count())


_spec: Optional[ClusterSpec] = None


def spec() -> ClusterSpec:
    global _spec
    if _spec is None:
        path = os.environ.get("CLUSTER_SPEC_PATH", "")
        _spec = ClusterSpec.from_json(path) if path else ClusterSpec.local()
    return _spec


def set_spec(s: ClusterSpec):
    global _spec
    _spec = s
