"""Per-run global constants: experiment/trial names and filesystem roots.

Parity with reference ``realhf/base/constants.py`` (the non-parallelism
half: experiment metadata and directory layout). The parallelism state
("model_scope", grids, groups) lives in ``realhf_tpu.parallel.mesh`` as
an explicit context object instead of ambient process globals -- on TPU
the ambient state is a `jax.sharding.Mesh`, not torch process groups.
"""

import getpass
import os
from pathlib import Path
from typing import Optional

# Filesystem roots. Overridable via env so tests can redirect to tmpdirs.
ROOT_DIR = os.environ.get("REALHF_TPU_ROOT", os.path.join(os.path.expanduser("~"), ".cache", "realhf_tpu"))

_experiment_name: Optional[str] = None
_trial_name: Optional[str] = None


def set_experiment_trial_names(experiment_name: str, trial_name: str):
    global _experiment_name, _trial_name
    if "_" in experiment_name or "/" in experiment_name:
        raise ValueError(f"Invalid experiment name: {experiment_name}")
    if "_" in trial_name or "/" in trial_name:
        raise ValueError(f"Invalid trial name: {trial_name}")
    _experiment_name = experiment_name
    _trial_name = trial_name


def experiment_name() -> str:
    if _experiment_name is None:
        raise RuntimeError("Experiment name is not set.")
    return _experiment_name


def trial_name() -> str:
    if _trial_name is None:
        raise RuntimeError("Trial name is not set.")
    return _trial_name


def get_user() -> str:
    try:
        return getpass.getuser()
    except Exception:  # pragma: no cover - some containers lack a passwd entry
        return os.environ.get("USER", "unknown")


def log_root() -> str:
    return os.path.join(ROOT_DIR, "logs", get_user())


def model_save_root() -> str:
    return os.path.join(ROOT_DIR, "checkpoints", get_user())


def run_log_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    e = experiment or experiment_name()
    t = trial or trial_name()
    p = os.path.join(log_root(), e, t)
    Path(p).mkdir(parents=True, exist_ok=True)
    return p


def run_save_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    e = experiment or experiment_name()
    t = trial or trial_name()
    p = os.path.join(model_save_root(), e, t)
    Path(p).mkdir(parents=True, exist_ok=True)
    return p


def recover_root(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    e = experiment or experiment_name()
    t = trial or trial_name()
    p = os.path.join(ROOT_DIR, "recover", get_user(), e, t)
    Path(p).mkdir(parents=True, exist_ok=True)
    return p
