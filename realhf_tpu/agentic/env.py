"""Environment protocol for environment-in-the-loop (agentic) RL.

ROADMAP item 2: multi-turn rollouts where generation alternates with
an external environment or tool executor. An :class:`Env` speaks in
TOKEN IDS -- the same currency the serving subsystem moves -- so the
episode loop needs no tokenizer: ``reset()`` yields the initial
observation (the prompt), ``step(action_tokens)`` executes the
policy's emission and returns the next observation tokens, the
TURN-LEVEL reward, and whether the episode is over.

Two concrete envs ship with the subsystem:

- :class:`CheckerEnv` -- a verifiable-reward task (GSM-style): the
  answer is a deterministic function of the prompt and a programmatic
  checker IS the reward model. Single-turn; the canonical workload
  for verifiable-reward RL.
- :class:`ToolGameEnv` -- a multi-turn toy tool-call game: each turn
  the tool reveals a target token, the model must emit a STRUCTURED
  call ``[CALL_TOKEN, arg]``, the env "executes" it (checks the arg
  against the revealed target, rewards the turn) and returns the next
  observation. Malformed calls earn zero -- structure is part of the
  task.

Envs are pure host-side python (no jax) and deterministic given
``(prompt, seed)``; the registry mirrors the dataset/interface
registries so experiment configs name envs by string.
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: conventional special tokens, matching the repo-wide convention that
#: ids 0/1 are pad/eos; envs only emit/expect ids >= 2
PAD_TOKEN = 0
EOS_TOKEN = 1
#: structured tool-call opener the ToolGameEnv requires
CALL_TOKEN = 2
#: marker opening every tool observation
OBS_TOKEN = 3
#: first id usable as task payload
PAYLOAD_BASE = 4


@dataclasses.dataclass
class EnvStep:
    """Result of one environment step.

    ``observation`` tokens are appended to the episode context BEFORE
    the next action (empty when ``done``); ``reward`` is the turn-level
    reward for the action just executed."""
    observation: np.ndarray
    reward: float
    done: bool
    info: Dict[str, Any] = dataclasses.field(default_factory=dict)


class Env:
    """Environment protocol (duck-typed; subclassing is optional).

    Lifecycle: ``reset()`` -> observation tokens; then repeatedly
    ``step(action_tokens)`` -> :class:`EnvStep` until ``done``. An env
    instance drives ONE episode; construct a fresh one per episode
    (``make_env``)."""

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: np.ndarray) -> EnvStep:
        raise NotImplementedError


ALL_ENV_CLASSES: Dict[str, Callable[..., Env]] = {}


def register_env(name: str, env_cls: Callable[..., Env]):
    if name in ALL_ENV_CLASSES:
        raise ValueError(f"Env {name} already registered.")
    ALL_ENV_CLASSES[name] = env_cls


def make_env(name: str, prompt, seed: int = 0, **kwargs) -> Env:
    """Instantiate a registered env for one episode. ``prompt`` is the
    task specification in token ids (usually a dataset sample's
    ``packed_prompts``); envs derive everything else from it plus
    ``seed``, so episodes are reproducible."""
    if name not in ALL_ENV_CLASSES:
        raise ValueError(
            f"Unknown env `{name}`; registered: "
            f"{sorted(ALL_ENV_CLASSES)}")
    return ALL_ENV_CLASSES[name](prompt=prompt, seed=seed, **kwargs)


def _payload_distance(a: int, b: int, vocab_size: int) -> int:
    """Circular distance within the payload id range."""
    n = max(vocab_size - PAYLOAD_BASE, 1)
    d = abs(int(a) - int(b)) % n
    return min(d, n - d)


class CheckerEnv(Env):
    """Verifiable-reward task: a programmatic checker is the reward
    model. The target is a deterministic function of the prompt:

    - ``task="copy"``: emit the prompt's last token (trivially
      verifiable; learnable by tiny models, so the e2e acceptance
      trains on it);
    - ``task="add"``: emit ``(a + b) mod payload_range`` for the
      prompt's last two tokens -- the GSM-flavored variant.

    The FIRST emitted token is the answer. Reward: 1.0 exact, else
    ``partial_credit * (1 - circular_distance / half_range)`` -- a
    dense, verifiable shaping signal (distance to the checked answer),
    0 for ids outside the payload range. Single-turn: done after one
    step."""

    def __init__(self, prompt, seed: int = 0, *, vocab_size: int = 97,
                 task: str = "copy", partial_credit: float = 0.5):
        if task not in ("copy", "add"):
            raise ValueError(f"CheckerEnv task must be copy|add: {task}")
        self.prompt = np.asarray(prompt, np.int32)
        if len(self.prompt) == 0:
            raise ValueError("CheckerEnv needs a non-empty prompt.")
        self.vocab_size = int(vocab_size)
        self.task = task
        self.partial_credit = float(partial_credit)
        self._done = False

    @property
    def target(self) -> int:
        n = self.vocab_size - PAYLOAD_BASE
        if self.task == "copy":
            t = int(self.prompt[-1])
        else:
            a = int(self.prompt[-1])
            b = int(self.prompt[-2]) if len(self.prompt) > 1 else a
            t = PAYLOAD_BASE + ((a - PAYLOAD_BASE) + (b - PAYLOAD_BASE)) % n
        return t

    def reset(self) -> np.ndarray:
        self._done = False
        return self.prompt.copy()

    def check(self, answer: int) -> float:
        """The programmatic checker: score one candidate answer."""
        t = self.target
        if int(answer) == t:
            return 1.0
        if not (PAYLOAD_BASE <= int(answer) < self.vocab_size):
            return 0.0
        half = max((self.vocab_size - PAYLOAD_BASE) // 2, 1)
        d = _payload_distance(answer, t, self.vocab_size)
        return self.partial_credit * max(0.0, 1.0 - d / half)

    def step(self, action: np.ndarray) -> EnvStep:
        if self._done:
            raise RuntimeError("CheckerEnv episode already finished.")
        self._done = True
        action = np.asarray(action)
        reward = self.check(int(action[0])) if len(action) else 0.0
        return EnvStep(observation=np.zeros(0, np.int32),
                       reward=float(reward), done=True,
                       info=dict(target=self.target))


class ToolGameEnv(Env):
    """Multi-turn toy tool-call game (the echo tool).

    The prompt seeds a hidden target sequence ``t_1..t_n`` (derived
    deterministically from the prompt tokens + ``seed``). Each turn
    the tool's observation ``[OBS_TOKEN, t_k]`` reveals the current
    target; the model must emit the structured call
    ``[CALL_TOKEN, arg]``. The env "executes" the call: a malformed
    emission (missing opener / no arg) earns 0.0; otherwise the arg
    scores 1.0 exact or distance-shaped partial credit. After
    ``n_turns`` calls the episode is done."""

    def __init__(self, prompt, seed: int = 0, *, vocab_size: int = 97,
                 n_turns: int = 3, partial_credit: float = 0.5):
        self.prompt = np.asarray(prompt, np.int32)
        self.vocab_size = int(vocab_size)
        self.n_turns = int(n_turns)
        if self.n_turns < 1:
            raise ValueError(f"n_turns must be >= 1: {n_turns}")
        self.partial_credit = float(partial_credit)
        rng = np.random.default_rng(
            int(np.asarray(prompt, np.int64).sum()) * 1000003 + seed)
        self.targets: List[int] = [
            int(x) for x in rng.integers(PAYLOAD_BASE, self.vocab_size,
                                         size=self.n_turns)]
        self._k = 0

    def _obs(self) -> np.ndarray:
        return np.asarray([OBS_TOKEN, self.targets[self._k]], np.int32)

    def reset(self) -> np.ndarray:
        self._k = 0
        # the prompt (task spec) plus the tool's first observation
        return np.concatenate([self.prompt, self._obs()])

    def step(self, action: np.ndarray) -> EnvStep:
        if self._k >= self.n_turns:
            raise RuntimeError("ToolGameEnv episode already finished.")
        action = np.asarray(action)
        target = self.targets[self._k]
        malformed = len(action) < 2 or int(action[0]) != CALL_TOKEN
        if malformed:
            reward = 0.0
        elif int(action[1]) == target:
            reward = 1.0
        else:
            half = max((self.vocab_size - PAYLOAD_BASE) // 2, 1)
            d = _payload_distance(int(action[1]), target,
                                  self.vocab_size)
            reward = self.partial_credit * max(0.0, 1.0 - d / half)
        self._k += 1
        done = self._k >= self.n_turns
        return EnvStep(
            observation=(np.zeros(0, np.int32) if done else self._obs()),
            reward=float(reward), done=done,
            info=dict(turn=self._k, target=target,
                      malformed=bool(malformed)))


register_env("checker_task", CheckerEnv)
register_env("tool_game", ToolGameEnv)
