"""EpisodeRunner: drive N concurrent env-in-the-loop episodes.

The generation side of the agentic subsystem (docs/agentic.md). The
runner keeps up to ``max_concurrent`` episodes live against anything
speaking the ``RolloutClient`` protocol (``submit / poll_results /
abandon``): the ZMQ client against a GenServer/fleet replica
(production), or the in-process
:class:`~realhf_tpu.agentic.local.LocalRolloutBackend` (inline runner,
tier-1 tests). Per episode it alternates

    env.reset() -> obs --submit(ctx)--> action --env.step--> obs' ...

submitting the FULL context (all observations + actions so far) each
turn and stamping every turn with the ``weight_version`` the serving
side generated it under -- the per-turn behavior-policy label the PPO
staleness machinery consumes downstream.

Episode teardown is explicit about in-flight work: dropping an episode
(env error, retry exhaustion, deadline, ``stop()``, or max-turns when
``drop_on_max_turns``) ABANDONS its in-flight request -- the request
is cancelled server-side and the client forgets its stream state, so
neither the client's event map nor the router's idempotency table
leaks (see ``RolloutClient.abandon``)."""

import dataclasses
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics

logger = logging.getLogger("agentic.episode", "system")

#: terminal episode statuses a trajectory can be built from
KEEP_STATUSES = ("done", "max_turns", "length")


@dataclasses.dataclass
class Turn:
    """One observation -> action exchange."""
    obs: np.ndarray        # env/tool tokens PRECEDING this action
    action: np.ndarray     # policy-emitted tokens
    logprobs: np.ndarray   # behavior logprob per action token
    reward: float          # turn-level reward for this action
    weight_version: int    # serving weight version the action decoded under
    no_eos: bool


@dataclasses.dataclass
class Episode:
    """A finished episode, in turn order."""
    sid: object
    turns: List[Turn]
    status: str            # done | max_turns | length
    info: Dict = dataclasses.field(default_factory=dict)

    @property
    def n_turns(self) -> int:
        return len(self.turns)

    @property
    def total_reward(self) -> float:
        return float(sum(t.reward for t in self.turns))


class _Live:
    __slots__ = ("sid", "env", "turns", "pending_obs", "rid",
                 "retries", "deadline")

    def __init__(self, sid, env, pending_obs, deadline):
        self.sid = sid
        self.env = env
        self.turns: List[Turn] = []
        self.pending_obs = pending_obs
        self.rid: Optional[str] = None
        self.retries = 0
        self.deadline = deadline


class EpisodeRunner:
    """Concurrent episode loop over one rollout client.

    ``episodes`` yields ``(sid, env)`` pairs; ``max_seq_len`` caps the
    context an episode may grow to (hit it and the episode finishes as
    ``"length"`` with what it has); ``episode_ttl`` bounds one
    episode's wall clock. Call ``pump()`` + ``poll()`` from your loop,
    or ``run_all()`` to drain the source."""

    def __init__(self, client,
                 episodes: Iterator[Tuple[object, object]], *,
                 max_concurrent: int = 8, max_turns: int = 8,
                 max_seq_len: Optional[int] = None,
                 ttl: Optional[float] = None,
                 episode_ttl: Optional[float] = None,
                 drop_on_max_turns: bool = False,
                 max_retries: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        self.client = client
        self._source = iter(episodes)
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_turns = max(1, int(max_turns))
        self.max_seq_len = max_seq_len
        self._ttl = ttl
        self._episode_ttl = episode_ttl
        self.drop_on_max_turns = drop_on_max_turns
        self.max_retries = max_retries
        self._clock = clock
        self._live: Dict[object, _Live] = {}
        self._by_rid: Dict[str, object] = {}
        self._exhausted = False
        # episodes finished by the length cap during pump() are handed
        # out on the next poll() (poll is the single completion surface)
        self._finished_overflow: List[Episode] = []
        # stats
        self.episodes_done = 0
        self.turns_done = 0
        self.env_errors = 0
        self.abandoned = 0
        self.resubmits = 0
        self.dropped: List[Tuple[object, str]] = []
        self.env_step_secs = 0.0
        #: env-step wall spent while OTHER requests were in flight --
        #: the env/generation overlap numerator (bench_agentic)
        self.env_step_overlap_secs = 0.0

    # ------------------------------------------------------------------
    @property
    def inflight(self) -> int:
        return len(self._by_rid)

    @property
    def live(self) -> int:
        return len(self._live)

    @property
    def exhausted(self) -> bool:
        return self._exhausted and not self._live

    # ------------------------------------------------------------------
    def _admit(self):
        while not self._exhausted and self.live < self.max_concurrent:
            try:
                sid, env = next(self._source)
            except StopIteration:
                self._exhausted = True
                return
            deadline = (None if self._episode_ttl is None
                        else self._clock() + self._episode_ttl)
            try:
                obs = np.asarray(env.reset(), np.int32)
            except Exception as e:  # noqa: BLE001 - a broken env must
                # not kill the other episodes
                logger.warning("Episode %s: env.reset failed: %r",
                               sid, e)
                self.env_errors += 1
                self.dropped.append((sid, "env_error"))
                continue
            self._live[sid] = _Live(sid, env, obs, deadline)

    def _context(self, ep: _Live) -> np.ndarray:
        parts = []
        for t in ep.turns:
            parts.append(t.obs)
            parts.append(t.action)
        parts.append(ep.pending_obs)
        return np.concatenate(parts).astype(np.int32)

    def _drop(self, ep: _Live, reason: str):
        """Drop a live episode, cancelling its in-flight request so
        no client/router state leaks."""
        if ep.rid is not None:
            self._by_rid.pop(ep.rid, None)
            self.client.abandon(ep.rid)
            self.abandoned += 1
            metrics.inc("agentic_abandoned_total", reason=reason)
        self._live.pop(ep.sid, None)
        self.dropped.append((ep.sid, reason))

    def _finish(self, ep: _Live, status: str) -> Episode:
        self._live.pop(ep.sid, None)
        self.episodes_done += 1
        metrics.inc("agentic_episodes_total", status=status)
        return Episode(sid=ep.sid, turns=ep.turns, status=status)

    # ------------------------------------------------------------------
    def pump(self) -> int:
        """Admit new episodes and submit generation for every episode
        awaiting an action. Returns how many requests were
        submitted."""
        self._admit()
        now = self._clock()
        n = 0
        for ep in list(self._live.values()):
            if ep.deadline is not None and now > ep.deadline:
                self._drop(ep, "deadline")
                continue
            if ep.rid is not None:
                continue
            ctx = self._context(ep)
            if self.max_seq_len is not None \
                    and len(ctx) >= self.max_seq_len:
                # context full: no room to act -- keep what we have
                self._live.pop(ep.sid, None)
                if ep.turns:
                    self._finished_overflow.append(
                        self._finish_overflow(ep))
                else:
                    self.dropped.append((ep.sid, "length"))
                continue
            ep.rid = self.client.submit(ctx, ttl=self._ttl)
            self._by_rid[ep.rid] = ep.sid
            n += 1
        return n

    def _finish_overflow(self, ep: _Live) -> Episode:
        self.episodes_done += 1
        metrics.inc("agentic_episodes_total", status="length")
        return Episode(sid=ep.sid, turns=ep.turns, status="length")

    def poll(self, timeout: float = 0.0) -> List[Episode]:
        """Harvest finished generations, step their envs, and return
        every episode that finished."""
        out: List[Episode] = list(self._finished_overflow)
        self._finished_overflow = []
        # harvest first, step envs after: `inflight` must count only
        # requests genuinely still generating at the backend, so the
        # env/generation overlap accounting stays honest (a batched
        # local backend returns everything at once = zero overlap)
        harvested = []
        for res in self.client.poll_results(timeout=timeout):
            sid = self._by_rid.pop(res.rid, None)
            if sid is not None and sid in self._live:
                harvested.append((sid, res))
        for sid, res in harvested:
            if sid not in self._live:
                continue  # dropped while processing an earlier result
            ep = self._live[sid]
            ep.rid = None
            if not res.ok:
                # rejected / draining / expired: backpressure, not an
                # answer -- resubmit the same context (bounded)
                ep.retries += 1
                self.resubmits += 1
                if ep.retries > self.max_retries:
                    self._drop(ep, f"retries:{res.status}")
                continue
            action = np.asarray(res.data["tokens"], np.int32)
            lp = np.asarray(res.data.get("logprobs", ()), np.float32)
            wv = int(res.data.get("weight_version") or 0)
            no_eos = bool(res.data.get("no_eos", False))
            if len(action) == 0:
                self._drop(ep, "empty_action")
                continue
            t0 = self._clock()
            try:
                step = ep.env.step(action)
            except Exception as e:  # noqa: BLE001 - env/tool executor
                # errors drop THIS episode only
                logger.warning("Episode %s: env.step failed: %r",
                               sid, e)
                self.env_errors += 1
                self._drop(ep, "env_error")
                continue
            finally:
                dt = self._clock() - t0
                self.env_step_secs += dt
                if self.inflight > 0:
                    self.env_step_overlap_secs += dt
            ep.turns.append(Turn(
                obs=ep.pending_obs, action=action,
                logprobs=lp[:len(action)], reward=float(step.reward),
                weight_version=wv, no_eos=no_eos))
            self.turns_done += 1
            metrics.inc("agentic_turns_total")
            if step.done:
                out.append(self._finish(ep, "done"))
            elif len(ep.turns) >= self.max_turns:
                if self.drop_on_max_turns:
                    self._drop(ep, "max_turns")
                else:
                    out.append(self._finish(ep, "max_turns"))
            else:
                ep.pending_obs = np.asarray(step.observation, np.int32)
        return out

    def step(self, timeout: float = 0.0) -> List[Episode]:
        self.pump()
        return self.poll(timeout=timeout)

    def run_all(self, deadline_secs: float = 600.0) -> List[Episode]:
        """Drive pump/poll until the episode source is drained; raises
        on stall."""
        deadline = self._clock() + deadline_secs
        out: List[Episode] = []
        while not self.exhausted:
            if self._clock() > deadline:
                raise TimeoutError(
                    f"EpisodeRunner stalled: {self.live} live, "
                    f"{self.inflight} in flight, stats={self.stats()}")
            out.extend(self.step(timeout=0.02))
        return out

    def stop(self) -> int:
        """Abandon every live episode (in-flight requests cancelled);
        returns how many were dropped."""
        n = 0
        for ep in list(self._live.values()):
            self._drop(ep, "stopped")
            n += 1
        return n

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        return dict(
            episodes_done=self.episodes_done,
            turns_done=self.turns_done,
            live=self.live, inflight=self.inflight,
            env_errors=self.env_errors,
            abandoned=self.abandoned,
            resubmits=self.resubmits,
            dropped=len(self.dropped),
            env_step_secs=round(self.env_step_secs, 4),
            env_step_overlap_secs=round(self.env_step_overlap_secs, 4))
