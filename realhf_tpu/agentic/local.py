"""In-process rollout backend speaking the ``RolloutClient`` protocol.

The :class:`~realhf_tpu.agentic.episode.EpisodeRunner` drives episodes
through whatever implements ``submit / poll_results / abandon /
close`` -- in production that is the ZMQ
:class:`~realhf_tpu.serving.server.RolloutClient` against the
GenServer fleet; for the inline runner and tier-1 tests this module
provides :class:`LocalRolloutBackend`, which fulfils requests by
calling a batched ``generate_fn`` directly (no sockets, no threads, no
server).

``generate_fn`` takes a list of prompt-token arrays and returns one
:class:`GenResult` per prompt; :func:`engine_generate_fn` builds one
from a real :class:`~realhf_tpu.engine.engine.Engine` (the
AgenticActorInterface path), and tests pass scripted callables."""

import dataclasses
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.serving import protocol
from realhf_tpu.serving.server import RolloutResult


@dataclasses.dataclass
class GenResult:
    """One prompt's generation, as ``generate_fn`` returns it."""
    tokens: np.ndarray
    logprobs: np.ndarray
    no_eos: bool = False


class LocalRolloutBackend:
    """Batched, in-process stand-in for ``RolloutClient``.

    Submissions queue up; every ``poll_results`` call runs ONE batched
    ``generate_fn`` over everything pending (continuous batching's
    degenerate, synchronous form) and returns the finished
    ``RolloutResult`` s stamped with ``version_fn()`` -- the weight
    version the batch was generated under."""

    def __init__(self, generate_fn: Callable[[List[np.ndarray]],
                                             List[GenResult]],
                 *, version_fn: Callable[[], int] = lambda: 0,
                 max_batch: Optional[int] = None):
        self._generate_fn = generate_fn
        self._version_fn = version_fn
        self._max_batch = max_batch
        self._queue: Dict[str, np.ndarray] = {}
        self.generated = 0
        self.batches = 0

    # -- RolloutClient protocol ----------------------------------------
    def submit(self, prompt, priority=None, ttl=None,
               rid: Optional[str] = None,
               min_weight_version: int = 0) -> str:
        rid = rid or uuid.uuid4().hex
        self._queue[rid] = np.asarray(prompt, np.int32)
        return rid

    def cancel(self, rid: str):
        self._queue.pop(rid, None)

    def abandon(self, rid: str):
        """Cancel + forget -- mirror of ``RolloutClient.abandon``; the
        local queue IS the only state, so dropping the entry is the
        whole contract."""
        self._queue.pop(rid, None)

    def poll_results(self, timeout: float = 0.0) -> List[RolloutResult]:
        if not self._queue:
            return []
        rids = list(self._queue)
        if self._max_batch is not None:
            rids = rids[:self._max_batch]
        prompts = [self._queue.pop(r) for r in rids]
        version = int(self._version_fn())
        outs = self._generate_fn(prompts)
        if len(outs) != len(prompts):
            raise ValueError(
                f"generate_fn returned {len(outs)} results for "
                f"{len(prompts)} prompts")
        self.generated += len(outs)
        self.batches += 1
        return [
            RolloutResult(rid=rid, status=protocol.DONE, data=dict(
                tokens=np.asarray(o.tokens, np.int32),
                logprobs=np.asarray(o.logprobs, np.float32),
                no_eos=bool(o.no_eos), weight_version=version))
            for rid, o in zip(rids, outs)
        ]

    def close(self):
        self._queue.clear()


def engine_generate_fn(model, gconfig) -> Callable[[List[np.ndarray]],
                                                   List[GenResult]]:
    """A ``generate_fn`` over a real engine: left-padded batched
    prefill + decode exactly like ``PPOActorInterface.generate``, one
    fresh fold of the experiment-seeded PRNG per batch (SPMD-safe:
    every worker-group member derives identical keys)."""
    import jax

    from realhf_tpu.engine import packing
    from realhf_tpu.interfaces.ppo import _base_key

    tok = model.tokenizer
    calls = [0]

    def generate(prompts: List[np.ndarray]) -> List[GenResult]:
        ids, seg, pos = packing.left_padded_prompts(
            prompts, pad_id=tok.pad_token_id)
        calls[0] += 1
        key = jax.random.fold_in(
            jax.random.fold_in(_base_key(), calls[0]), 0x5EED)
        out = model.engine.generate(
            ids, seg, pos, key, gconfig,
            eos_token_id=tok.eos_token_id,
            pad_token_id=tok.pad_token_id).to_host()
        gen_tokens = np.asarray(out.tokens)
        gen_lp = np.asarray(out.logprobs)
        gen_lens = np.asarray(out.lengths)
        no_eos = np.asarray(out.no_eos_mask)
        return [
            GenResult(tokens=gen_tokens[i, :int(gen_lens[i])],
                      logprobs=gen_lp[i, :int(gen_lens[i])],
                      no_eos=bool(no_eos[i]))
            for i in range(len(prompts))
        ]

    return generate
