"""Trajectory-structured SequenceSample assembly.

An episode flattens to ONE packed sequence -- observations and actions
interleaved in turn order -- so multi-turn data flows through the
existing per-sample buffer, data plane, and PPO interfaces unchanged
(acceptance criterion of ISSUE 11). The encoding:

- ``packed_input_ids``: ``obs_1 + act_1 + obs_2 + act_2 + ...``
- ``prompt_mask`` (full length): True on every token the policy did
  NOT emit -- the initial prompt AND every env/tool observation. The
  PPO shifted loss mask (``~prompt_mask[1:]`` per sequence) therefore
  excludes observation tokens from the policy loss with NO interface
  change.
- ``packed_logprobs`` (length l-1): behavior logprobs on action
  prediction slots, zeros elsewhere (an action token at absolute
  index ``j`` is predicted at shifted slot ``j-1``).
- ``dense_rewards`` (length l-1): each turn's reward at its LAST
  action token's prediction slot -- the turn boundary -- zeros
  elsewhere. Consumed by the ``turn_level_credit`` knob
  (interfaces/ppo.py); the scalar ``rewards`` key carries the episode
  total for the default end-of-sequence path and stats.
- metadata: per-sample ``weight_version`` (MIN over turns -- the most
  conservative behavior-policy label for the staleness machinery),
  ``staleness``, ``n_turns``, and ``turn_spans`` of
  ``(start, n_obs, n_action, weight_version)``.
"""

from typing import List, Optional, Tuple

import numpy as np

from realhf_tpu.agentic.episode import KEEP_STATUSES, Episode
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.system.rollout import Trajectory, trajectories_to_sample


def episode_to_trajectory(ep: Episode, *, trainer_version: int = 0
                          ) -> Trajectory:
    """Flatten one finished episode into a multi-turn
    :class:`~realhf_tpu.system.rollout.Trajectory` (consumed by the
    shared ``trajectories_to_sample`` packer)."""
    if not ep.turns:
        raise ValueError(f"episode {ep.sid} has no turns")
    if ep.status not in KEEP_STATUSES:
        raise ValueError(
            f"episode {ep.sid} has status {ep.status!r}; only "
            f"{KEEP_STATUSES} flatten to trajectories")
    tokens, pmask = [], []
    spans: List[Tuple[int, int, int, int]] = []
    start = 0
    for t in ep.turns:
        n_obs, n_act = len(t.obs), len(t.action)
        if n_act < 1:
            raise ValueError(
                f"episode {ep.sid}: a turn has an empty action")
        tokens.append(np.asarray(t.obs, np.int32))
        tokens.append(np.asarray(t.action, np.int32))
        pmask.append(np.ones(n_obs, bool))
        pmask.append(np.zeros(n_act, bool))
        spans.append((start, n_obs, n_act, int(t.weight_version)))
        start += n_obs + n_act
    flat = np.concatenate(tokens)
    pmask = np.concatenate(pmask)
    l = len(flat)
    if len(ep.turns[0].obs) < 1:
        raise ValueError(
            f"episode {ep.sid}: first observation is empty -- the "
            "first prediction slot needs at least one prompt token")
    logprobs = np.zeros(l - 1, np.float32)
    dense = np.zeros(l - 1, np.float32)
    for (s, n_obs, n_act, _wv), t in zip(spans, ep.turns):
        a0 = s + n_obs          # absolute index of first action token
        logprobs[a0 - 1:a0 - 1 + n_act] = \
            np.asarray(t.logprobs, np.float32)[:n_act]
        # reward at the turn's LAST action token's prediction slot
        # (abs index a0+n_act-1, shifted slot a0+n_act-2; >= 0 because
        # the first observation is non-empty and actions are non-empty)
        dense[a0 + n_act - 2] += np.float32(t.reward)
    versions = [int(t.weight_version) for t in ep.turns]
    wv = min(versions)
    prompt = flat[:spans[0][1]]
    return Trajectory(
        sid=ep.sid, prompt=prompt, tokens=flat[len(prompt):],
        logprobs=logprobs,
        no_eos=bool(ep.turns[-1].no_eos or ep.status != "done"),
        weight_version=wv,
        staleness=max(0, int(trainer_version) - wv),
        prompt_mask=pmask, dense_rewards=dense,
        reward=ep.total_reward, turns=spans)


def episodes_to_sample(episodes: List[Episode], *,
                       trainer_version: int = 0,
                       ids: Optional[list] = None) -> SequenceSample:
    """Pack finished episodes into one trajectory-structured batch via
    the shared packer. ``ids`` (optional) reorders the episodes to
    match an input batch's id order -- the AgenticActorInterface must
    return samples in ``input_.ids`` order."""
    if ids is not None:
        by_sid = {ep.sid: ep for ep in episodes}
        missing = [i for i in ids if i not in by_sid]
        if missing:
            raise ValueError(
                f"episodes missing for ids {missing[:8]} "
                f"({len(missing)} of {len(ids)}); dropped episodes "
                "cannot flow into a fixed-id batch")
        episodes = [by_sid[i] for i in ids]
    return trajectories_to_sample(
        [episode_to_trajectory(ep, trainer_version=trainer_version)
         for ep in episodes])


def turn_segments(sample: SequenceSample, i: int
                  ) -> List[Tuple[int, int, int, int]]:
    """The i-th sample's per-turn ``(start, n_obs, n_action,
    weight_version)`` spans (metadata accessor for tests/tools)."""
    return list(sample.metadata["turn_spans"][i])
