"""Environment-in-the-loop agentic RL (docs/agentic.md).

The multi-turn / tool-use workload subsystem (ROADMAP item 2): token-
level :class:`Env` protocol + registry with a verifiable-reward
checker task and a multi-turn tool-call game, an
:class:`EpisodeRunner` driving concurrent episodes through the
``RolloutClient`` protocol (serving fleet or the in-process
:class:`LocalRolloutBackend`), and trajectory-structured
``SequenceSample`` assembly feeding the existing per-sample buffer /
PPO pipeline unchanged. Importing this package registers the
``agentic_actor`` interface and the envs."""

from realhf_tpu.agentic.env import (  # noqa: F401
    ALL_ENV_CLASSES,
    CheckerEnv,
    Env,
    EnvStep,
    ToolGameEnv,
    make_env,
    register_env,
)
from realhf_tpu.agentic.episode import (  # noqa: F401
    Episode,
    EpisodeRunner,
    Turn,
)
from realhf_tpu.agentic.local import (  # noqa: F401
    GenResult,
    LocalRolloutBackend,
    engine_generate_fn,
)
from realhf_tpu.agentic.trajectory import (  # noqa: F401
    episode_to_trajectory,
    episodes_to_sample,
    turn_segments,
)

import realhf_tpu.agentic.interface  # noqa: F401  (registers itself)
