"""Agentic actor interface: env-in-the-loop generation as an MFC.

Drops into the PPO dataflow graph where ``actor_gen`` sits: instead of
one prompt -> one completion, each dataset prompt seeds an
environment episode driven by the
:class:`~realhf_tpu.agentic.episode.EpisodeRunner` over the in-process
:class:`~realhf_tpu.agentic.local.LocalRolloutBackend` (the inline /
single-mesh path; distributed async training feeds the same
trajectories through the serving fleet instead -- see
``system/rollout.py``). The output is a trajectory-structured batch
(``agentic/trajectory.py``): observation tokens masked out of the
policy loss, per-turn rewards at turn boundaries, and the episode
total under ``rewards`` -- the ENV is the reward model, so agentic
graphs have no ``rew_inf`` MFC.

``inference`` / ``train_step`` are inherited from
:class:`~realhf_tpu.interfaces.ppo.PPOActorInterface` unchanged
(set ``turn_level_credit=True`` there to place credit at turn
boundaries instead of end-of-sequence)."""

import dataclasses
from typing import Dict, Optional

import numpy as np

from realhf_tpu.agentic.env import make_env
from realhf_tpu.agentic.episode import EpisodeRunner
from realhf_tpu.agentic.local import LocalRolloutBackend, \
    engine_generate_fn
from realhf_tpu.agentic.trajectory import episodes_to_sample
from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.base.datapack import flat2d
from realhf_tpu.interfaces.ppo import PPOActorInterface

logger = logging.getLogger("AgenticInterface")


@dataclasses.dataclass
class AgenticActorInterface(PPOActorInterface):
    #: registered env name (realhf_tpu.agentic.env)
    env: str = "checker_task"
    #: extra env constructor kwargs; ``vocab_size`` defaults to the
    #: model's
    env_args: Dict = dataclasses.field(default_factory=dict)
    max_turns: int = 4
    #: context cap per episode (tokens); None = 4x the model's
    #: generation budget past the longest prompt
    max_context_len: Optional[int] = None
    #: concurrent episodes; 0 = the whole batch at once
    max_concurrent: int = 0

    def generate(self, model: model_api.Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        prompt_lens = flat2d(input_.seqlens["packed_prompts"])
        flat = input_.data["packed_prompts"]
        prompts, off = [], 0
        for l in prompt_lens:
            prompts.append(np.asarray(flat[off:off + l], np.int32))
            off += l

        env_args = dict(self.env_args)
        env_args.setdefault("vocab_size", model.config.vocab_size)
        self._gen_calls += 1
        seed_base = self._gen_calls * 100003

        def episodes():
            for i, (sid, p) in enumerate(zip(input_.ids, prompts)):
                yield sid, make_env(self.env, prompt=p,
                                    seed=seed_base + i, **env_args)

        backend = LocalRolloutBackend(
            engine_generate_fn(model, self.gconfig),
            version_fn=lambda: model.version.global_step)
        max_ctx = self.max_context_len
        if max_ctx is None:
            max_ctx = max(prompt_lens) \
                + 4 * self.max_turns * self.gconfig.max_new_tokens
        runner = EpisodeRunner(
            backend, episodes(),
            max_concurrent=(self.max_concurrent or len(prompts)),
            max_turns=self.max_turns, max_seq_len=max_ctx)
        finished = runner.run_all()
        if runner.dropped:
            # a fixed-id batch cannot tolerate holes -- surface the
            # drop reasons instead of failing downstream with a
            # cryptic id mismatch
            raise RuntimeError(
                f"agentic generate dropped episodes: {runner.dropped}")
        sample = episodes_to_sample(
            finished, trainer_version=model.version.global_step,
            ids=list(input_.ids))
        st = runner.stats()
        logger.debug("Agentic generate: %s", st)
        rew = sample.data["rewards"]
        logger.info(
            "Agentic generate (%s): %d episodes, %d turns, mean "
            "episode reward %.4f.", self.env, st["episodes_done"],
            st["turns_done"], float(np.mean(rew)))
        return sample


model_api.register_interface("agentic_actor", AgenticActorInterface)
