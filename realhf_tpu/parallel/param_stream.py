"""Chunked host-side parameter streaming for cross-group sync.

The cross-group parameter sync (reference NCCL param reallocation,
``realhf/impl/model/comm/param_realloc.py:82,312``: per-(layer-range,
shard) steps, one sender per node) moves a role's weights between
worker groups over the host data plane. Round 3 shipped the whole
pytree as ONE pickle blob -- correct but O(model) host memory per
message and unmeasurable as a stream. This module provides the
leaf-level decomposition:

- ``flatten_params`` / ``unflatten_params``: nested-dict pytree <->
  list of (path, array) pairs (paths are tuples of str keys).
- ``plan_chunks``: group leaves into chunks bounded by
  ``max_chunk_bytes`` (one oversized leaf forms its own chunk -- it
  must travel whole anyway).
- ``chunk_payload``: materialize one chunk as {path: array}.

The sender publishes each chunk as its own versioned blob plus a small
manifest; receivers fetch chunk-by-chunk and install incrementally
(``parallel/realloc.py:install_param_chunks``), so peak receiver host
memory is one chunk, not one model.
"""

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

DEFAULT_CHUNK_BYTES = 64 << 20  # 64 MiB

Path = Tuple[str, ...]


def flatten_params(params: Any, _prefix: Path = ()
                   ) -> List[Tuple[Path, np.ndarray]]:
    """Nested-dict pytree -> sorted [(path, leaf)] (no copies)."""
    out: List[Tuple[Path, np.ndarray]] = []
    for k in sorted(params):
        v = params[k]
        if isinstance(v, dict):
            out.extend(flatten_params(v, _prefix + (str(k),)))
        else:
            out.append((_prefix + (str(k),), v))
    return out


def unflatten_params(items: Dict[Path, Any]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, leaf in items.items():
        node = root
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf
    return root


def leaf_nbytes(a) -> int:
    return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize


def plan_chunks(flat: Sequence[Tuple[Path, Any]],
                max_chunk_bytes: int = DEFAULT_CHUNK_BYTES
                ) -> List[List[int]]:
    """Greedy contiguous grouping of leaf indices into byte-bounded
    chunks (deterministic given the sorted flatten order)."""
    chunks: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, (_, leaf) in enumerate(flat):
        nb = leaf_nbytes(leaf)
        if cur and cur_bytes + nb > max_chunk_bytes:
            chunks.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        chunks.append(cur)
    return chunks


def chunk_payload(flat: Sequence[Tuple[Path, Any]],
                  idxs: Sequence[int]) -> Dict[Path, Any]:
    return {flat[i][0]: np.asarray(flat[i][1]) for i in idxs}


def build_manifest(flat: Sequence[Tuple[Path, Any]],
                   chunks: Sequence[Sequence[int]]) -> Dict:
    return {
        "n_chunks": len(chunks),
        "total_bytes": sum(leaf_nbytes(l) for _, l in flat),
        "paths": [[list(flat[i][0]) for i in idxs] for idxs in chunks],
    }
