"""shard_map compatibility layer for the pipeline schedules.

The pipeline schedules (parallel/pipeline.py GPipe, parallel/schedule.py
1F1B) run inside a shard_map that is MANUAL over the "pipe" mesh axis
only -- data/ctx/model axes stay under GSPMD so tensor parallelism
inside each stage needs no hand-written collectives. Two jax API
generations express that:

- New jax exposes ``jax.shard_map(..., axis_names={"pipe"})`` plus the
  varying-manual-axes type system (``jax.lax.pcast``). Used verbatim
  when present.
- Older jax (<= 0.4.x) only has
  ``jax.experimental.shard_map.shard_map`` whose partial-manual mode
  (``auto=...``) hard-crashes XLA's SPMD partitioner on any collective
  in the manual region (``Check failed: IsManualSubgroup`` -- a process
  abort, not an exception). The only safe lowering there is FULLY
  manual, which is valid precisely when every non-pipe axis is trivial
  (size 1): nothing is left for GSPMD to partition. pp-only meshes --
  the CPU-CI configuration -- therefore work on old jax; pp x tp / pp
  x dp meshes raise ``NotImplementedError`` up front instead of
  aborting the process.
"""

from functools import partial
from typing import Any, Optional

import jax

from realhf_tpu.parallel.mesh import PIPE_AXIS

#: new-API probe: ``jax.shard_map`` (vma era) vs experimental shard_map
NEW_SHARD_MAP = hasattr(jax, "shard_map")
#: pcast landed after jax.shard_map; probe independently
HAS_PCAST = hasattr(jax.lax, "pcast")


def mesh_supported(mesh) -> Optional[str]:
    """None when the pipeline shard_map can lower on this jax for this
    mesh, else a human-readable reason string."""
    if NEW_SHARD_MAP:
        return None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    bad = {a: n for a, n in sorted(sizes.items())
           if a != PIPE_AXIS and n > 1}
    if bad:
        return (
            "this jax has no partial-manual shard_map (jax.shard_map); "
            "the fully-manual fallback needs every non-pipe mesh axis "
            f"to be size 1, got {bad}. Use a pp-only mesh or a newer "
            "jax for pp x tp / pp x dp layouts.")
    return None


def pipe_shard_map(f=None, *, mesh, in_specs, out_specs):
    """shard_map manual over the "pipe" axis only, on whichever API
    this jax provides. Usable as a decorator
    (``@partial(pipe_shard_map, mesh=..., in_specs=..., out_specs=...)``)
    exactly like the raw APIs."""
    if f is None:
        return partial(pipe_shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
    if NEW_SHARD_MAP:
        return jax.shard_map(f, mesh=mesh, axis_names={PIPE_AXIS},
                             in_specs=in_specs, out_specs=out_specs)
    reason = mesh_supported(mesh)
    if reason is not None:
        def _raise(*a, **k):
            raise NotImplementedError(f"pipeline shard_map: {reason}")
        return _raise
    from jax.experimental.shard_map import shard_map as _shard_map
    # Fully manual (no auto axes exist to partition); check_rep off:
    # the old checker predates partial replication over trivial axes
    # and the P() outputs here are genuinely psum-replicated already.
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def to_varying(x: Any):
    """Mark a pipe-replicated value as device-varying over "pipe" so it
    can mix with rotated state under the new vma type system; identity
    on old jax (no varying types in fully-manual mode)."""
    if NEW_SHARD_MAP and HAS_PCAST:
        return jax.lax.pcast(x, (PIPE_AXIS,), to="varying")
    return x
