"""Pipeline parallelism: GPipe-style microbatch rotation over the
"pipe" mesh axis.

TPU-native replacement for the reference's pipeline machinery
(``realhf/impl/model/parallelism/pipeline_parallel/static_schedule.py``
InferenceSchedule:155 / TrainSchedule:319, ``backend/pipe_runner.py:148``
instruction executor, and the p2p send/recv in ``p2p.py``): instead of
an interpreted per-step instruction list with explicit NCCL p2p, the
schedule is a single ``lax.scan`` over pipeline ticks inside a
partial-manual ``shard_map`` (manual over "pipe" only -- data/ctx/model
axes stay under GSPMD, so tensor parallelism inside each stage is
unchanged). Microbatch rotation between stages is one
``lax.ppermute`` per tick, which XLA lowers to ICI neighbor transfers;
reverse-mode autodiff through the scan+ppermute yields the backward
pipeline (the 1F1B equivalent of TrainSchedule) for free -- there is no
hand-written BackwardPass/SendGrad/RecvGrad instruction set.

Schedule shape: with S stages and M microbatches the loop runs
T = M + S - 1 ticks; stage s processes microbatch m at tick t = m + s.
The bubble fraction is (S-1)/T, so callers should use M >= S (default
2*S) microbatches.

Layer placement: the transformer's stacked-block pytree (leading dim
``n_layers``) is sharded ``P("pipe")`` on its leading axis, so each
stage holds a contiguous ``n_layers / S`` slab -- the same
even-contiguous split as the reference's
``partition_pipeline_layers`` (real_llm_parallel.py:342). Embedding
and LM/critic heads run OUTSIDE the pipeline under plain GSPMD with
pipe-replicated weights (the reference puts them on the first/last
stage instead; replication costs n_vocab*H per extra stage but keeps
head/embedding math entirely in XLA's hands).
"""

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from realhf_tpu.parallel import smap
from realhf_tpu.parallel.mesh import PIPE_AXIS

# block_step(blocks_slab, layer_ids, x, seg, cos, sin)
#   -> (y, aux_scalars_dict)
BlockStep = Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]


@dataclasses.dataclass(frozen=True)
class PipelineContext:
    """Static pipeline execution plan for one model.

    ``schedule`` picks the tick schedule models/transformer.forward
    runs: "gpipe" (this module -- lockstep rotation, autodiff
    backward; the inference default) or "1f1b"
    (parallel/schedule.pipeline_blocks_1f1b -- explicit instruction
    streams with a custom-VJP backward pipeline; the training
    default, selected via ParallelismConfig.pipeline_schedule)."""
    mesh: Mesh
    n_stages: int
    n_microbatches: int
    schedule: str = "gpipe"

    def __post_init__(self):
        assert self.n_stages > 1, "PipelineContext needs >= 2 stages"
        assert self.n_microbatches >= 1
        assert self.schedule in ("gpipe", "1f1b"), self.schedule


def microbatch_weights(b_orig: int, bm: int, n_mb: int) -> np.ndarray:
    """Per-microbatch aux weights: REAL stream count of each
    microbatch over the total real stream count. ``pad_streams``
    appends all-padding streams at the end, so microbatch m holds
    ``clip(b_orig - m*bm, 0, bm)`` real streams -- a partially-padded
    trailing microbatch must weigh less than a full one (it used to
    count as full, deflating every real microbatch's aux share)."""
    real = np.clip(b_orig - np.arange(n_mb) * bm, 0, bm)
    return (real / max(b_orig, 1)).astype(np.float32)


def pad_streams(arrs, n_streams_multiple: int, pad_value=0):
    """Pad the leading (stream) dim of each array to a multiple of
    ``n_streams_multiple``. Padded streams carry seg_id 0 everywhere =
    all-padding, so they are masked out of attention and losses."""
    b = arrs[0].shape[0]
    m = n_streams_multiple
    pad = (m - b % m) % m
    if pad == 0:
        return arrs, b
    out = []
    for a in arrs:
        width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        out.append(jnp.pad(a, width, constant_values=pad_value))
    return out, b


def pipeline_blocks(
    pipe: PipelineContext,
    blocks: Any,                    # stacked pytree, leading dim n_layers
    n_layers: int,
    x: jnp.ndarray,                 # [B, L, H] residual after embedding
    seg_ids: jnp.ndarray,           # [B, L]
    cos: jnp.ndarray,               # [B, L, hd/2]
    sin: jnp.ndarray,               # [B, L, hd/2]
    block_step: BlockStep,
    return_aux: bool = False,
    remat_tick: bool = False,
):
    """Run the block stack as a pipeline; returns (hidden, aux).

    ``blocks`` must be sharded P("pipe") on the leading layer dim (see
    models/sharding.param_pspecs with pipeline=True); x/seg/cos/sin are
    pipe-replicated. Streams are padded to a multiple of
    ``n_microbatches`` internally.

    ``remat_tick``: rematerialize each TICK (the whole per-stage layer
    slab) in backward instead of each block. The scan's saved
    residuals then shrink from O(T * layers_per_stage) microbatch
    activations to O(T) single tick boundaries -- depth-INDEPENDENT
    resident memory, the 1F1B-class profile (reference TrainSchedule
    keeps <= S in-flight microbatch activation sets,
    static_schedule.py:319; with M ~ 2S this holds ~3S tick tensors).
    Cost: one extra forward of the slab per tick during backward, the
    same recompute block-level remat already pays.
    """
    S, M = pipe.n_stages, pipe.n_microbatches
    assert n_layers % S == 0, (n_layers, S)
    per_stage = n_layers // S
    if remat_tick:
        block_step = jax.checkpoint(
            block_step, policy=jax.checkpoint_policies.nothing_saveable,
            prevent_cse=False)

    (x, seg_ids, cos, sin), b_orig = pad_streams(
        [x, seg_ids, cos, sin], M)
    B, L, H = x.shape
    Bm = B // M
    T = M + S - 1
    # Microbatches consisting entirely of internal padding streams
    # (pad_streams appends them at the end) contribute zero aux; real
    # microbatches weigh by their REAL stream count, so a
    # partially-padded trailing microbatch counts proportionally.
    mb_w = jnp.asarray(microbatch_weights(b_orig, Bm, M))

    @partial(smap.pipe_shard_map, mesh=pipe.mesh,
             in_specs=(P(PIPE_AXIS), P(None), P(None), P(None), P(None),
                       P(None)),
             out_specs=(P(PIPE_AXIS), P()))
    def run(blocks_local, x, seg, cos, sin, w):
        idx = jax.lax.axis_index(PIPE_AXIS)
        layer_ids = idx * per_stage + jnp.arange(per_stage,
                                                 dtype=jnp.int32)

        def mb(a):
            # pipe-varying so stages can index their own microbatch
            return smap.to_varying(a.reshape(M, Bm, *a.shape[1:]))

        mbs_x, mbs_seg, mbs_cos, mbs_sin = mb(x), mb(seg), mb(cos), mb(sin)
        wv = smap.to_varying(w)
        state = smap.to_varying(jnp.zeros((Bm, L, H), x.dtype))

        def tick(state, t):
            # Stage `idx` processes microbatch m = t - idx at tick t
            # (clamped during bubble ticks, which compute on garbage
            # and are discarded below). Activations arrive via the
            # rotation; per-microbatch metadata (segments, rotary
            # phases) is indexed locally instead of rotated -- it is
            # pipe-replicated, so indexing costs no communication.
            m = jnp.clip(t - idx, 0, M - 1)
            pick = lambda a: jax.lax.dynamic_index_in_dim(
                a, m, 0, keepdims=False)
            inj = pick(mbs_x)
            xc = jnp.where(idx == 0, inj, state)
            y, aux = block_step(blocks_local, layer_ids, xc, pick(mbs_seg),
                                pick(mbs_cos), pick(mbs_sin))
            # Bubble ticks (stage s active only for s <= t < s + M):
            # their aux must not count; their outputs are never
            # consumed (see collection below), so they contribute zero
            # gradient. Valid ticks weigh by their microbatch's real
            # stream share.
            valid = (((t - idx) >= 0) & ((t - idx) < M)).astype(
                jnp.float32)
            aux = {k: v * (valid * pick(wv)) for k, v in aux.items()}
            nxt = jax.lax.ppermute(
                y, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return nxt, (y, aux)

        _, (ys, auxs) = jax.lax.scan(tick, state, jnp.arange(T))
        # Microbatch m leaves the LAST stage at tick m + S - 1; on every
        # other stage this slice is bubble garbage that the caller
        # discards by indexing stage S-1 of the stacked output.
        outs = ys[S - 1:]                       # [M, Bm, L, H]
        # Aux losses are per-token means inside each (layer,
        # microbatch) evaluation, already weighted per microbatch
        # above (the reference likewise applies MoE aux per forward
        # microbatch, utils/moe.py:395-416); sum over stages.
        # sorted: one psum per aux key -- every pipeline stage must
        # issue them in the same order or the collectives deadlock
        # (det-unsorted-iter)
        aux_tot = {k: jax.lax.psum(v.sum(), PIPE_AXIS)
                   for k, v in sorted(auxs.items())}
        return outs[None], aux_tot

    outs, aux = run(blocks, x, seg_ids, cos, sin, mb_w)
    hidden = outs[S - 1].reshape(B, L, H)[:b_orig]
    if return_aux:
        return hidden, aux
    return hidden, {}
