"""Multi-host bootstrap: one JAX distributed runtime per worker fleet.

TPU-native counterpart of reference ``impl/model/comm/global_comm.py``
(setup_global_comm:44): there, peers discover each other through
name_resolve, rank 0 publishes a ``tcp://ip:port``, and
``torch.distributed.init_process_group`` builds the NCCL world. Here
the same rendezvous feeds ``jax.distributed.initialize``: every host
process registers under ``names.distributed_peer``, ranks are the
sorted registration order, rank 0 publishes the coordinator address
under ``names.distributed_master``, and after initialize()
``jax.devices()`` spans every host -- a single Mesh over ICI+DCN, with
XLA inserting cross-host collectives (SURVEY §5.8).

Emulated multi-host testing works on CPU: N OS processes each with
``xla_force_host_platform_device_count`` virtual devices form one
2N-device world over gRPC (the ``LocalMultiProcessTest`` pattern,
reference base/testing.py:112).
"""

import socket
import time
import uuid
from typing import List, Optional, Tuple

from realhf_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("multihost")


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _peer_root(experiment_name: str, trial_name: str, group: str) -> str:
    return names.distributed_peer(experiment_name, trial_name, group)


def rendezvous(experiment_name: str, trial_name: str, n_processes: int,
               group: str = "global", timeout: float = 300.0
               ) -> Tuple[int, str]:
    """Register this process; return (process_id, coordinator_address).

    Mirrors the reference's peer discovery (global_comm.py:56-101):
    ranks are the sorted order of registered peer keys; rank 0 binds a
    free port and publishes the coordinator address.
    """
    root = _peer_root(experiment_name, trial_name, group)
    my_token = uuid.uuid4().hex
    name_resolve.add(f"{root}/{my_token}", network.gethostip(),
                     delete_on_exit=True)

    deadline = time.monotonic() + timeout
    while True:
        peers: List[str] = name_resolve.find_subtree(root)
        if len(peers) >= n_processes:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"Only {len(peers)}/{n_processes} peers registered "
                f"under {root}.")
        time.sleep(0.1)
    if len(peers) > n_processes:
        raise RuntimeError(
            f"{len(peers)} peers registered for a {n_processes}-process "
            f"group {group} -- stale trial state? clear_subtree first.")

    process_id = sorted(peers).index(f"{root}/{my_token}")
    master_key = names.distributed_master(experiment_name, trial_name,
                                          group)
    if process_id == 0:
        addr = f"{network.gethostip()}:{find_free_port()}"
        name_resolve.add(master_key, addr, replace=True,
                         delete_on_exit=True)
    else:
        addr = name_resolve.wait(master_key, timeout=timeout)
    return process_id, addr


def initialize_multihost(experiment_name: str, trial_name: str,
                         n_processes: int, group: str = "global",
                         local_device_count: Optional[int] = None,
                         timeout: float = 300.0) -> int:
    """Join the distributed runtime; returns this process's id.

    After this call ``jax.devices()`` lists every host's devices and
    Meshes may span hosts (collectives ride ICI within a host-slice
    and DCN across; reference NCCL world, global_comm.py:124-127).
    """
    import jax

    if n_processes <= 1:
        return 0
    process_id, addr = rendezvous(experiment_name, trial_name,
                                  n_processes, group, timeout)
    kwargs = dict(coordinator_address=addr, num_processes=n_processes,
                  process_id=process_id)
    if local_device_count is not None:
        kwargs["local_device_ids"] = list(range(local_device_count))
    jax.distributed.initialize(**kwargs)
    logger.info("jax.distributed initialized: process %d/%d, "
                "coordinator %s, %d global devices.", process_id,
                n_processes, addr, jax.device_count())
    return process_id


def initialize_worker_world(experiment_name: str, trial_name: str,
                            n_processes: int, process_id: int,
                            local_device_count: Optional[int] = None,
                            group: str = "model_workers",
                            timeout: float = 300.0) -> None:
    """Join the model-worker jax.distributed world with a FIXED rank.

    Unlike ``rendezvous`` (ranks from sorted registration order), the
    worker world needs rank == worker_index so the master's
    worker-group assignments map deterministically onto
    ``jax.devices()`` process indices. Worker 0 binds a free port and
    publishes the coordinator address; everyone else waits for it.
    """
    import jax

    if n_processes <= 1:
        return
    master_key = names.distributed_master(experiment_name, trial_name,
                                          group)
    if process_id == 0:
        addr = f"{network.gethostip()}:{find_free_port()}"
        name_resolve.add(master_key, addr, replace=True,
                         delete_on_exit=True)
    else:
        addr = name_resolve.wait(master_key, timeout=timeout)
    kwargs = dict(coordinator_address=addr, num_processes=n_processes,
                  process_id=process_id,
                  initialization_timeout=int(timeout))
    if local_device_count is not None:
        kwargs["local_device_ids"] = list(range(local_device_count))
    jax.distributed.initialize(**kwargs)
    logger.info("Worker world initialized: rank %d/%d, coordinator %s, "
                "%d global devices.", process_id, n_processes, addr,
                jax.device_count())


def shutdown_multihost():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 - best effort on teardown
        logger.warning("jax.distributed.shutdown: %s", e)
