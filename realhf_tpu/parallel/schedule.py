"""Steady-state 1F1B-class pipeline schedule.

TPU-native counterpart of the reference's TrainSchedule
(``realhf/impl/model/parallelism/pipeline_parallel/static_schedule.py:319``):
explicit per-tick forward/backward instruction streams -- warm-up,
steady body, cool-down -- instead of differentiating through the GPipe
rotation scan (parallel/pipeline.py). Three things change versus GPipe
autodiff:

1. **Explicit backward pipeline.** ``pipeline_blocks_1f1b`` wraps the
   pipelined forward in a ``jax.custom_vjp``; the backward runs as its
   own scan over M + S - 1 ticks in the REVERSE rotation direction
   (stage s handles microbatch m at tick ``m + (S-1-s)``), recomputing
   each stage-tick forward from the saved stage input and applying the
   cotangent with ``jax.vjp`` -- the instruction-stream structure of
   TrainSchedule's BackwardPass/SendGrad/RecvGrad, expressed as one
   reverse ``lax.ppermute`` per tick.

2. **1F1B-class residual memory.** The forward saves ONLY each stage's
   microbatch INPUT boundary activations: one ``[M, Bm, L, H]``
   buffer per stage == exactly one full-batch boundary activation set
   (M * Bm == B), independent of BOTH the tick count and the stage
   depth. GPipe autodiff instead saves O(T) per-tick residuals --
   whole per-block activation sets unless ``pipeline_remat="tick"``
   stacks a second checkpoint level. Because the residual total does
   not grow with M, the microbatch count can rise to shrink the
   bubble: the engine defaults to M = 4*pp here vs 2*pp for GPipe
   (bubble overhead (S-1)/M halves).

3. **Masked bubble ticks.** Warm-up/cool-down ticks on inactive stages
   run a ``lax.cond`` no-op branch instead of computing garbage the
   way the GPipe scan does. Per pass, each stage computes exactly M
   stage-steps instead of M + S - 1 (a (S-1)/(M+S-1) FLOP saving,
   measured directly by ``scripts/bench_pipeline.py``; on lockstep
   silicon it returns energy/HBM slack rather than wall-clock).
   ``REALHF_TPU_PIPE_MASK=0`` disables the cond (escape hatch for
   backends whose partitioner rejects stage-varying predicates).

The schedule needs the same mesh contract as GPipe: blocks sharded
P("pipe") on the leading layer axis, activations pipe-replicated,
manual over "pipe" only (parallel/smap.py picks the shard_map API).
Rotary phase inputs (cos/sin) receive zero cotangents -- they are
functions of integer positions, so no real gradient path exists
through them.
"""

import dataclasses
import os
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realhf_tpu.parallel.mesh import PIPE_AXIS

GPIPE = "gpipe"
ONE_F_ONE_B = "1f1b"
SCHEDULES = (GPIPE, ONE_F_ONE_B)

# ----------------------------------------------------------------------
# Instruction streams (pure python -- golden-testable, drive the docs
# and the bench's analytic bubble accounting; the scans below realize
# exactly these streams via index arithmetic)
# ----------------------------------------------------------------------
WARMUP = "warmup"
STEADY = "steady"
COOLDOWN = "cooldown"


@dataclasses.dataclass(frozen=True)
class Tick:
    """One (stage, tick) instruction: op "F"/"B" on a microbatch, or a
    masked "NOOP" bubble tick."""
    op: str               # "F" | "B" | "NOOP"
    microbatch: int       # -1 for NOOP
    phase: str            # warmup | steady | cooldown


def _phase_of(t: int, n_stages: int, n_microbatches: int) -> str:
    """Global phase of pass-tick t: warm-up until every stage has
    work, steady while all S stages compute, cool-down while the
    trailing stages drain."""
    if t < n_stages - 1:
        return WARMUP
    if t < n_microbatches:
        return STEADY
    return COOLDOWN


def forward_stage_stream(n_stages: int, n_microbatches: int,
                         stage: int) -> List[Tick]:
    """Per-tick instructions of one stage for the forward pass
    (M + S - 1 ticks; stage s runs F(m) at tick m + s)."""
    out = []
    for t in range(n_microbatches + n_stages - 1):
        m = t - stage
        phase = _phase_of(t, n_stages, n_microbatches)
        if 0 <= m < n_microbatches:
            out.append(Tick("F", m, phase))
        else:
            out.append(Tick("NOOP", -1, phase))
    return out


def backward_stage_stream(n_stages: int, n_microbatches: int,
                          stage: int) -> List[Tick]:
    """Backward-pass instructions (M + S - 1 ticks): the mirror
    pipeline -- stage s runs B(m) at tick m + (S - 1 - stage), so the
    LAST stage leads and input-cotangents rotate backwards."""
    rev = n_stages - 1 - stage
    out = []
    for t in range(n_microbatches + n_stages - 1):
        m = t - rev
        phase = _phase_of(t, n_stages, n_microbatches)
        if 0 <= m < n_microbatches:
            out.append(Tick("B", m, phase))
        else:
            out.append(Tick("NOOP", -1, phase))
    return out


def train_stage_stream(n_stages: int, n_microbatches: int,
                       stage: int) -> List[Tick]:
    """Full train-step stream: forward pass then backward pass
    (2 * (M + S - 1) ticks). The backward cannot begin before the last
    forward output's cotangent exists (it comes from the head/loss
    OUTSIDE the pipeline), so the two passes concatenate rather than
    interleave; the 1F1B property lives in the backward's own
    warm-up/steady/cool-down structure and the bounded residuals."""
    return (forward_stage_stream(n_stages, n_microbatches, stage)
            + backward_stage_stream(n_stages, n_microbatches, stage))


def train_schedule(n_stages: int, n_microbatches: int) -> List[List[Tick]]:
    """All stages' train streams (index = stage)."""
    return [train_stage_stream(n_stages, n_microbatches, s)
            for s in range(n_stages)]


# ----------------------------------------------------------------------
# Analytics (consumed by search/engine.py cost model and bench.py)
# ----------------------------------------------------------------------
def default_microbatches(pp: int, schedule: str = ONE_F_ONE_B) -> int:
    """Engine default microbatch count. 1F1B holds one full-batch
    boundary activation set per stage REGARDLESS of M, so it can
    afford twice GPipe's microbatch count and halve the (S-1)/M
    bubble overhead; GPipe autodiff residuals grow with the tick
    count, so it stays at 2*pp."""
    return 4 * pp if schedule == ONE_F_ONE_B else 2 * pp


def ticks_per_pass(n_stages: int, n_microbatches: int) -> int:
    return n_microbatches + n_stages - 1


def train_ticks(n_stages: int, n_microbatches: int) -> int:
    """Lockstep ticks of one train step (forward + backward pass)."""
    return 2 * ticks_per_pass(n_stages, n_microbatches)


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Fraction of a pass's ticks that are bubble: (S-1)/(M+S-1).
    Identical for forward and backward passes, hence also the
    train-step fraction. Equivalently an (S-1)/M overhead over the
    M-tick ideal."""
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


def computed_stage_steps(n_stages: int, n_microbatches: int,
                         schedule: str) -> int:
    """Stage-step computations actually executed per train step:
    GPipe's lockstep scan computes every stage every tick (garbage on
    bubble ticks, forward AND autodiff backward); 1F1B's cond masks
    them, leaving exactly the 2*M*S useful steps."""
    t = ticks_per_pass(n_stages, n_microbatches)
    if schedule == ONE_F_ONE_B:
        return 2 * n_microbatches * n_stages
    return 2 * t * n_stages


def train_bubble_factor(pp: int, n_mb: Optional[int] = None,
                        schedule: str = ONE_F_ONE_B) -> float:
    """Wall-clock multiplier over perfect pipeline scaling for one
    train step: (M + pp - 1) / M at the schedule's (default)
    microbatch count. The schedules share the per-M formula; they
    differ through the M each can afford (see default_microbatches),
    which is what re-ranks pp candidates in the allocation search."""
    if pp <= 1:
        return 1.0
    m = n_mb or default_microbatches(pp, schedule)
    return (m + pp - 1) / m


# ----------------------------------------------------------------------
# The pipelined forward with an explicit 1F1B backward
# ----------------------------------------------------------------------
def _mask_bubbles() -> bool:
    """Trace-time knob: lax.cond-mask bubble ticks (default) or
    compute-and-discard like GPipe (REALHF_TPU_PIPE_MASK=0 -- escape
    hatch for partitioners that reject stage-varying predicates)."""
    return os.environ.get("REALHF_TPU_PIPE_MASK", "1") != "0"


def pipeline_blocks_1f1b(
    pipe,                           # parallel.pipeline.PipelineContext
    blocks: Any,                    # stacked pytree, leading dim n_layers
    n_layers: int,
    x,                              # [B, L, H] residual after embedding
    seg_ids,                        # [B, L] int
    cos,                            # [B, L, hd/2]
    sin,                            # [B, L, hd/2]
    block_step,                     # (slab, layer_ids, x, seg, cos, sin)
                                    #   -> (y, aux_scalars_dict)
    return_aux: bool = False,
):
    """Run the block stack as a 1F1B-scheduled pipeline; returns
    (hidden, aux) exactly like ``pipeline.pipeline_blocks``.

    Differentiable via a custom VJP: the forward saves one stage-input
    boundary activation per microbatch (``[M, Bm, L, H]`` per stage ==
    one full-batch set); the backward is its own reverse-rotation scan
    that recomputes each tick's forward from that input (block-level
    ``jax.checkpoint`` inside ``block_step`` still bounds the
    transient per-tick memory). Aux losses are weighted by each
    microbatch's REAL stream count, so a partially-padded trailing
    microbatch contributes proportionally (same semantics as the
    GPipe path after the ISSUE 6 fix).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from realhf_tpu.parallel import smap
    from realhf_tpu.parallel.pipeline import (microbatch_weights,
                                              pad_streams)

    S, M = pipe.n_stages, pipe.n_microbatches
    assert n_layers % S == 0, (n_layers, S)
    per_stage = n_layers // S
    mask = _mask_bubbles()

    (x, seg_ids, cos, sin), b_orig = pad_streams(
        [x, seg_ids, cos, sin], M)
    B, L, H = x.shape
    Bm = B // M
    T = M + S - 1
    mb_w = jnp.asarray(microbatch_weights(b_orig, Bm, M))  # [M] f32

    # Aux output structure of one stage-step, needed to build the
    # cond's zero branch and the custom_vjp cotangent structure.
    slab_s = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct((per_stage,) + a.shape[1:],
                                       a.dtype), blocks)
    _, aux_shapes = jax.eval_shape(
        block_step, slab_s,
        jax.ShapeDtypeStruct((per_stage,), jnp.int32),
        jax.ShapeDtypeStruct((Bm, L, H), x.dtype),
        jax.ShapeDtypeStruct((Bm, L), seg_ids.dtype),
        jax.ShapeDtypeStruct((Bm, L, cos.shape[-1]), cos.dtype),
        jax.ShapeDtypeStruct((Bm, L, sin.shape[-1]), sin.dtype))
    aux_keys = sorted(aux_shapes)

    def _mb_split(a):
        """[B, ...] -> pipe-varying [M, Bm, ...] (stages index their
        own microbatch with a stage-varying index)."""
        return smap.to_varying(a.reshape(M, Bm, *a.shape[1:]))

    def _pick(a, m):
        import jax as _jax
        return _jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False)

    @partial(smap.pipe_shard_map, mesh=pipe.mesh,
             in_specs=(P(PIPE_AXIS), P(None), P(None), P(None), P(None),
                       P(None)),
             out_specs=(P(PIPE_AXIS), P(), P(PIPE_AXIS)))
    def fwd_run(blocks_l, xr, seg, cosr, sinr, w):
        idx = jax.lax.axis_index(PIPE_AXIS)
        layer_ids = idx * per_stage + jnp.arange(per_stage,
                                                 dtype=jnp.int32)
        mbs_x, mbs_seg = _mb_split(xr), _mb_split(seg)
        mbs_cos, mbs_sin = _mb_split(cosr), _mb_split(sinr)
        wv = smap.to_varying(w)
        state0 = smap.to_varying(jnp.zeros((Bm, L, H), xr.dtype))
        xsave0 = smap.to_varying(jnp.zeros((M, Bm, L, H), xr.dtype))
        outbuf0 = smap.to_varying(jnp.zeros((M, Bm, L, H), xr.dtype))
        aux0 = {k: smap.to_varying(
            jnp.zeros(aux_shapes[k].shape, aux_shapes[k].dtype))
            for k in aux_keys}

        def compute(m, xin):
            return block_step(blocks_l, layer_ids, xin,
                              _pick(mbs_seg, m), _pick(mbs_cos, m),
                              _pick(mbs_sin, m))

        def tick(carry, t):
            state, xsave, outbuf, aux_acc = carry
            m = jnp.clip(t - idx, 0, M - 1)
            valid = ((t - idx) >= 0) & ((t - idx) < M)
            inj = _pick(mbs_x, m)
            xin = jnp.where(idx == 0, inj, state)
            xsave = jax.lax.dynamic_update_index_in_dim(
                xsave, jnp.where(valid, xin, _pick(xsave, m)), m, 0)
            if mask:
                y, aux = jax.lax.cond(
                    valid, lambda xc: compute(m, xc),
                    lambda xc: (jnp.zeros_like(xc), aux0), xin)
            else:
                y, aux = compute(m, xin)
                vf = valid.astype(jnp.float32)
                aux = {k: aux[k] * vf for k in aux_keys}
            # real-stream aux weight of this tick's microbatch (zero
            # contribution on bubble ticks: aux is already zeroed)
            wt = _pick(wv, m)
            aux_acc = {k: aux_acc[k] + aux[k] * wt for k in aux_keys}
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where((idx == S - 1) & valid, y, _pick(outbuf, m)),
                m, 0)
            nxt = jax.lax.ppermute(
                y, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, xsave, outbuf, aux_acc), None

        (_, xsave, outbuf, aux_acc), _ = jax.lax.scan(
            tick, (state0, xsave0, outbuf0, aux0), jnp.arange(T))
        # sorted: one psum per aux key, same order on every stage
        # (det-unsorted-iter)
        aux_tot = {k: jax.lax.psum(v, PIPE_AXIS)
                   for k, v in sorted(aux_acc.items())}
        return outbuf[None], aux_tot, xsave[None]

    @partial(smap.pipe_shard_map, mesh=pipe.mesh,
             in_specs=(P(PIPE_AXIS), P(PIPE_AXIS), P(None), P(None),
                       P(None), P(None), P(PIPE_AXIS), P(None)),
             out_specs=(P(PIPE_AXIS), P(PIPE_AXIS)))
    def bwd_run(blocks_l, xsave_l, seg, cosr, sinr, w, g_l, g_aux):
        idx = jax.lax.axis_index(PIPE_AXIS)
        rev = (S - 1) - idx
        layer_ids = idx * per_stage + jnp.arange(per_stage,
                                                 dtype=jnp.int32)
        mbs_seg = _mb_split(seg)
        mbs_cos, mbs_sin = _mb_split(cosr), _mb_split(sinr)
        wv = smap.to_varying(w)
        xsave = xsave_l[0]
        g_loc = g_l[0]
        g_aux_v = {k: smap.to_varying(g_aux[k]) for k in aux_keys}
        gstate0 = smap.to_varying(jnp.zeros((Bm, L, H), g_l.dtype))
        dblk0 = jax.tree.map(jnp.zeros_like, blocks_l)
        dxbuf0 = smap.to_varying(jnp.zeros((M, Bm, L, H), g_l.dtype))

        def tick(carry, t):
            gstate, dblk, dxbuf = carry
            m = jnp.clip(t - rev, 0, M - 1)
            valid = ((t - rev) >= 0) & ((t - rev) < M)
            gy = jnp.where(idx == S - 1, _pick(g_loc, m), gstate)
            xin = _pick(xsave, m)
            wt = _pick(wv, m)
            g_aux_t = {k: g_aux_v[k] * wt for k in aux_keys}

            def live(op):
                xin, gy, g_aux_t = op

                def f(blk, xi):
                    return block_step(blk, layer_ids, xi,
                                      _pick(mbs_seg, m),
                                      _pick(mbs_cos, m),
                                      _pick(mbs_sin, m))

                _, vjp_fn = jax.vjp(f, blocks_l, xin)
                return vjp_fn((gy, g_aux_t))

            def dead(op):
                return (jax.tree.map(jnp.zeros_like, blocks_l),
                        jnp.zeros_like(op[0]))

            if mask:
                dblk_t, dx_t = jax.lax.cond(valid, live, dead,
                                            (xin, gy, g_aux_t))
            else:
                dblk_t, dx_t = live((xin, gy, g_aux_t))
                vf = valid.astype(dx_t.dtype)
                dblk_t = jax.tree.map(lambda a: a * vf, dblk_t)
                dx_t = dx_t * vf
            dblk = jax.tree.map(jnp.add, dblk, dblk_t)
            dxbuf = jax.lax.dynamic_update_index_in_dim(
                dxbuf,
                jnp.where((idx == 0) & valid, dx_t, _pick(dxbuf, m)),
                m, 0)
            nxt = jax.lax.ppermute(
                dx_t, PIPE_AXIS, [(i, (i - 1) % S) for i in range(S)])
            return (nxt, dblk, dxbuf), None

        (_, dblk, dxbuf), _ = jax.lax.scan(
            tick, (gstate0, dblk0, dxbuf0), jnp.arange(T))
        return dblk, dxbuf[None]

    def _primal(blocks, xp, segp, cosp, sinp):
        outs, aux, _ = fwd_run(blocks, xp, segp, cosp, sinp, mb_w)
        return outs, aux

    pipelined = jax.custom_vjp(_primal)

    def _fwd(blocks, xp, segp, cosp, sinp):
        outs, aux, xsave = fwd_run(blocks, xp, segp, cosp, sinp, mb_w)
        return (outs, aux), (blocks, xsave, segp, cosp, sinp)

    def _bwd(res, g):
        g_outs, g_aux = g
        blocks_r, xsave, segp, cosp, sinp = res
        dblocks, dxbuf = bwd_run(blocks_r, xsave, segp, cosp, sinp,
                                 mb_w, g_outs, g_aux)
        dx = dxbuf[0].reshape(B, L, H)
        # integer segments carry float0 cotangents; rotary phases are
        # functions of integer positions -- no gradient path exists
        dseg = np.zeros(segp.shape, jax.dtypes.float0)
        return (dblocks, dx, dseg, jnp.zeros_like(cosp),
                jnp.zeros_like(sinp))

    pipelined.defvjp(_fwd, _bwd)

    outs, aux = pipelined(blocks, x, seg_ids, cos, sin)
    hidden = outs[S - 1].reshape(B, L, H)[:b_orig]
    if return_aux:
        return hidden, aux
    return hidden, {}


def fwd_residual_shapes(pipe, x) -> Dict[str, Any]:
    """``jax.eval_shape`` view of what the 1F1B VJP keeps resident
    between forward and backward beyond the (replicated) original
    inputs: the saved stage-input buffer, ``[S, M, Bm, L, H]`` == one
    full-batch boundary activation set per stage -- independent of
    n_layers and of the tick count. Exposed for the
    peak-residual-memory test."""
    import jax

    from realhf_tpu.parallel.pipeline import pad_streams

    S, M = pipe.n_stages, pipe.n_microbatches

    def residuals(x):
        (xp,), _ = pad_streams([x], M)
        B, L, H = xp.shape
        return jax.numpy.zeros((S, M, B // M, L, H), xp.dtype)

    return jax.eval_shape(residuals, x)
