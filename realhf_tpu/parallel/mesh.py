"""Device meshes and the per-model parallelism context.

TPU-native replacement for reference ``realhf/base/topology.py``
(`ProcessTopology`/`ParallelGrid`) and the ambient parallelism globals
in ``realhf/base/constants.py:170-513``: each model (one node of the
dataflow graph) owns a `jax.sharding.Mesh` over a slice of the
device fleet plus a `ParallelismConfig`. GSPMD + pjit derive all
collectives from shardings, so there are no explicit communication
groups to build -- the mesh IS the topology.

Axis convention (stable across the framework):
  - "pipe":  pipeline stages (GPipe microbatch rotation, see
             parallel/pipeline.py; blocks are layer-sharded over it).
  - "data":  data parallelism over packed sequence streams.
  - "model": tensor parallelism; with ``sequence_parallel`` the
             sequence dim of activations is also sharded over this
             axis in norm/residual regions (Megatron-SP analog,
             free under GSPMD).
"""

import contextlib
import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from realhf_tpu.api.config import ModelName

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
CTX_AXIS = "ctx"  # context parallelism (ring attention over sequence)
MODEL_AXIS = "model"
MESH_AXES = (PIPE_AXIS, DATA_AXIS, CTX_AXIS, MODEL_AXIS)


@dataclasses.dataclass(frozen=True)
class ParallelismConfig:
    """3D parallelism degrees of one model, mirroring reference
    ``api/quickstart/model.py:15`` (ParallelismConfig)."""
    data_parallel_size: int = 1
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    # ring attention over the sequence dim (the reference's missing
    # context parallelism, megatron.py:60-61 TODO)
    context_parallel_size: int = 1
    sequence_parallel: bool = False
    gradient_checkpointing: bool = False
    # Pipeline microbatch count when pipeline_parallel_size > 1
    # (0 = auto, schedule-dependent: 2*pp for gpipe, 4*pp for 1f1b --
    # parallel/schedule.default_microbatches); not part of the weight
    # layout (same_layout ignores it).
    pipeline_microbatches: int = 0
    # Tick schedule for pipeline-parallel TRAINING: "1f1b" (default --
    # explicit instruction streams, custom-VJP backward pipeline,
    # bounded residuals, masked bubble ticks; parallel/schedule.py) or
    # "gpipe" (lockstep rotation scan with autodiff backward;
    # parallel/pipeline.py). Inference-only forwards always use the
    # GPipe rotation (no backward to schedule). Not part of the weight
    # layout (same_layout ignores it).
    pipeline_schedule: str = "1f1b"
    # Tensor-parallel degree of the DECODE VIEW used for generation on
    # a pipeline- or context-parallel mesh (engine.decode_engine):
    # weights reshard onto a collapsed (world/gen_tp) x gen_tp dp x tp
    # mesh over the same devices. 0 = inherit tensor_parallel_size.
    # Not part of the weight layout (same_layout ignores it).
    gen_tp_size: int = 0

    def __post_init__(self):
        if self.sequence_parallel and self.tensor_parallel_size == 1:
            object.__setattr__(self, "sequence_parallel", False)
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"pipeline_schedule must be 'gpipe' or '1f1b', got "
                f"{self.pipeline_schedule!r}")

    @property
    def world_size(self) -> int:
        return (self.data_parallel_size * self.tensor_parallel_size *
                self.pipeline_parallel_size * self.context_parallel_size)

    def same_layout(self, other: "ParallelismConfig") -> bool:
        """Same device-placement layout (ignores flags like
        gradient_checkpointing that do not affect weight sharding)."""
        return (self.data_parallel_size == other.data_parallel_size
                and self.tensor_parallel_size == other.tensor_parallel_size
                and self.pipeline_parallel_size == other.pipeline_parallel_size
                and self.context_parallel_size == other.context_parallel_size
                and self.sequence_parallel == other.sequence_parallel)

    def __str__(self):
        s = (f"d{self.data_parallel_size}t{self.tensor_parallel_size}"
             f"p{self.pipeline_parallel_size}")
        if self.context_parallel_size > 1:
            s += f"c{self.context_parallel_size}"
        if self.sequence_parallel:
            s += "s"
        if self.gen_tp_size:
            s += f"g{self.gen_tp_size}"
        return s


def parse_parallelism(name: str) -> ParallelismConfig:
    """Parse the reference's ``d$Np$Pm$M`` allocation shorthand
    (``experiments/common/utils.py:201``), e.g. "d4t2" or "d2t2p2".
    Axis letters: d = data, t = tensor (m also accepted), p = pipeline;
    trailing "s" enables sequence parallelism.
    """
    import re
    s = name.strip()
    tokens = re.findall(r"([dtmpcg])(\d+)|(s)(?!\d)", s)
    consumed = "".join(t[0] + t[1] + t[2] for t in tokens)
    sizes = {"d": 1, "t": 1, "p": 1, "c": 1, "g": 0}
    seq_par = False
    for axis, num, sp in tokens:
        if sp:
            seq_par = True
            continue
        key = "t" if axis == "m" else axis  # m = model = tensor
        sizes[key] = int(num)
    if consumed != s or not tokens:
        raise ValueError(f"Cannot parse parallelism spec `{name}`; "
                         "expected e.g. d4t2, d4p1m2, d2t2p1, d1t8s "
                         "(any axis order; m is an alias for t).")
    return ParallelismConfig(
        data_parallel_size=sizes["d"],
        tensor_parallel_size=sizes["t"],
        pipeline_parallel_size=sizes["p"],
        context_parallel_size=sizes["c"],
        sequence_parallel=seq_par,
        gen_tp_size=sizes["g"])


def default_devices() -> List:
    """Device fleet used when no explicit slice is given.

    ``REALHF_TPU_BACKEND`` overrides the platform (tests set it to
    "cpu" to get the virtual 8-device CPU mesh even when a TPU plugin
    is registered as the default backend).
    """
    backend = os.environ.get("REALHF_TPU_BACKEND")
    return list(jax.devices(backend) if backend else jax.devices())


def make_mesh(parallel: ParallelismConfig,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the (pipe, data, model) mesh for one model over the given
    device slice (defaults to all local devices).

    Device ordering follows jax's default enumeration, which on real
    TPU slices keeps ICI neighbors adjacent -- the "model" (innermost)
    axis therefore rides the fastest links, matching the reference's
    placement of TP on NVLink (`docs/source/impl.rst`).
    """
    devices = list(devices) if devices is not None else default_devices()
    if parallel.world_size != len(devices):
        raise ValueError(
            f"Parallelism {parallel} needs {parallel.world_size} devices, "
            f"got {len(devices)}.")
    arr = np.array(devices).reshape(
        parallel.pipeline_parallel_size,
        parallel.data_parallel_size,
        parallel.context_parallel_size,
        parallel.tensor_parallel_size)
    return Mesh(arr, MESH_AXES)


@dataclasses.dataclass
class MeshContext:
    """Everything parallelism-related about one model instance:
    replaces the reference's `ParallelGrid` + `constants.model_scope`
    ambient state with an explicit object."""
    model_name: ModelName
    mesh: Mesh
    parallel: ParallelismConfig

    @property
    def dp_size(self) -> int:
        return self.parallel.data_parallel_size

    @property
    def tp_size(self) -> int:
        return self.parallel.tensor_parallel_size

    @property
    def pp_size(self) -> int:
        return self.parallel.pipeline_parallel_size

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))


# ----------------------------------------------------------------------
# Optional ambient registry. The runtime registers one MeshContext per
# model and switches scope around interface calls, mirroring
# `constants.model_scope` (reference constants.py:170) for code that
# cannot take the context as an argument.
# ----------------------------------------------------------------------
_local = threading.local()
_contexts: Dict[ModelName, MeshContext] = {}


def register_context(ctx: MeshContext):
    _contexts[ctx.model_name] = ctx


def clear_contexts():
    _contexts.clear()


@contextlib.contextmanager
def model_scope(model_name: ModelName):
    prev = getattr(_local, "active", None)
    _local.active = _contexts[model_name]
    try:
        yield _local.active
    finally:
        _local.active = prev


def current_context() -> MeshContext:
    ctx = getattr(_local, "active", None)
    if ctx is None:
        raise RuntimeError("No active model scope; use model_scope(...).")
    return ctx
