"""Parameter reallocation: reshard live weights between meshes.

TPU-native replacement for the reference's signature feature
(``realhf/impl/model/comm/param_realloc.py`` + ``nn/flatten_param.py``
+ ``nn/real_llm_parallel.py``): there, every (layer range, TP shard)
pair is sliced out of a flat buffer and NCCL-broadcast between groups.
Here a model's weights are one sharded pytree, and moving them between
two `jax.sharding.Mesh`es -- different dp/tp degrees, overlapping or
disjoint device sets -- is a single `jax.device_put` onto the target
shardings: XLA computes the minimal device-to-device transfer plan
(the interval arithmetic the reference implements by hand in
``param_intervals_from_keys``, flatten_param.py:301).

EMA reallocation (``target = eta*src + (1-eta)*target``, reference
``patch_reparallelization``, real_llm_api.py:762) runs as a jitted
lerp on the target mesh after resharding.

Only the vocab dimension needs host arithmetic: replicas with
different tp degrees carry different Megatron-style vocab padding,
so wte/head are unpadded/repadded in transit.
"""

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from realhf_tpu.base import logging
from realhf_tpu.models import sharding as shard_rules
from realhf_tpu.models.config import TransformerConfig

logger = logging.getLogger("param_realloc", "benchmark")


def _repad_for_target(cfg: TransformerConfig, params: Any,
                      target_tp: int) -> Any:
    """Adjust vocab padding from the source tp to the target tp."""
    vp_target = shard_rules.padded_vocab_size(cfg, target_tp)
    if params["embed"]["wte"].shape[0] == vp_target:
        return params
    params = shard_rules.unpad_vocab(cfg, params)
    return shard_rules.pad_vocab(cfg, params, target_tp)


@jax.jit
def _ema_lerp(src, dst, eta):
    return jax.tree.map(
        lambda x, y: (eta * x.astype(jnp.float32)
                      + (1.0 - eta) * y.astype(jnp.float32)).astype(y.dtype),
        src, dst)


def reallocate(
    cfg: TransformerConfig,
    src_params: Any,
    dst_engine,
    eta: float = 1.0,
) -> float:
    """Move (or EMA-merge) src weights onto dst_engine's mesh.

    Returns the wall-clock seconds of the resharding transfer (the
    north-star reshard-latency metric).
    """
    t0 = time.monotonic()
    params = _repad_for_target(cfg, src_params, dst_engine.ctx.tp_size)
    moved = jax.device_put(params, dst_engine._param_shardings)
    if eta != 1.0:
        moved = _ema_lerp(moved, dst_engine.params,
                          jnp.asarray(eta, jnp.float32))
    jax.block_until_ready(moved)
    dt = time.monotonic() - t0
    dst_engine.set_params(moved, already_sharded=True)
    return dt


def install_param_chunks(cfg: TransformerConfig, dst_engine, n_chunks: int,
                         fetch_chunk, eta: float = 1.0):
    """Streamed receiver install: ``fetch_chunk(i) -> {path: ndarray}``
    chunks land on the target mesh one at a time (vocab repad + dtype
    cast + optional EMA per leaf), so peak host overhead is one chunk,
    not one model (VERDICT r3 missing #2; reference streams per
    (layer-range, shard) step, comm/param_realloc.py:312).

    Returns (seconds, bytes_received)."""
    from realhf_tpu.parallel import param_stream

    t0 = time.monotonic()
    tp = dst_engine.ctx.tp_size
    pdt = jnp.dtype(cfg.param_dtype)
    shardings = dict(param_stream.flatten_params(
        dst_engine._param_shardings))
    old = dict(param_stream.flatten_params(dst_engine.params))
    eta_dev = jnp.asarray(eta, jnp.float32)
    moved = {}
    total = 0
    for i in range(n_chunks):
        chunk = fetch_chunk(i)
        # sorted: every host must issue the per-leaf device_puts in
        # the same order -- a chunk dict deserialized from the wire
        # carries the SENDER's insertion order (det-unsorted-iter)
        for path, arr in sorted(chunk.items()):
            path = tuple(path)
            total += param_stream.leaf_nbytes(arr)
            arr = shard_rules.repad_vocab_leaf(cfg, path, arr, tp)
            if arr.dtype != pdt:
                arr = arr.astype(pdt)
            leaf = jax.device_put(arr, shardings[path])
            if eta != 1.0:
                # a bare array is a valid pytree: reuse the jitted lerp
                leaf = _ema_lerp(leaf, old[path], eta_dev)
            moved[path] = leaf
    missing = set(shardings) - set(moved)
    assert not missing, f"param stream missed leaves: {sorted(missing)}"
    params = param_stream.unflatten_params(moved)
    jax.block_until_ready(params)
    dst_engine.set_params(params, already_sharded=True)
    return time.monotonic() - t0, total


def offload_to_host(params: Any) -> Any:
    """Move a pytree to host memory (reference async_offload,
    real_llm_api.py:274 -- pinned-CPU offload)."""
    cpu = jax.devices("cpu")[0]
    return jax.device_put(params, cpu)


class ReplicaManager:
    """Keeps secondary engines (replicas with different meshes) of a
    role in sync with the trainable primary.

    Mirrors reference ``resolve_replica_ids`` + ``resolve_rpc_hooks``
    (experiments/common/utils.py:126,143): the trainable replica is
    the source of truth; stale replicas are refreshed by reallocation
    before executing their MFC.
    """

    def __init__(self):
        # role -> replica engine id -> version of last sync
        self._synced: Dict[str, Dict[int, int]] = {}
        self.last_reshard_secs: Optional[float] = None

    def ensure_fresh(self, role: str, primary_model, replica_model,
                     eta: float = 1.0):
        if replica_model is primary_model:
            return
        pv = primary_model.version.global_step
        synced = self._synced.setdefault(role, {})
        rid = id(replica_model)
        if synced.get(rid) == pv:
            return
        dt = reallocate(primary_model.config,
                        primary_model.engine.params,
                        replica_model.engine, eta=eta)
        self.last_reshard_secs = dt
        synced[rid] = pv
        logger.info(
            "Reallocated %s %s -> %s in %.3fs", role,
            primary_model.engine.ctx.parallel,
            replica_model.engine.ctx.parallel, dt)
