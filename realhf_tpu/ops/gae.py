"""Generalized Advantage Estimation over packed sequences.

TPU-native replacement for the reference CUDA kernel ``csrc/cugae/
gae.cu`` (gae_1d_nolp_misalign:10) and its python fallback
``ppo_functional.pygae1d_nolp_misalign:337``: a vectorized reverse
`lax.scan` over a padded [n_seqs, L] view of the packed data. GAE is
O(T) and runs fused under jit -- no native kernel needed.

Semantics (misaligned packing, identical to the reference):
- ``rewards`` is 1D packed with per-sequence lengths ``l_i``;
- ``values`` is 1D packed with lengths ``l_i + 1`` (bootstrap value
  appended per sequence);
- ``bootstrap[i]`` (the `seq_no_eos_mask`) keeps the bootstrap value
  for truncated sequences and zeroes it for EOS-terminated ones.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def gae_padded(
    rewards: jnp.ndarray,    # [B, L] (entries beyond l_i are ignored)
    values: jnp.ndarray,     # [B, L + 1] (values[i, l_i] = bootstrap)
    lengths: jnp.ndarray,    # [B] int32 reward lengths l_i
    bootstrap: jnp.ndarray,  # [B] float/bool: 1 keeps bootstrap value
    gamma: float,
    lam: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Padded-layout GAE; returns (advantages, returns) of shape [B, L]
    with zeros beyond each sequence."""
    b, l = rewards.shape
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    t_idx = jnp.arange(l)[None, :]
    valid = t_idx < lengths[:, None]
    # factor applied to V(t+1): 1 inside the sequence, `bootstrap` at
    # the final step, 0 beyond.
    nv_factor = jnp.where(
        t_idx == lengths[:, None] - 1,
        bootstrap.astype(jnp.float32)[:, None],
        valid.astype(jnp.float32))
    delta = rewards + gamma * values[:, 1:] * nv_factor - values[:, :-1]
    delta = jnp.where(valid, delta, 0.0)

    def body(gae, x):
        d, m = x
        gae = d + gamma * lam * m * gae
        return gae, gae

    # reverse scan over time, vectorized over batch
    _, adv_rev = jax.lax.scan(
        body, jnp.zeros((b,), jnp.float32),
        (delta.T[::-1], valid.astype(jnp.float32).T[::-1]))
    adv = adv_rev[::-1].T
    adv = jnp.where(valid, adv, 0.0)
    returns = adv + jnp.where(valid, values[:, :-1], 0.0)
    return adv, returns


def gae_packed_numpy(
    rewards: np.ndarray,
    values: np.ndarray,
    cu_seqlens: np.ndarray,  # [B+1] boundaries of `rewards`
    bootstrap: np.ndarray,   # [B]
    gamma: float,
    lam: float,
) -> Tuple[np.ndarray, np.ndarray]:
    """1D-packed misaligned GAE with the exact reference signature
    (cugae1d_nolp_misalign_func, gae.cu:10). Host-side convenience:
    pads, runs the jitted padded kernel, re-packs."""
    lens = np.diff(cu_seqlens).astype(np.int32)
    b, lmax = len(lens), int(lens.max())
    r_pad = np.zeros((b, lmax), np.float32)
    v_pad = np.zeros((b, lmax + 1), np.float32)
    v_off = 0
    for i, ln in enumerate(lens):
        r_pad[i, :ln] = rewards[cu_seqlens[i]:cu_seqlens[i + 1]]
        v_pad[i, :ln + 1] = values[v_off:v_off + ln + 1]
        v_off += ln + 1
    adv_p, ret_p = jax.jit(gae_padded, static_argnames=("gamma", "lam"))(
        jnp.asarray(r_pad), jnp.asarray(v_pad), jnp.asarray(lens),
        jnp.asarray(np.asarray(bootstrap, np.float32)), gamma=gamma, lam=lam)
    adv_p, ret_p = np.asarray(adv_p), np.asarray(ret_p)
    adv = np.concatenate([adv_p[i, :ln] for i, ln in enumerate(lens)])
    ret = np.concatenate([ret_p[i, :ln] for i, ln in enumerate(lens)])
    return adv, ret
