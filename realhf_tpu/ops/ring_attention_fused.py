"""Fused ring attention: one Pallas kernel per device with the KV
ring riding inter-chip RDMA (``pltpu.make_async_remote_copy``)
overlapped against flash compute.

The shard_map/ppermute formulation (``ops/ring_attention.py``) leaves
the comm/compute overlap to XLA's scheduler and re-enters jitted
glue between rounds. Here one kernel owns the whole ring: KV shards
live in a double-buffered HBM slab, each round's send to the right
neighbor is issued BEFORE the round's flash compute so the transfer
hides behind it, and slot reuse is fenced by a neighbor handshake
(regular semaphore: a receiver frees a slot only after its own reads
AND its forwarding send of that slot have completed). Per-round
compute is the same tiled online-softmax (flash-2 schedule, GQA,
packed-segment + causal + sliding-window masks on GLOBAL positions)
as ``ops/flash_attention.py``.

By default the ring is BIDIRECTIONAL: each device's KV shard splits
into two halves that counter-rotate (dir 0 rightward, dir 1
leftward), so both ICI ring directions carry traffic and per-round
transfer time halves -- the full-bisection-bandwidth pattern. Falls
back to one direction when a half-shard would not tile.

Ring choreography per device and direction (n = ring size,
slot = r % 2):

  round r first cell:  r==0: neighbor barrier (all members entered)
                       r>0:  wait recv[slot]  (this round's KV landed)
                             wait send[1-slot] (our r-1 send drained)
                             signal LEFT: "my slot 1-slot is free"
                       r<n-1: (r>0: wait RIGHT's free signal)
                              start RDMA kbuf/vbuf/segk[slot] ->
                              right neighbor's [1-slot]
  every cell:          local DMA of this (batch, kv-head) KV slice
                       HBM slab -> VMEM, flash-accumulate the q tile
  round n-1:           normalize and write o

Cross-round accumulator state (m / l / unnormalized acc) persists in
unblocked HBM slabs (``pl.ANY`` outputs) moved by explicit local DMAs
each cell -- Mosaic's output pipeline forbids revisiting blocked
output windows across non-adjacent grid cells, and these are the same
bytes the shard_map formulation carries through its fori_loop anyway.

Interpret-mode tested on the virtual CPU mesh (remote DMAs + remote
semaphore signals are emulated by ``pltpu.InterpretParams``); real
multi-chip validation pending hardware (docs/PARITY.md).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
try:
    from jax import shard_map  # check_vma-era API (jax >= 0.6)
except ImportError:  # older jax spells it check_rep under experimental
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
from jax.sharding import Mesh, PartitionSpec as P

from realhf_tpu.ops.ring_attention import ring_attention

# The kernel needs the CompilerParams/InterpretParams-era Pallas TPU
# API (remote-DMA interpret emulation in particular). On older jax the
# module still imports -- callers gate on FUSED_RING_SUPPORTED and the
# entry point raises with the reason instead of an AttributeError deep
# inside pallas_call.
FUSED_RING_UNSUPPORTED_REASON = None
if not hasattr(pltpu, "CompilerParams"):
    FUSED_RING_UNSUPPORTED_REASON = (
        "jax.experimental.pallas.tpu lacks CompilerParams "
        "(has_side_effects/collective_id); jax too old for the fused "
        "ring kernel.")
elif not hasattr(pltpu, "InterpretParams"):
    FUSED_RING_UNSUPPORTED_REASON = (
        "jax.experimental.pallas.tpu lacks InterpretParams (remote-DMA "
        "interpret emulation); jax too old for the fused ring kernel.")
FUSED_RING_SUPPORTED = FUSED_RING_UNSUPPORTED_REASON is None

NEG_INF = -2.0 ** 30
LANES = 128
SUBLANES = 8


def _fit_block(lc: int, block: int) -> int:
    b = min(block, lc)
    while lc % b:
        b -= 1
    if b < 8:
        # a silent mis-grid (empty q dimension / dropped tail tokens)
        # would return uninitialized output -- refuse instead
        raise ValueError(
            f"local context shard of {lc} tokens has no >=8 tile "
            f"divisor <= {block}; pad the sequence or adjust the "
            "ctx degree for ring_attention_fused.")
    return b


def _ring_kernel(q_ref, segq_ref,                     # blocked inputs
                 kin_ref, vin_ref, segin_ref,         # ANY inputs
                 o_ref,                                # ANY output
                 kbuf_ref, vbuf_ref, segk_ref,        # ANY ring slabs
                 m_ref, l_ref, acc_ref,               # ANY state slabs
                 k_vmem, v_vmem, sk_vmem,             # VMEM KV scratch
                 m_vmem, l_vmem, acc_vmem, o_vmem,    # VMEM state
                 kv_sems,                              # local KV copies
                 misc_sems,                            # state/out copies
                 send_sems, recv_sems,                 # RDMA [3, 2, nd]
                 free_sems,                            # handshake [nd]
                 *, n: int, axis: str, bq: int, bk: int, group: int,
                 n_dirs: int, scale: float, causal: bool,
                 sliding_window: Optional[int]):
    r = pl.program_id(0)
    bi = pl.program_id(1)
    hk = pl.program_id(2)
    qi = pl.program_id(3)
    n_qb = pl.num_programs(3)
    my = jax.lax.axis_index(axis)
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)
    slot = jax.lax.rem(r, 2)
    nxt = 1 - slot
    lch = k_vmem.shape[1]                  # per-direction shard length
    lc = lch * n_dirs

    # direction d sends to send_to[d]; the device that sends TO us in
    # direction d is from_of[d] (dir 0 rotates right, dir 1 left)
    send_to = [right, left]
    from_of = [left, right]

    first_cell = jnp.logical_and(
        jnp.logical_and(bi == 0, hk == 0), qi == 0)

    def slab_rdma(d, slot_src, slot_dst, sem_i):
        """RDMA descriptors for direction d's three ring slabs."""
        return [
            pltpu.make_async_remote_copy(
                src_ref=src.at[d, slot_src], dst_ref=src.at[d, slot_dst],
                send_sem=send_sems.at[i, sem_i, d],
                recv_sem=recv_sems.at[i, sem_i, d],
                device_id={axis: send_to[d]},
                device_id_type=pltpu.DeviceIdType.MESH)
            for i, src in enumerate((kbuf_ref, vbuf_ref, segk_ref))
        ]

    # ---- round bookkeeping (once per round) --------------------------
    @pl.when(jnp.logical_and(first_cell, r == 0))
    def _round0_setup():
        # every ring member must have entered the kernel (allocated
        # its slabs) before anyone RDMAs into it
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(bar, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(bar, 2)
        # local KV halves -> ring slot 0 (what round 0 sends from)
        cps = [pltpu.make_async_copy(src.at[d], dst.at[d, 0],
                                     kv_sems.at[i, d])
               for d in range(n_dirs)
               for i, (src, dst) in enumerate(
                   ((kin_ref, kbuf_ref), (vin_ref, vbuf_ref),
                    (segin_ref, segk_ref)))]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    @pl.when(jnp.logical_and(first_cell, r > 0))
    def _round_start():
        # this round's KV landed in [slot]; our forwarding sends of
        # [nxt] (issued in round r-1 from slot (r-1)%2 == nxt) have
        # drained, so each direction's sender may overwrite [nxt]
        for d in range(n_dirs):
            for desc in slab_rdma(d, nxt, slot, slot):
                desc.wait()

        @pl.when(r < n - 1)
        def _free_slots():
            # matched by each sender's _wait_free at its round r
            # (sends happen at rounds 0..n-2); an unguarded signal at
            # round n-1 would leave the semaphores non-zero at exit
            for d in range(n_dirs):
                pltpu.semaphore_signal(
                    free_sems.at[d], inc=1,
                    device_id={axis: from_of[d]},
                    device_id_type=pltpu.DeviceIdType.MESH)

    @pl.when(jnp.logical_and(first_cell, r < n - 1))
    def _round_send():
        # overlap: the sends for round r+1 fly while round r computes
        for d in range(n_dirs):
            @pl.when(r > 0)
            def _wait_free(d=d):
                pltpu.semaphore_wait(free_sems.at[d], 1)

            for desc in slab_rdma(d, slot, nxt, nxt):
                desc.start()

    # ---- this cell's KV slices: HBM slabs -> VMEM --------------------
    kv_cps = [c for d in range(n_dirs) for c in (
        pltpu.make_async_copy(kbuf_ref.at[d, slot, bi, hk],
                              k_vmem.at[d], kv_sems.at[0, d]),
        pltpu.make_async_copy(vbuf_ref.at[d, slot, bi, hk],
                              v_vmem.at[d], kv_sems.at[1, d]),
        pltpu.make_async_copy(segk_ref.at[d, slot, bi],
                              sk_vmem.at[d], kv_sems.at[2, d]),
    )]
    for c in kv_cps:
        c.start()

    # ---- cross-round accumulator state: HBM slab -> VMEM -------------
    @pl.when(r > 0)
    def _load_state():
        cps = [
            pltpu.make_async_copy(
                m_ref.at[bi, hk, :, pl.ds(qi * bq, bq)], m_vmem,
                misc_sems.at[0]),
            pltpu.make_async_copy(
                l_ref.at[bi, hk, :, pl.ds(qi * bq, bq)], l_vmem,
                misc_sems.at[1]),
            pltpu.make_async_copy(
                acc_ref.at[bi, hk, :, pl.ds(qi * bq, bq)], acc_vmem,
                misc_sems.at[2]),
        ]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    @pl.when(r == 0)
    def _init_state():
        m_vmem[...] = jnp.full(m_vmem.shape, NEG_INF, jnp.float32)
        l_vmem[...] = jnp.zeros(l_vmem.shape, jnp.float32)
        acc_vmem[...] = jnp.zeros(acc_vmem.shape, jnp.float32)

    for c in kv_cps:
        c.wait()

    # ---- flash-accumulate this q tile vs each direction's shard ------
    q_off = my * (n_qb * bq) + qi * bq
    seg_q = segq_ref[0, :, 0]              # [bq]
    n_kb = lch // bk

    for g in range(group):
        q = q_ref[0, 0, g].astype(jnp.float32) * scale     # [bq, hd]
        hd = q.shape[-1]
        carry = (m_vmem[g], l_vmem[g], acc_vmem[g])

        for d in range(n_dirs):
            # dir 0 holds the [0:lch] half of shard (my - r) % n;
            # dir 1 the [lch:lc] half of shard (my + r) % n
            src_dev = jax.lax.rem(my - r + n, n) if d == 0 \
                else jax.lax.rem(my + r, n)
            k_off = src_dev * lc + d * lch

            def body(j, carry, q=q, d=d, k_off=k_off):
                m, l_sum, acc = carry
                k = k_vmem[d, pl.ds(j * bk, bk), :].astype(jnp.float32)
                v = v_vmem[d, pl.ds(j * bk, bk), :]
                seg_k = sk_vmem[d, 0, pl.ds(j * bk, bk)]
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bq, bk]
                qg = q_off + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                kg = (k_off + j * bk
                      + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
                mask = (seg_q[:, None] == seg_k[None, :]) \
                    & (seg_q[:, None] != 0)
                if causal:
                    mask &= qg >= kg
                if sliding_window is not None:
                    mask &= (qg - kg) < sliding_window
                s = jnp.where(mask, s, NEG_INF)
                m_new = jnp.maximum(m, s.max(axis=1))
                p = jnp.exp(s - m_new[:, None])
                alpha = jnp.exp(m - m_new)
                l_new = l_sum * alpha + p.sum(axis=1)
                acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return m_new, l_new, acc_new

            carry = jax.lax.fori_loop(0, n_kb, body, carry)

        m, l_sum, acc = carry
        m_vmem[g] = m
        l_vmem[g] = l_sum
        acc_vmem[g] = acc

        @pl.when(r == n - 1)
        def _finalize(m=m, l_sum=l_sum, acc=acc, g=g):
            row_valid = m > NEG_INF / 2
            safe_l = jnp.where(l_sum > 0, l_sum, 1.0)
            out = jnp.where(row_valid[:, None], acc / safe_l[:, None],
                            0.0)
            o_vmem[g] = out.astype(o_vmem.dtype)

    # ---- state / output: VMEM -> HBM slabs ---------------------------
    @pl.when(r < n - 1)
    def _store_state():
        cps = [
            pltpu.make_async_copy(
                m_vmem, m_ref.at[bi, hk, :, pl.ds(qi * bq, bq)],
                misc_sems.at[0]),
            pltpu.make_async_copy(
                l_vmem, l_ref.at[bi, hk, :, pl.ds(qi * bq, bq)],
                misc_sems.at[1]),
            pltpu.make_async_copy(
                acc_vmem, acc_ref.at[bi, hk, :, pl.ds(qi * bq, bq)],
                misc_sems.at[2]),
        ]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    @pl.when(r == n - 1)
    def _store_out():
        cp = pltpu.make_async_copy(
            o_vmem, o_ref.at[bi, hk, :, pl.ds(qi * bq, bq)],
            misc_sems.at[3])
        cp.start()
        cp.wait()


def _plan_dirs(lc: int, block_k: int, want_bidir: bool):
    """(n_dirs, lch, bk): split the local shard across both ICI ring
    directions when each half still tiles; else one direction."""
    if want_bidir and lc % 2 == 0 and lc // 2 >= 8:
        try:
            return 2, lc // 2, _fit_block(lc // 2, block_k)
        except ValueError:
            pass  # the half has no tileable block; the full shard may
    return 1, lc, _fit_block(lc, block_k)


def _fused_local(q, k, v, seg, *, mesh, axis, n, scale, causal,
                 sliding_window, bq, bk, n_dirs, lch, interpret,
                 collective_id):
    """Per-device body under shard_map. Local shapes:
    q [b, lc, nq, hd], k/v [b, lc, nkv, hd], seg [b, lc]."""
    b, lc, nq, hd = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    n_qb = lc // bq

    qt = q.transpose(0, 2, 1, 3).reshape(b, nkv, group, lc, hd)
    segq = jnp.broadcast_to(seg[:, :, None], (b, lc, LANES))
    # dir-major KV halves: [nd, b, nkv, lch, hd] (contiguous split of
    # the sequence dim; nd == 1 keeps the whole shard in "half" 0)
    kt = k.transpose(0, 2, 1, 3).reshape(
        b, nkv, n_dirs, lch, hd).transpose(2, 0, 1, 3, 4)
    vt = v.transpose(0, 2, 1, 3).reshape(
        b, nkv, n_dirs, lch, hd).transpose(2, 0, 1, 3, 4)
    segk = jnp.broadcast_to(seg[:, None, :], (b, SUBLANES, lc)).reshape(
        b, SUBLANES, n_dirs, lch).transpose(2, 0, 1, 3)

    grid = (n, b, nkv, n_qb)
    kernel = functools.partial(
        _ring_kernel, n=n, axis=axis, bq=bq, bk=bk, group=group,
        n_dirs=n_dirs, scale=scale, causal=causal,
        sliding_window=sliding_window)

    any_spec = pl.BlockSpec(memory_space=pl.ANY)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, group, bq, hd),
                         lambda r, bi, hk, qi: (bi, hk, 0, qi, 0)),
            pl.BlockSpec((1, bq, LANES),
                         lambda r, bi, hk, qi: (bi, qi, 0)),  # segq
            any_spec, any_spec, any_spec,        # local k / v / segk
        ],
        out_shape=(
            # o + ring slabs + cross-round state, all manually DMA'd
            jax.ShapeDtypeStruct((b, nkv, group, lc, hd), q.dtype),
            jax.ShapeDtypeStruct((n_dirs, 2) + kt.shape[1:], kt.dtype),
            jax.ShapeDtypeStruct((n_dirs, 2) + vt.shape[1:], vt.dtype),
            jax.ShapeDtypeStruct((n_dirs, 2) + segk.shape[1:],
                                 segk.dtype),
            jax.ShapeDtypeStruct((b, nkv, group, lc), jnp.float32),
            jax.ShapeDtypeStruct((b, nkv, group, lc), jnp.float32),
            jax.ShapeDtypeStruct((b, nkv, group, lc, hd), jnp.float32),
        ),
        out_specs=(any_spec,) * 7,
        scratch_shapes=[
            pltpu.VMEM((n_dirs, lch, hd), k.dtype),     # k slices
            pltpu.VMEM((n_dirs, lch, hd), v.dtype),     # v slices
            pltpu.VMEM((n_dirs, SUBLANES, lch), seg.dtype),
            pltpu.VMEM((group, bq), jnp.float32),       # m
            pltpu.VMEM((group, bq), jnp.float32),       # l
            pltpu.VMEM((group, bq, hd), jnp.float32),   # acc
            pltpu.VMEM((group, bq, hd), q.dtype),       # out tile
            pltpu.SemaphoreType.DMA((3, n_dirs)),       # local KV
            pltpu.SemaphoreType.DMA((4,)),              # state / out
            pltpu.SemaphoreType.DMA((3, 2, n_dirs)),    # RDMA send
            pltpu.SemaphoreType.DMA((3, 2, n_dirs)),    # RDMA recv
            pltpu.SemaphoreType.REGULAR((n_dirs,)),     # slot free
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
        interpret=(pltpu.InterpretParams() if interpret else False),
    )(qt, segq, kt, vt, segk)

    o = out[0].reshape(b, nq, lc, hd).transpose(0, 2, 1, 3)
    return o.astype(q.dtype)


def ring_attention_fused(
    q: jnp.ndarray,        # [B, L, nq, hd] -- L sharded over `axis`
    k: jnp.ndarray,
    v: jnp.ndarray,
    seg_ids: jnp.ndarray,  # [B, L]
    mesh: Mesh,
    axis: str = "ctx",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: int = 256,
    block_k: int = 512,
    bidirectional: bool = True,
    interpret: bool = False,
    collective_id: int = 7,
) -> jnp.ndarray:
    """Drop-in for :func:`ring_attention` with the fused-RDMA kernel
    on the forward pass. Differentiable: the backward delegates to the
    shard_map/ppermute formulation's VJP (recompute-based -- the same
    work gradient checkpointing already schedules), so gradients are
    bit-identical to the unfused path while the forward gains the
    overlapped ring.

    ``bidirectional`` (default): each device's KV shard splits in two
    halves that counter-rotate (dir 0 rightward, dir 1 leftward), so
    both ICI ring directions carry traffic and per-round transfer time
    halves; falls back to one direction when a half would not tile.
    """
    if not FUSED_RING_SUPPORTED:
        raise NotImplementedError(FUSED_RING_UNSUPPORTED_REASON)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = mesh.shape[axis]
    if n == 1:
        return ring_attention(q, k, v, seg_ids, mesh, axis,
                              causal=causal, scale=scale,
                              sliding_window=sliding_window)
    lc = q.shape[1] // n
    bq = _fit_block(lc, block_q)
    n_dirs, lch, bk = _plan_dirs(lc, block_k, bidirectional)

    data_ax = "data" if "data" in mesh.axis_names \
        and mesh.shape["data"] > 1 else None
    model_ax = "model" if ("model" in mesh.axis_names
                           and mesh.shape["model"] > 1
                           and q.shape[2] % mesh.shape["model"] == 0
                           and k.shape[2] % mesh.shape["model"] == 0) \
        else None
    spec4 = P(data_ax, axis, model_ax, None)
    spec2 = P(data_ax, axis)

    local = functools.partial(
        _fused_local, mesh=mesh, axis=axis, n=n, scale=scale,
        causal=causal, sliding_window=sliding_window, bq=bq, bk=bk,
        n_dirs=n_dirs, lch=lch, interpret=interpret,
        collective_id=collective_id)
    fused_fwd = shard_map(local, mesh=mesh,
                          in_specs=(spec4, spec4, spec4, spec2),
                          out_specs=spec4, check_vma=False)

    @jax.custom_vjp
    def attn(q, k, v, seg):
        return fused_fwd(q, k, v, seg)

    def attn_fwd(q, k, v, seg):
        return fused_fwd(q, k, v, seg), (q, k, v, seg)

    def attn_bwd(res, g):
        q, k, v, seg = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: ring_attention(
                q_, k_, v_, seg, mesh, axis, causal=causal,
                scale=scale, sliding_window=sliding_window,
                block_q=block_q, block_k=block_k),
            q, k, v)
        return (*vjp(g), None)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn(q, k, v, seg_ids)
