"""Pallas flash attention over packed segments (TPU).

TPU-native replacement for the reference's flash-attn varlen kernels
(``realhf/impl/model/modules/attn.py:20-23``): tiled online-softmax
attention (flash-attention-2 schedule) with

- causal masking,
- segment-id masking for packed variable-length sequences (the
  cu_seqlens equivalent),
- GQA (query-head groups share KV heads),
- a custom VJP with Pallas backward kernels (dq and dkv passes),
  recomputing probabilities from the saved log-sum-exp.

Layout contract: q [B, L, nq, hd], k/v [B, L, nkv, hd], seg_ids [B, L]
(0 = padding). L must be a multiple of the Q block; hd should be a
multiple of 128 for MXU tiling (128 for llama-family models). K and V
are kept whole in VMEM per (batch, head) -- fine to L ~= 8k at
hd=128/bf16; longer contexts will stream KV via DMA (future work,
alongside ring attention over a context-parallel mesh axis).

Mosaic requires the last two dims of every block to be (8, 128)-tile
aligned, so 1D row metadata rides wider layouts: q-side segment ids
and the saved lse/delta are broadcast over a 128-lane axis, k-side
segment ids over an 8-sublane axis (same scheme as jax's bundled
flash kernel).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BQ = 256
DEFAULT_BK = 512
NEG_INF = -2.0 ** 30
LANES = 128
SUBLANES = 8


def _blocks(l: int, bq: int, bk: int):
    bq = min(bq, l)
    bk = min(bk, l)
    while l % bq:
        bq //= 2
    while l % bk:
        bk //= 2
    return max(bq, 8), max(bk, 8)


# ----------------------------------------------------------------------
# Forward
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref,  # inputs
                o_ref, lse_ref,  # outputs
                *, scale: float, bk: int, causal: bool):
    qi = pl.program_id(2)
    bq, hd = q_ref.shape[-2], q_ref.shape[-1]
    l = k_ref.shape[-2]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, hd]
    seg_q = segq_ref[0, :, 0]  # [BQ]
    q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, hd), jnp.float32)

    n_kv = pl.cdiv((qi + 1) * bq, bk) if causal else l // bk

    def body(j, carry):
        m, l_sum, acc = carry
        k = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)  # [BK, hd]
        v = v_ref[0, 0, pl.ds(j * bk, bk), :]
        seg_k = segk_ref[0, 0, pl.ds(j * bk, bk)]  # [BK]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, BK]
        k_idx = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (seg_q[:, None] == seg_k[None, :]) & (seg_q[:, None] != 0)
        if causal:
            mask &= q_idx >= k_idx
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l_sum * alpha + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l_sum, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    # Rows that never saw a valid key (all-padding rows) keep
    # m == NEG_INF: their p = exp(NEG_INF - NEG_INF) = 1 garbage must be
    # zeroed here. (Fully-masked *blocks* of otherwise-valid rows
    # self-correct via the alpha rescaling once a valid block arrives.)
    row_valid = m > NEG_INF / 2
    safe_l = jnp.where(l_sum > 0, l_sum, 1.0)
    out = jnp.where(row_valid[:, None], acc / safe_l[:, None], 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)
    lse = jnp.where(row_valid, m + jnp.log(safe_l), NEG_INF)
    lse_ref[0, 0] = jnp.broadcast_to(lse[:, None], (bq, LANES))


def _expand_segments(seg_ids):
    """seg [B, L] -> lane-broadcast q view [B, L, LANES] and
    sublane-broadcast kv view [B, SUBLANES, L]."""
    b, l = seg_ids.shape
    segq = jnp.broadcast_to(seg_ids[:, :, None], (b, l, LANES))
    segk = jnp.broadcast_to(seg_ids[:, None, :], (b, SUBLANES, l))
    return segq, segk


def _flash_fwd(q, k, v, seg_ids, scale, causal, bq, bk):
    b, l, nq, hd = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    bq, bk = _blocks(l, bq, bk)

    qt = q.transpose(0, 2, 1, 3)  # [B, nq, L, hd]
    kt = k.transpose(0, 2, 1, 3)  # [B, nkv, L, hd]
    vt = v.transpose(0, 2, 1, 3)
    segq, segk = _expand_segments(seg_ids)

    grid = (b, nq, l // bq)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bk=bk, causal=causal),
        out_shape=(
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, nq, l, LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda bi, h, qi: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, l, hd),
                         lambda bi, h, qi, g=group: (bi, h // g, 0, 0)),
            pl.BlockSpec((1, 1, l, hd),
                         lambda bi, h, qi, g=group: (bi, h // g, 0, 0)),
            pl.BlockSpec((1, bq, LANES), lambda bi, h, qi: (bi, qi, 0)),
            pl.BlockSpec((1, SUBLANES, l), lambda bi, h, qi: (bi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, bq, hd), lambda bi, h, qi: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda bi, h, qi: (bi, h, qi, 0)),
        ),
    )(qt, kt, vt, segq, segk)
    return out.transpose(0, 2, 1, 3), lse


# ----------------------------------------------------------------------
# Backward
# ----------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, do_ref,
                   lse_ref, delta_ref, dq_ref,
                   *, scale: float, bk: int, causal: bool):
    qi = pl.program_id(2)
    bq, hd = q_ref.shape[-2], q_ref.shape[-1]
    l = k_ref.shape[-2]

    q = q_ref[0, 0].astype(jnp.float32) * scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    seg_q = segq_ref[0, :, 0]
    q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    n_kv = pl.cdiv((qi + 1) * bq, bk) if causal else l // bk

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        seg_k = segk_ref[0, 0, pl.ds(j * bk, bk)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_idx = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (seg_q[:, None] == seg_k[None, :]) & (seg_q[:, None] != 0)
        if causal:
            mask &= q_idx >= k_idx
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kv, body, jnp.zeros((bq, hd), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref,
                    *, scale: float, bq: int, causal: bool):
    ki = pl.program_id(2)
    bk, hd = k_ref.shape[-2], k_ref.shape[-1]
    l = q_ref.shape[-2]

    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    seg_k = segk_ref[0, 0, pl.ds(ki * bk, bk)]
    k_idx = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    start_q = (ki * bk) // bq if causal else 0
    n_q = l // bq

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(j * bq, bq), :].astype(jnp.float32) * scale
        do = do_ref[0, 0, pl.ds(j * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * bq, bq), 0]
        delta = delta_ref[0, 0, pl.ds(j * bq, bq), 0]
        seg_q = segq_ref[0, pl.ds(j * bq, bq), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        q_idx = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        mask = (seg_q[:, None] == seg_k[None, :]) & (seg_q[:, None] != 0)
        if causal:
            mask &= q_idx >= k_idx
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, hd), jnp.float32)
    dv0 = jnp.zeros((bk, hd), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, n_q, body, (dk0, dv0))
    # Per-q-head partials; summed over each KV group outside (race-free).
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, bq, bk):
    q, k, v, seg_ids, out, lse = res
    do = g
    b, l, nq, hd = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    bq_, bk_ = _blocks(l, bq, bk)

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    segq, segk = _expand_segments(seg_ids)

    delta = (ot.astype(jnp.float32) * dot.astype(jnp.float32)).sum(-1)
    delta = jnp.broadcast_to(delta[..., None], (b, nq, l, LANES))

    grid_q = (b, nq, l // bq_)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, bk=bk_,
                          causal=causal),
        out_shape=jax.ShapeDtypeStruct(qt.shape, jnp.float32),
        grid=grid_q,
        in_specs=[
            pl.BlockSpec((1, 1, bq_, hd), lambda bi, h, qi: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, l, hd),
                         lambda bi, h, qi, g_=group: (bi, h // g_, 0, 0)),
            pl.BlockSpec((1, 1, l, hd),
                         lambda bi, h, qi, g_=group: (bi, h // g_, 0, 0)),
            pl.BlockSpec((1, bq_, LANES), lambda bi, h, qi: (bi, qi, 0)),
            pl.BlockSpec((1, SUBLANES, l), lambda bi, h, qi: (bi, 0, 0)),
            pl.BlockSpec((1, 1, bq_, hd), lambda bi, h, qi: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, bq_, LANES),
                         lambda bi, h, qi: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, bq_, LANES),
                         lambda bi, h, qi: (bi, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq_, hd),
                               lambda bi, h, qi: (bi, h, qi, 0)),
    )(qt, kt, vt, segq, segk, dot, lse, delta)

    grid_k = (b, nq, l // bk_)
    dk_partial, dv_partial = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, bq=bq_,
                          causal=causal),
        out_shape=(
            jax.ShapeDtypeStruct((b, nq, l, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, nq, l, hd), jnp.float32),
        ),
        grid=grid_k,
        in_specs=[
            pl.BlockSpec((1, 1, l, hd), lambda bi, h, ki: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, bk_, hd),
                         lambda bi, h, ki, g_=group: (bi, h // g_, ki, 0)),
            pl.BlockSpec((1, 1, bk_, hd),
                         lambda bi, h, ki, g_=group: (bi, h // g_, ki, 0)),
            pl.BlockSpec((1, l, LANES), lambda bi, h, ki: (bi, 0, 0)),
            pl.BlockSpec((1, SUBLANES, l), lambda bi, h, ki: (bi, 0, 0)),
            pl.BlockSpec((1, 1, l, hd), lambda bi, h, ki: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, l, LANES), lambda bi, h, ki: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, l, LANES), lambda bi, h, ki: (bi, h, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, bk_, hd), lambda bi, h, ki: (bi, h, ki, 0)),
            pl.BlockSpec((1, 1, bk_, hd), lambda bi, h, ki: (bi, h, ki, 0)),
        ),
    )(qt, kt, vt, segq, segk, dot, lse, delta)

    # Sum q-head partials within each KV group.
    dk = dk_partial.reshape(b, nkv, group, l, hd).sum(2).transpose(0, 2, 1, 3)
    dv = dv_partial.reshape(b, nkv, group, l, hd).sum(2).transpose(0, 2, 1, 3)
    dq_ = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    return (dq_, dk.astype(k.dtype), dv.astype(v.dtype), None)


# ----------------------------------------------------------------------
# Public API
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, seg_ids, scale, causal, bq, bk):
    out, _ = _flash_fwd(q, k, v, seg_ids, scale, causal, bq, bk)
    return out


def _flash_attention_fwd(q, k, v, seg_ids, scale, causal, bq, bk):
    out, lse = _flash_fwd(q, k, v, seg_ids, scale, causal, bq, bk)
    return out, (q, k, v, seg_ids, out, lse)


_flash_attention.defvjp(
    _flash_attention_fwd,
    lambda scale, causal, bq, bk, res, g: _flash_bwd(
        res, g, scale, causal, bq, bk))


def flash_attention(q, k, v, seg_ids, *, causal: bool = True,
                    scale: Optional[float] = None,
                    logits_soft_cap: Optional[float] = None,
                    block_q: int = DEFAULT_BQ,
                    block_k: int = DEFAULT_BK) -> jnp.ndarray:
    """Packed-segment flash attention; drop-in for
    `ops.attention.packed_attention_xla` on TPU."""
    if logits_soft_cap is not None:
        raise NotImplementedError(
            "soft cap not yet supported by the flash kernel; use the XLA "
            "path (packed_attention(..., use_flash=False)).")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_attention(q, k, v, seg_ids.astype(jnp.int32),
                            float(scale), causal, block_q, block_k)
