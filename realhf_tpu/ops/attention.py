"""Attention over packed variable-length sequences.

TPU-native replacement for the reference's flash-attn usage
(``realhf/impl/model/modules/attn.py:20-23``): packed batches carry
segment ids instead of cu_seqlens -- tokens attend only within their
own segment, causally. Two paths:

- ``packed_attention``: training/prefill attention on ``[B, L]``
  packed streams. Default implementation is pure XLA (einsum + fp32
  softmax with segment masking); a Pallas flash kernel
  (``realhf_tpu.ops.flash_attention``) is used on TPU for long L.
- ``decode_attention``: single-token decode against a padded KV cache
  (replaces ``flash_attn_with_kvcache``).

Segment id 0 marks padding; valid segments are >= 1.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from realhf_tpu.base.backend import pallas_enabled

NEG_INF = -2.0 ** 30  # large finite value; -inf breaks softmax for all-masked rows


def _segment_mask(seg_q: jnp.ndarray, seg_k: jnp.ndarray,
                  causal: bool,
                  sliding_window: Optional[int] = None) -> jnp.ndarray:
    """[B, Lq, Lk] bool mask: same non-zero segment (+ causality,
    + optional sliding window).

    Within a packed stream, positions inside a segment are contiguous,
    so the stream-index difference equals the in-segment position
    difference and the (q_idx - k_idx) < window test is exact.
    """
    mask = (seg_q[:, :, None] == seg_k[:, None, :]) & (seg_q[:, :, None] != 0)
    lq, lk = seg_q.shape[1], seg_k.shape[1]
    idx_q = jnp.arange(lq)[:, None]
    idx_k = jnp.arange(lk)[None, :]
    if causal:
        mask = mask & (idx_q >= idx_k)[None]
    if sliding_window is not None:
        mask = mask & ((idx_q - idx_k) < sliding_window)[None]
    return mask


def packed_attention_xla(
    q: jnp.ndarray,  # [B, L, nq, hd]
    k: jnp.ndarray,  # [B, L, nkv, hd]
    v: jnp.ndarray,  # [B, L, nkv, hd]
    seg_ids: jnp.ndarray,  # [B, L] int32, 0 = padding
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Reference XLA implementation; O(L^2) scores in fp32.

    GQA is expressed by grouping query heads over each KV head so the
    einsum keeps a single contraction (MXU-friendly).
    """
    b, l, nq, hd = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    scale = scale if scale is not None else hd ** -0.5

    qg = q.reshape(b, l, nkv, group, hd)
    # [B, nkv, g, Lq, Lk]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    mask = _segment_mask(seg_ids, seg_ids, causal,
                         sliding_window)[:, None, None]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, l, nq, hd).astype(q.dtype)


def packed_attention(q, k, v, seg_ids, *, causal=True, scale=None,
                     logits_soft_cap=None, sliding_window=None,
                     use_flash: Optional[bool] = None):
    """Dispatch between the Pallas flash kernel (TPU) and the XLA path.

    ``use_flash=None`` auto-selects: flash on TPU backends when shapes
    meet the kernel's tiling constraints, XLA otherwise (CPU tests).
    """
    if use_flash is None:
        use_flash = (pallas_enabled()
                     and q.shape[1] % 128 == 0 and q.shape[3] >= 64
                     # the flash kernel requires a static python scale
                     # and has no soft-cap / sliding-window support
                     and logits_soft_cap is None
                     and sliding_window is None
                     and (scale is None or isinstance(scale, (int, float))))
    if use_flash:
        assert sliding_window is None, \
            "flash kernel has no sliding-window support yet"
        try:
            from realhf_tpu.ops.flash_attention import flash_attention
        except ImportError:
            flash_attention = None
        if flash_attention is not None:
            return flash_attention(q, k, v, seg_ids, causal=causal,
                                   scale=scale,
                                   logits_soft_cap=logits_soft_cap)
    return packed_attention_xla(q, k, v, seg_ids, causal=causal, scale=scale,
                                logits_soft_cap=logits_soft_cap,
                                sliding_window=sliding_window)


def make_sharded_attention(mesh, inner=None):
    """Factory for a packed-attention fn that partitions the Pallas
    flash kernel over a dp x tp mesh with `shard_map` (B over "data",
    heads over "model"; L stays whole -- sequence sharding is ring
    attention's job). A bare pallas_call under GSPMD has no
    partitioning rule, so without this the sharded forward would
    gather full Q/K/V onto every device. Engines install this as
    ``attention_fn`` on non-trivial TPU meshes.

    Falls back to the XLA path (which GSPMD partitions natively) when
    shapes do not divide the mesh or the scale is traced. ``inner``
    overrides the per-shard implementation (tests inject the
    interpret-mode kernel)."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    local = inner or packed_attention

    def attn(q, k, v, seg_ids, causal=True, scale=None,
             sliding_window=None):
        b, _, nq, _ = q.shape
        nkv = k.shape[2]
        if dp * tp == 1:
            return local(q, k, v, seg_ids, causal=causal, scale=scale,
                         sliding_window=sliding_window)
        if (b % dp or nq % tp or nkv % tp
                or not (scale is None
                        or isinstance(scale, (int, float)))):
            return packed_attention_xla(
                q, k, v, seg_ids, causal=causal, scale=scale,
                sliding_window=sliding_window)

        extra = [a for a in mesh.axis_names
                 if a not in (DATA_AXIS, MODEL_AXIS)]
        axis_names = (set(mesh.axis_names)
                      if all(mesh.shape[a] == 1 for a in extra)
                      else {DATA_AXIS, MODEL_AXIS})

        @_partial(jax.shard_map, mesh=mesh,
                  axis_names=axis_names,
                  in_specs=(P(DATA_AXIS, None, MODEL_AXIS, None),
                            P(DATA_AXIS, None, MODEL_AXIS, None),
                            P(DATA_AXIS, None, MODEL_AXIS, None),
                            P(DATA_AXIS, None)),
                  out_specs=P(DATA_AXIS, None, MODEL_AXIS, None),
                  # pallas_call outputs carry no varying-axes metadata
                  check_vma=False)
        def run(q_l, k_l, v_l, seg_l):
            return local(q_l, k_l, v_l, seg_l, causal=causal,
                         scale=scale, sliding_window=sliding_window)

        return run(q, k, v, seg_ids)

    return attn


def decode_attention(
    q: jnp.ndarray,        # [B, nq, hd] -- one new token per stream
    k_cache: jnp.ndarray,  # [B, nkv, S, hd] (head-major)
    v_cache: jnp.ndarray,  # [B, nkv, S, hd]
    valid_mask: jnp.ndarray,  # [B, S] bool: which cache slots hold real
                              # tokens (left-padded prompts leave invalid
                              # low slots, so a prefix length is not enough)
    *,
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,  # [B] int32 current write index,
                                         # required with sliding_window
    mesh=None,  # partition the pallas kernel over a dp x tp mesh
) -> jnp.ndarray:
    """Single-step decode attention against a padded KV cache.

    The caller has already written the new token's K/V (and marked its
    slot valid). Replaces `flash_attn_with_kvcache`
    (reference ``attn.py:238``). Cache slot indices are sequential
    stream positions, so the sliding window keeps slots in
    ``(slot - window, slot]``.
    """
    b, nq, hd = q.shape
    nkv, s = k_cache.shape[1], k_cache.shape[2]
    group = nq // nkv

    # Pallas flash-decode on TPU: single tiled pass over the cache, no
    # [B, nq, S] score tensor. Routing (bare / head-sharded /
    # KV-sequence-split shard_map) lives in one dispatcher shared with
    # the stacked path (ops/decode_attention.run_decode_kernels);
    # None = no kernel partitioning applies -> the XLA path below,
    # which GSPMD partitions itself.
    if pallas_enabled() and hd >= 64 and logits_soft_cap is None:
        try:
            from realhf_tpu.ops.decode_attention import (
                run_decode_kernels,
            )
            out = run_decode_kernels(
                mesh, q, (k_cache, v_cache), valid_mask, slot, None,
                stacked=False, scale=scale,
                sliding_window=sliding_window)
            if out is not None:
                return out
        except ImportError:
            pass

    scale = scale if scale is not None else hd ** -0.5

    qg = q.reshape(b, nkv, group, hd)
    scores = jnp.einsum("bhgd,bhkd->bhgk", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    keep = valid_mask
    if sliding_window is not None:
        assert slot is not None, "sliding_window decode needs slot indices"
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        keep = keep & ((slot[:, None] - idx) < sliding_window)
    scores = jnp.where(keep[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgk,bhkd->bhgd", probs.astype(v_cache.dtype), v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, nq, hd).astype(q.dtype)
