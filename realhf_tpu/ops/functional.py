"""Shared tensor ops over packed streams.

Parity with reference ``realhf/impl/model/utils/functional.py``:
next-token logprob gathering (:165), masked normalization (:227),
logits masking (:214) -- expressed on the framework's [S, L] packed
stream layout. The vocab-parallel cross entropy of the reference
(``modules.py:1050``) is unnecessary: the head matmul + log_softmax
under GSPMD shard the vocab dim and XLA inserts the reductions.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.transformer import head_weight


def shifted_logprobs_from_hidden(
    cfg: TransformerConfig,
    params,
    hidden: jnp.ndarray,      # [S, L, H] final hidden states
    input_ids: jnp.ndarray,   # [S, L]
    seg_ids: jnp.ndarray,     # [S, L]
    *,
    chunk: int = 1024,
    temperature: float = 1.0,
    logits_mask: Optional[jnp.ndarray] = None,  # [S, L, V] bool, True=allowed
) -> jnp.ndarray:
    """Log p(input_ids[t+1] | ...) at every position t, zero where t+1
    starts a different segment or is padding.

    Computed in chunks along L so the full [S, L, V] logits tensor is
    never materialized (the fused-CE trick; reference gathers shifted
    logprobs after a full logits pass, functional.py:165).

    Returns [S, L] fp32; position t holds the logprob of token t+1.
    The last position of each segment (and pads) hold 0.
    """
    s, l, h = hidden.shape
    w = head_weight(cfg, params).astype(hidden.dtype)

    labels = jnp.concatenate(
        [input_ids[:, 1:], jnp.zeros((s, 1), input_ids.dtype)], axis=1)
    valid = jnp.concatenate(
        [(seg_ids[:, 1:] == seg_ids[:, :-1]) & (seg_ids[:, 1:] != 0),
         jnp.zeros((s, 1), bool)], axis=1)

    n_chunks = max(1, (l + chunk - 1) // chunk)
    pad_l = n_chunks * chunk - l
    if pad_l:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad_l), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad_l)))
        if logits_mask is not None:
            logits_mask = jnp.pad(logits_mask, ((0, 0), (0, pad_l), (0, 0)),
                                  constant_values=True)

    hidden_c = hidden.reshape(s, n_chunks, chunk, h).swapaxes(0, 1)
    labels_c = labels.reshape(s, n_chunks, chunk).swapaxes(0, 1)
    if logits_mask is not None:
        mask_c = logits_mask.reshape(s, n_chunks, chunk, -1).swapaxes(0, 1)
        xs = (hidden_c, labels_c, mask_c)
    else:
        xs = (hidden_c, labels_c)

    def body(_, x):
        if logits_mask is not None:
            hc, lc, mc = x
        else:
            hc, lc = x
            mc = None
        logits = jnp.einsum("slh,hv->slv", hc, w,
                            preferred_element_type=jnp.float32)
        if logits.shape[-1] != cfg.vocab_size:  # tp-padded vocab
            logits = logits[..., :cfg.vocab_size]
        if temperature != 1.0:
            logits = logits / temperature
        if mc is not None:
            logits = jnp.where(mc, logits, -1e30)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return None, jnp.take_along_axis(logp, lc[..., None], axis=-1)[..., 0]

    _, lp = jax.lax.scan(body, None, xs)
    lp = lp.swapaxes(0, 1).reshape(s, n_chunks * chunk)[:, :l]
    return jnp.where(valid, lp, 0.0)


def masked_normalization(
    x: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    unbiased: bool = False,
    eps: float = 1e-5,
    high_precision: bool = True,
) -> jnp.ndarray:
    """Normalize x to zero mean / unit std over masked entries.

    Under pjit the arrays are global, so the "all-reduce over DP+TP"
    of the reference (functional.py:227) is implicit.
    """
    dtype = jnp.float64 if (high_precision and
                            jax.config.read("jax_enable_x64")) else jnp.float32
    xf = x.astype(dtype)
    if mask is None:
        factor = jnp.asarray(x.size, dtype)
        mean = xf.sum() / factor
        mean_sq = (xf ** 2).sum() / factor
    else:
        m = mask.astype(dtype)
        factor = m.sum()
        mean = (xf * m).sum() / factor
        mean_sq = (xf ** 2 * m).sum() / factor
    var = mean_sq - mean ** 2
    if unbiased:
        var = var * factor / (factor - 1)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if mask is not None:
        out = out * m
    return out.astype(x.dtype)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    m = mask.astype(jnp.float32)
    return (x.astype(jnp.float32) * m).sum() / jnp.maximum(m.sum(), 1.0)


def entropy_from_hidden(cfg, params, hidden, *, chunk: int = 1024,
                        temperature: float = 1.0) -> jnp.ndarray:
    """Per-position policy entropy, chunked like shifted logprobs."""
    s, l, h = hidden.shape
    w = head_weight(cfg, params).astype(hidden.dtype)
    n_chunks = max(1, (l + chunk - 1) // chunk)
    pad_l = n_chunks * chunk - l
    if pad_l:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad_l), (0, 0)))
    hidden_c = hidden.reshape(s, n_chunks, chunk, h).swapaxes(0, 1)

    def body(_, hc):
        logits = jnp.einsum("slh,hv->slv", hc, w,
                            preferred_element_type=jnp.float32) / temperature
        if logits.shape[-1] != cfg.vocab_size:  # tp-padded vocab
            logits = logits[..., :cfg.vocab_size]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return None, -(jnp.exp(logp) * logp).sum(-1)

    _, ent = jax.lax.scan(body, None, hidden_c)
    return ent.swapaxes(0, 1).reshape(s, n_chunks * chunk)[:, :l]
