"""Per-kernel engaged/fallback disposition (ROADMAP weak #2).

Every handwritten Pallas kernel has an XLA fallback, which makes
"kernel silently not engaged" a first-contact risk on new hardware:
a wrong gate and the bench measures the fallback while the record
claims the kernel. ``kernel_dispositions()`` evaluates the SAME gates
the dispatch sites use and reports, per kernel, whether it would
engage and in which mode -- the table lands in every BENCH payload
(``kernel_disposition``) and drives the skip reasons of the
compiled-mode CI tier (tests/ops/test_compiled_kernels.py).

Modes:
  - "compiled":  real Mosaic lowering on a TPU backend
  - "interpret": Pallas interpret emulation (CPU CI wiring coverage)
  - "xla":       the kernel does not engage; the XLA path runs
"""

import os
from typing import Any, Dict

KERNELS = (
    "flash_attention",               # ops/flash_attention.py (packed fwd/bwd)
    "flash_decode_attention",        # ops/decode_attention.py per-layer
    "flash_decode_attention_stacked",  # scalar-prefetch stacked decode
    "ring_attention_fused",          # ops/ring_attention_fused.py
)


def _base_mode() -> Dict[str, Any]:
    """Gate shared by all kernels: base/backend.pallas_enabled()."""
    import jax

    if os.environ.get("REALHF_TPU_DISABLE_PALLAS") == "1":
        return dict(mode="xla", engaged=False,
                    reason="REALHF_TPU_DISABLE_PALLAS=1 forces the "
                           "GSPMD/XLA paths (A-B rig)")
    backend = jax.default_backend()
    if backend == "tpu":
        return dict(mode="compiled", engaged=True,
                    reason="TPU backend: Mosaic-compiled kernels")
    if os.environ.get("REALHF_TPU_FORCE_PALLAS") == "1":
        return dict(mode="interpret", engaged=True,
                    reason=f"backend '{backend}' with "
                           "REALHF_TPU_FORCE_PALLAS=1: interpret-mode "
                           "emulation (wiring coverage, not perf)")
    return dict(mode="xla", engaged=False,
                reason=f"backend '{backend}' cannot lower Mosaic "
                       "kernels and REALHF_TPU_FORCE_PALLAS is unset")


def kernel_dispositions() -> Dict[str, Dict[str, Any]]:
    """Evaluate each kernel's engagement gate on the CURRENT backend;
    returns {kernel: {mode, engaged, reason}} (keys sorted for a
    stable payload diff)."""
    base = _base_mode()
    out: Dict[str, Dict[str, Any]] = {k: dict(base) for k in KERNELS}

    # The fused ring kernel has two extra gates: a jax-version feature
    # probe and an explicit opt-in (validated-on-silicon policy).
    from realhf_tpu.ops.ring_attention_fused import (
        FUSED_RING_SUPPORTED,
        FUSED_RING_UNSUPPORTED_REASON,
    )
    fused = out["ring_attention_fused"]
    if not FUSED_RING_SUPPORTED:
        fused.update(mode="xla", engaged=False,
                     reason=FUSED_RING_UNSUPPORTED_REASON)
    elif os.environ.get("REALHF_TPU_FUSED_RING") != "1":
        fused.update(mode="xla", engaged=False,
                     reason="REALHF_TPU_FUSED_RING unset (kernel is "
                            "opt-in until validated on multi-chip "
                            "hardware); shard_map ring runs instead")

    return {k: out[k] for k in sorted(out)}
