"""Token sampling: temperature, top-k, top-p, greedy, logits mask.

Parity with reference ``realhf/impl/model/utils/logits_warper.py``
(top_k_top_p_logits:203) and the sampling step of
``nn/real_llm_generate.py:genstep:26``, including the logits-mask
output that PPO replays during inference for numerical consistency
(reference model_api.py:57-67).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class GenerationHyperparameters:
    """Sampling configuration (reference ``model_api.py:57`` /
    GenerationHyperparameters)."""
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    temperature: float = 1.0
    # Whether generate() returns the per-step logits mask so that later
    # inference passes can reproduce exactly the sampled distribution.
    force_no_logits_mask: bool = False

    def __post_init__(self):
        if not self.greedy and self.temperature <= 0.0:
            raise ValueError(
                "temperature must be > 0 for sampling; use greedy=True "
                "for deterministic decoding.")


def top_k_top_p_logits(logits: jnp.ndarray, top_k: int = 0,
                       top_p: float = 1.0) -> jnp.ndarray:
    """Mask logits outside the top-k / top-p nucleus to -inf.

    Semantics are UNIONED, matching the reference's generate path
    (real_llm_generate.py:82-87 calls top_k_top_p_logits with
    ordered=False): the nucleus is computed over the FULL softmax
    distribution, then intersected with the top-k set. With top-k
    active only a `lax.top_k` over the vocab runs (no full sort) --
    probabilities use the full-vocab logsumexp denominator, so the
    prefix-mass cutoff over the k survivors reproduces full-vocab
    top-p exactly (a nucleus needing more than k tokens is clamped to
    k by the union with top-k anyway). The full vocab sort only
    happens for pure top-p sampling. On a v5e decode step at 32k
    vocab, the full sort costs ~9 ms; `lax.top_k` ~0.3 ms.
    """
    v = logits.shape[-1]
    if (top_k <= 0 or top_k >= v) and top_p >= 1.0:
        return logits
    if 0 < top_k < v:
        topv, _ = jax.lax.top_k(logits, top_k)  # [..., k] descending
        if top_p < 1.0:
            # full-distribution probabilities of the k survivors
            probs = jnp.exp(
                topv - jax.nn.logsumexp(logits, axis=-1, keepdims=True))
            cum = jnp.cumsum(probs, axis=-1)
            # number of tokens needed to reach top_p mass (at least 1)
            include = cum - probs < top_p
            cutoff_idx = include.sum(-1) - 1
            cutoff = jnp.take_along_axis(topv, cutoff_idx[..., None],
                                         axis=-1)
        else:
            cutoff = topv[..., top_k - 1:top_k]
        return jnp.where(logits >= cutoff, logits, NEG_INF)
    # pure top-p: needs the whole sorted distribution
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    include = cum - probs < top_p
    cutoff_idx = include.sum(-1) - 1
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[..., None],
                                 axis=-1)
    return jnp.where(logits >= cutoff, logits, NEG_INF)


def sample_from_logits(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    gconfig: GenerationHyperparameters,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One sampling step -> (tokens [B], logits_mask [B, V] bool).

    The mask marks tokens that were sample-able after warping; replayed
    by PPO's inference pass (reference genstep:131-136).
    """
    if gconfig.greedy:
        tokens = jnp.argmax(logits, axis=-1)
        mask = jnp.ones_like(logits, dtype=bool)
        return tokens.astype(jnp.int32), mask
    warped = top_k_top_p_logits(logits / gconfig.temperature,
                                gconfig.top_k, gconfig.top_p)
    tokens = jax.random.categorical(key, warped, axis=-1)
    mask = warped > NEG_INF / 2
    return tokens.astype(jnp.int32), mask
