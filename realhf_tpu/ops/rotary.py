"""Rotary position embeddings.

Parity with reference ``realhf/impl/model/modules/rotary.py``
(RotaryEmbedding:121 + linear/dynamic-NTK scaling :175-242), computed
functionally: frequencies are derived from explicit position ids, so
packed sequences and KV-cache decode use the same code path.
"""

from typing import Optional, Tuple

import jax.numpy as jnp


def rotary_freqs(positions: jnp.ndarray, head_dim: int, base: float,
                 scaling: Optional[float] = None,
                 scaling_type: Optional[str] = None,
                 max_positions: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given integer positions.

    positions: any integer array shape ``S``; returns cos/sin of shape
    ``S + (head_dim // 2,)`` in fp32.
    """
    if scaling_type is not None and scaling is None:
        raise ValueError("rotary scaling_type set but scaling factor is None")
    if scaling_type == "linear":
        positions = positions / scaling
    elif scaling_type == "dynamic":
        if max_positions is None:
            raise ValueError("dynamic NTK rotary scaling requires max_positions")
        # Dynamic NTK: enlarge the base when sequences exceed the
        # trained context (reference rotary.py:206-242).
        seq_len = positions.max() + 1
        ratio = jnp.maximum(seq_len / max_positions, 1.0)
        dim = head_dim
        base = base * (scaling * ratio - (scaling - 1)) ** (dim / (dim - 2))
    elif scaling_type is not None:
        raise NotImplementedError(f"rotary scaling type {scaling_type}")
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
                 interleaved: bool = False) -> jnp.ndarray:
    """Rotate q or k. x: [..., n_heads, head_dim]; cos/sin broadcast over
    the head axis: [..., head_dim//2]."""
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    if interleaved:
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
    else:
        half = x.shape[-1] // 2
        x1 = x[..., :half]
        x2 = x[..., half:]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.concatenate([o1, o2], axis=-1)
    return out.astype(x.dtype)
