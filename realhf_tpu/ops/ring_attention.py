"""Ring attention: context parallelism over a mesh axis.

Fills the reference's explicitly-missing capability (context
parallelism is a TODO at ``realhf/impl/model/backend/megatron.py:60``;
max sequence length there is bounded by one TP group's activation
memory). Here the sequence dim is sharded over a "ctx" mesh axis:
each device holds L/ctx tokens of every stream, K/V shards rotate
around the ring with `lax.ppermute`, and partial attention results
merge with the online-softmax combine -- so attention memory and
compute scale 1/ctx per device while packed-segment and causal
semantics are preserved via global position offsets.

The per-round partial attention runs BLOCKWISE (flash-style online
softmax over [block_q, block_k] tiles) once the local shard exceeds a
block, so per-device attention memory is O(bq*bk) regardless of
context length -- 32k+ contexts train at ctx>=4 without ever
materializing [Lq_loc, Lk_loc] scores. Fusing the ring rounds into a
single Pallas kernel with overlapped RDMA
(pltpu.make_async_remote_copy) remains the next optimization.
"""

from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -2.0 ** 30


def _fit_block(lc: int, block: int) -> int:
    """Largest divisor of lc that is <= block (>= 1)."""
    b = min(block, lc)
    while lc % b:
        b -= 1
    return b


def _partial_attention(q, k, v, seg_q, seg_k, q_off, k_off, scale, causal,
                       sliding_window=None):
    """One ring step: q [B, Lq, nq, hd] vs k/v [B, Lk, nkv, hd] with
    global offsets; returns (m [B, nq, Lq], l, acc [B, nq, Lq, hd])."""
    b, lq, nq, hd = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    qg = (q * scale).reshape(b, lq, nkv, group, hd)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   k.astype(jnp.float32))
    s = s.reshape(b, nq, lq, -1)
    mask = (seg_q[:, :, None] == seg_k[:, None, :]) & (seg_q[:, :, None] != 0)
    qi = q_off + jnp.arange(lq)
    ki = k_off + jnp.arange(k.shape[1])
    if causal:
        mask = mask & (qi[:, None] >= ki[None, :])[None]
    if sliding_window is not None:
        # global stream indices make the window exact across ring steps
        mask = mask & ((qi[:, None] - ki[None, :]) < sliding_window)[None]
    s = jnp.where(mask[:, None], s, NEG_INF)
    m = s.max(axis=-1)  # [B, nq, Lq]
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    pv = p.reshape(b, nkv, group, lq, -1)
    acc = jnp.einsum("bhgqk,bkhd->bhgqd", pv, v.astype(jnp.float32))
    acc = acc.reshape(b, nq, lq, hd)
    return m, l, acc


def _combine(state, new):
    m0, l0, a0 = state
    m1, l1, a1 = new
    m = jnp.maximum(m0, m1)
    w0 = jnp.exp(m0 - m)
    w1 = jnp.exp(m1 - m)
    return m, l0 * w0 + l1 * w1, a0 * w0[..., None] + a1 * w1[..., None]


def _partial_attention_blockwise(q, k, v, seg_q, seg_k, q_off, k_off,
                                 scale, causal, sliding_window,
                                 bq, bk, vary=lambda x: x):
    """Blockwise (flash-style) version of ``_partial_attention``: the
    score matrix only ever exists as [B, nq, bq, bk] tiles, so one
    ring step's attention memory is O(bq*bk) instead of
    O(Lq_loc * Lk_loc) -- the piece that made 32k contexts OOM. Both
    scans have static trip counts and are reverse-differentiable."""
    b, lq, nq, hd = q.shape
    lk = k.shape[1]
    nqc, nkc = lq // bq, lk // bk

    # chunk axes to the front for scan
    qc = q.reshape(b, nqc, bq, nq, hd).transpose(1, 0, 2, 3, 4)
    sqc = seg_q.reshape(b, nqc, bq).transpose(1, 0, 2)
    kc = k.reshape(b, nkc, bk, *k.shape[2:]).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(b, nkc, bk, *v.shape[2:]).transpose(1, 0, 2, 3, 4)
    skc = seg_k.reshape(b, nkc, bk).transpose(1, 0, 2)

    def per_q_chunk(_, xs):
        qi, q_blk, sq_blk = xs

        def per_k_chunk(carry, ys):
            kj, k_blk, v_blk, sk_blk = ys
            part = _partial_attention(
                q_blk, k_blk, v_blk, sq_blk, sk_blk,
                q_off + qi * bq, k_off + kj * bk, scale, causal,
                sliding_window)
            return _combine(carry, part), None

        # vary: mark the carry device-varying over the sharded mesh
        # axes (shard_map vma tracking; see _vary in ring_attention)
        init = (vary(jnp.full((b, nq, bq), NEG_INF, jnp.float32)),
                vary(jnp.zeros((b, nq, bq), jnp.float32)),
                vary(jnp.zeros((b, nq, bq, hd), jnp.float32)))
        (m, l, acc), _ = jax.lax.scan(
            per_k_chunk, init,
            (jnp.arange(nkc), kc, vc, skc))
        return None, (m, l, acc)

    _, (m, l, acc) = jax.lax.scan(
        per_q_chunk, None, (jnp.arange(nqc), qc, sqc))
    # [nqc, B, nq, bq(, hd)] -> [B, nq, Lq(, hd)]
    m = m.transpose(1, 2, 0, 3).reshape(b, nq, lq)
    l = l.transpose(1, 2, 0, 3).reshape(b, nq, lq)
    acc = acc.transpose(1, 2, 0, 3, 4).reshape(b, nq, lq, hd)
    return m, l, acc


def ring_attention(
    q: jnp.ndarray,        # [B, L, nq, hd] -- L sharded over `axis`
    k: jnp.ndarray,
    v: jnp.ndarray,
    seg_ids: jnp.ndarray,  # [B, L]
    mesh: Mesh,
    axis: str = "ctx",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: int = 512,
    block_k: int = 512,
) -> jnp.ndarray:
    """Sequence-parallel attention over the given mesh axis.

    Call with GLOBAL arrays under jit; shard_map splits L over `axis`
    internally. Differentiable (shard_map + ppermute autodiff).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = mesh.shape[axis]
    lc = q.shape[1] // n
    # Keep the batch and head dims sharded over their own mesh axes
    # (when present) instead of replicating them into the shard_map.
    data_ax = "data" if "data" in mesh.axis_names and mesh.shape["data"] > 1 \
        else None
    model_ax = "model" if ("model" in mesh.axis_names
                           and mesh.shape["model"] > 1
                           and q.shape[2] % mesh.shape["model"] == 0
                           and k.shape[2] % mesh.shape["model"] == 0) \
        else None

    def local_fn(q, k, v, seg):
        # local shapes: q [b_loc, Lc, nq_loc, hd], seg [b_loc, Lc]
        b, _, nq, hd = q.shape
        idx = jax.lax.axis_index(axis)
        q_off = idx * lc

        def _vary(x):
            # Mark as device-varying over every sharded axis so the
            # fori_loop carry type stays stable (shard_map vma tracking):
            # the loop body mixes in q/k/v, which vary over all of them.
            axes = tuple(a for a in (axis, data_ax, model_ax)
                         if a is not None)
            if hasattr(jax.lax, "pvary"):
                return jax.lax.pvary(x, axes)
            return x

        m = _vary(jnp.full((b, nq, lc), NEG_INF, jnp.float32))
        lsum = _vary(jnp.zeros((b, nq, lc), jnp.float32))
        acc = _vary(jnp.zeros((b, nq, lc, hd), jnp.float32))

        # blockwise (flash-style) per-step attention once the local
        # shard outgrows one block -- long-context memory stays
        # O(block_q * block_k) per device. Blocks round down to
        # divisors of lc so the tiled path never silently degrades to
        # the dense [Lq_loc, Lk_loc] score tensor.
        bq_fit = _fit_block(lc, block_q)
        bk_fit = _fit_block(lc, block_k)
        blockwise = lc > bq_fit or lc > bk_fit

        def body(r, carry):
            m, lsum, acc, k, v, seg_k = carry
            src = (idx - r) % n  # whose KV shard we currently hold
            if blockwise:
                part = _partial_attention_blockwise(
                    q, k, v, seg, seg_k, q_off, src * lc, scale,
                    causal, sliding_window, bq_fit, bk_fit,
                    vary=_vary)
            else:
                part = _partial_attention(q, k, v, seg, seg_k, q_off,
                                          src * lc, scale, causal,
                                          sliding_window)
            m, lsum, acc = _combine((m, lsum, acc), part)
            perm = [(i, (i + 1) % n) for i in range(n)]
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
            seg_k = jax.lax.ppermute(seg_k, axis, perm)
            return m, lsum, acc, k, v, seg_k

        m, lsum, acc, _, _, _ = jax.lax.fori_loop(
            0, n, body, (m, lsum, acc, k, v, seg))
        safe = jnp.where(lsum > 0, lsum, 1.0)
        out = jnp.where((m > NEG_INF / 2)[..., None], acc / safe[..., None],
                        0.0)
        return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Lc, nq, hd]

    spec4 = P(data_ax, axis, model_ax, None)
    spec2 = P(data_ax, axis)
    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec2),
        out_specs=spec4,
    )(q, k, v, seg_ids)
