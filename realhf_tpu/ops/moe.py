"""Mixture-of-experts layer: routing, dispatch, expert GEMMs, losses.

TPU-native replacement for reference ``realhf/impl/model/modules/moe/``
(TopKRouter router.py:24, MoETokenDispatcher token_dispatcher.py:17,
GroupedMLP experts.py:98) and ``impl/model/utils/moe.py`` (aux losses
:13-166). Instead of permute/unpermute + grouped GEMM, dispatch is
expressed as dense one-hot einsums over a static expert-capacity axis
(XLA-friendly static shapes); expert GEMMs are one batched einsum over
the stacked [E, H, F] weights, which GSPMD shards over the "model"
axis (TP-sharded experts, the reference's layout) and can shard over
an expert axis for true EP.

Three dispatch modes:
- ``capacity_factor=None`` + ``use_grouped_gemm`` (default): RAGGED
  mode -- (token, k) pairs sorted by expert feed
  ``jax.lax.ragged_dot`` grouped GEMMs (the true grouped-GEMM
  equivalent of reference experts.py:98 GroupedMLP, lowered to TPU
  ragged matmuls). Exact (no token dropping), top-k cost only.
- ``capacity_factor=None`` + ``use_grouped_gemm=False``: dense mode --
  every expert sees every token, weighted by its gate (exact; E/topk
  times the FLOPs; the correctness reference for tests).
- ``capacity_factor=c``: capacity dispatch -- each expert processes at
  most c * T * topk / E tokens; overflow tokens are dropped from that
  expert (standard Switch/GShard semantics, reference
  topk_softmax_with_capacity, utils/moe.py:310).
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from realhf_tpu.models.config import MoEConfig, TransformerConfig


def router_probs(cfg_moe: MoEConfig, logits: jnp.ndarray,
                 key: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[T, E] logits -> (top-k probs [T, k], indices [T, k]).

    Default (aux_loss/none): softmax over all experts, take top-k,
    renormalize (Mixtral semantics, equivalent to the reference's
    topk_softmax_with_capacity). Sinkhorn routing selects indices from
    the sinkhorn-normalized logits WITHOUT gradient, while gate values
    come from the raw logits (sigmoid for k=1, softmax for k>1) --
    matching reference router.py:53-76.
    """
    logits = logits.astype(jnp.float32)
    if cfg_moe.input_jitter_eps and key is not None:
        noise = jax.random.uniform(
            key, logits.shape, minval=1.0 - cfg_moe.input_jitter_eps,
            maxval=1.0 + cfg_moe.input_jitter_eps)
        logits = logits * noise
    if cfg_moe.routing_type == "sinkhorn":
        routed = sinkhorn(jax.lax.stop_gradient(logits))
        _, top_idx = jax.lax.top_k(routed, cfg_moe.top_k)
        if cfg_moe.top_k == 1:
            top_probs = jax.nn.sigmoid(
                jnp.take_along_axis(logits, top_idx, axis=-1))
        else:
            sel = jnp.take_along_axis(logits, top_idx, axis=-1)
            top_probs = jax.nn.softmax(sel, axis=-1)
        return top_probs, top_idx
    probs = jax.nn.softmax(logits, axis=-1)
    top_probs, top_idx = jax.lax.top_k(probs, cfg_moe.top_k)
    top_probs = top_probs / jnp.maximum(
        top_probs.sum(-1, keepdims=True), 1e-9)
    return top_probs, top_idx


def sinkhorn(logits: jnp.ndarray, n_iters: int = 8,
             tol: float = 1e-4) -> jnp.ndarray:
    """Sinkhorn normalization of routing logits (reference
    utils/moe.py:69), fixed iteration count for jit."""
    cost = jnp.exp(logits)
    d0 = jnp.ones(cost.shape[0], jnp.float32)
    d1 = jnp.ones(cost.shape[1], jnp.float32)

    def body(_, carry):
        d0, d1 = carry
        d0 = 1.0 / (cost.shape[0] * (cost @ d1.reshape(-1, 1))[:, 0] + 1e-8)
        d1 = 1.0 / (cost.shape[1] * (d0 @ cost) + 1e-8)
        return d0, d1

    d0, d1 = jax.lax.fori_loop(0, n_iters, body, (d0, d1))
    return jnp.log(d1[None, :] * cost * d0[:, None] + 1e-20)


def load_balancing_loss(probs: jnp.ndarray, top_idx: jnp.ndarray,
                        n_experts: int, top_k: int,
                        valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Switch-transformer aux loss (reference
    switch_load_balancing_loss_func, utils/moe.py:13), over valid
    tokens only."""
    t = probs.shape[0]
    if valid is None:
        valid = jnp.ones((t,), jnp.float32)
    n = jnp.maximum(valid.sum(), 1.0)
    counts = jnp.zeros(n_experts, jnp.float32).at[top_idx.reshape(-1)].add(
        jnp.repeat(valid, top_idx.shape[1]))
    fraction_tokens = counts / (n * top_k)
    fraction_probs = (probs * valid[:, None]).sum(axis=0) / n
    return n_experts * (fraction_tokens * fraction_probs).sum()


def z_loss(logits: jnp.ndarray,
           valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Router z-loss (reference z_loss_func, utils/moe.py:54)."""
    z = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1) ** 2
    if valid is None:
        return z.mean()
    return (z * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def _expert_ffn(cfg: TransformerConfig, m: Dict, xs: jnp.ndarray
                ) -> jnp.ndarray:
    """Batched expert MLP: xs [E, C, H] -> [E, C, H] through stacked
    [E, H, F] weights (one einsum per projection = the grouped GEMM)."""
    from realhf_tpu.models.transformer import _activation
    cdt = xs.dtype
    gate = jnp.einsum("ech,ehf->ecf", xs, m["wg"].astype(cdt))
    up = jnp.einsum("ech,ehf->ecf", xs, m["wu"].astype(cdt))
    return jnp.einsum("ecf,efh->ech", _activation(cfg, gate) * up,
                      m["wd"].astype(cdt))


def ragged_dispatch_enabled(cfg: TransformerConfig) -> bool:
    """Single source of truth for whether the grouped-GEMM (ragged)
    dispatch path is active for this config."""
    return (cfg.mlp_type == "moe" and cfg.moe is not None
            and cfg.moe.capacity_factor is None
            and cfg.moe.use_grouped_gemm
            and hasattr(jax.lax, "ragged_dot"))


def _ragged_moe(cfg: TransformerConfig, m: Dict, xt: jnp.ndarray,
                top_probs: jnp.ndarray, top_idx: jnp.ndarray
                ) -> jnp.ndarray:
    """Grouped-GEMM dispatch: sort (token, k) pairs by expert, run
    ``jax.lax.ragged_dot`` per projection over the stacked [E, H, F]
    weights, scatter-add gate-weighted outputs back. Exact top-k MoE
    (reference GroupedMLP, experts.py:98) with static shapes."""
    from realhf_tpu.models.transformer import _activation
    t, h = xt.shape
    e = cfg.moe.num_experts
    k = cfg.moe.top_k
    cdt = xt.dtype

    flat_expert = top_idx.reshape(-1)                 # [T*k]
    order = jnp.argsort(flat_expert)                  # sort by expert
    tok_idx = order // k
    xs = xt[tok_idx]                                  # [T*k, H] sorted
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    gate = jax.lax.ragged_dot(xs, m["wg"].astype(cdt), group_sizes)
    up = jax.lax.ragged_dot(xs, m["wu"].astype(cdt), group_sizes)
    down = jax.lax.ragged_dot(_activation(cfg, gate) * up,
                              m["wd"].astype(cdt), group_sizes)
    gates_sorted = top_probs.reshape(-1)[order]       # pads carry 0
    weighted = down.astype(jnp.float32) * gates_sorted[:, None]
    return jnp.zeros((t, h), jnp.float32).at[tok_idx].add(weighted)


def moe_mlp_with_losses(cfg: TransformerConfig, m: Dict, x: jnp.ndarray,
                        rng: Optional[jax.Array] = None,
                        valid_mask: Optional[jnp.ndarray] = None,
                        ep_constraint=None
                        ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """MoE feed-forward over [B, L, H]; ``valid_mask`` [B, L] excludes
    padding tokens from routing, expert capacity, and the aux losses
    (pad positions carry real hidden states in the packed layout).

    ``ep_constraint`` (models/sharding.py moe_ep_constraint) pins the
    expert-major intermediates to the expert-parallel axis so GSPMD
    lowers dispatch/combine to all-to-alls; requires the capacity or
    dense dispatch mode."""
    moe = cfg.moe
    if moe.input_jitter_eps and rng is None:
        raise NotImplementedError(
            "input_jitter_eps requires threading an rng key through the "
            "forward pass, which is not wired yet; unset it.")
    b, l, h = x.shape
    t = b * l
    xt = x.reshape(t, h)
    if valid_mask is None:
        valid = jnp.ones((t,), jnp.float32)
    else:
        valid = valid_mask.reshape(t).astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    logits = (xt.astype(jnp.float32)
              @ m["router"].astype(jnp.float32))  # [T, E]
    probs_full = jax.nn.softmax(logits, axis=-1)
    top_probs, top_idx = router_probs(moe, logits, rng)
    # pads contribute nothing: zero their gates everywhere below
    top_probs = top_probs * valid[:, None]

    e = moe.num_experts
    ep = ep_constraint if ep_constraint is not None else (lambda a: a)
    if ragged_dispatch_enabled(cfg):
        if ep_constraint is not None:
            raise ValueError(
                "expert_parallel requires the capacity or dense "
                "dispatch mode; ragged grouped GEMMs cannot shard the "
                "group dim (set capacity_factor or "
                "use_grouped_gemm=False).")
        out = _ragged_moe(cfg, m, xt.astype(x.dtype), top_probs,
                          top_idx)
    elif moe.capacity_factor is None:
        # Dense mode: every expert over all tokens, gate-weighted.
        xs = ep(jnp.broadcast_to(xt[None], (e, t, h)).astype(x.dtype))
        expert_out = ep(_expert_ffn(cfg, m, xs))  # [E, T, H]
        gates = jnp.zeros((t, e), jnp.float32)
        gates = jax.vmap(lambda g, idx, p: g.at[idx].add(p))(
            gates, top_idx, top_probs)
        out = jnp.einsum("eth,te->th", expert_out.astype(jnp.float32), gates)
    else:
        cap = max(1, int(moe.capacity_factor * t * moe.top_k / e))
        # position of each (token, k) within its expert's capacity;
        # pads removed from the one-hot so they never occupy slots
        onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.int32)  # [T, k, E]
        onehot = onehot * valid.astype(jnp.int32)[:, None, None]
        flat = onehot.reshape(t * moe.top_k, e)
        pos = jnp.cumsum(flat, axis=0) * flat - 1  # [T*k, E]
        pos = pos.reshape(t, moe.top_k, e)
        within = (pos < cap) & (onehot > 0)
        # Each (token, expert) pair occupies at most one k slot, so the
        # k axis collapses before the big einsums: dispatch/combine are
        # [T, E, C], not [T, k, E, C].
        disp = within[..., None] & (
            pos[..., None] == jnp.arange(cap)[None, None, None, :])
        disp_tec = disp.sum(axis=1).astype(x.dtype)  # [T, E, C]
        expert_in = ep(jnp.einsum("th,tec->ech", xt.astype(x.dtype),
                                  disp_tec))
        expert_out = ep(_expert_ffn(cfg, m, expert_in))  # [E, C, H]
        combine = (disp.astype(jnp.float32)
                   * top_probs[:, :, None, None]).sum(axis=1)  # [T, E, C]
        out = jnp.einsum("ech,tec->th", expert_out.astype(jnp.float32),
                         combine)

    losses = {}
    if moe.routing_type == "aux_loss" and moe.aux_loss_coeff:
        losses["moe_aux_loss"] = moe.aux_loss_coeff * load_balancing_loss(
            probs_full, top_idx, e, moe.top_k, valid=valid)
    if moe.z_loss_coeff:
        losses["moe_z_loss"] = moe.z_loss_coeff * z_loss(logits, valid=valid)
    return out.reshape(b, l, h).astype(x.dtype), losses
