"""Pallas flash-decode attention against a padded KV cache (TPU).

Replaces the plain-XLA ``ops.attention.decode_attention`` on the hot
decode path (reference ``flash_attn_with_kvcache``, attn.py:238): one
query token per stream attends over the whole cache with a tiled
online softmax, never materializing the ``[B, nq, S]`` score tensor.
Decode is HBM-bandwidth bound -- the kernel makes a single pass over
K/V per step, with all query heads of a KV group (GQA) sharing each
loaded block.

Layout contract: q [B, nq, hd], k/v caches [B, S, nkv, hd],
keep-mask [B, S] (validity AND the sliding window -- precomputed in
XLA, it is O(B*S) elementwise). The query-group axis is padded up to
the fp32 sublane count (8); hd should be a multiple of 128 on real
TPUs. S is padded to the K block.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -2.0 ** 30
SUBLANES = 8
DEFAULT_BK = 512


def _decode_kernel(q_ref, k_ref, v_ref, keep_ref, o_ref, *, scale, bk):
    gp, hd = q_ref.shape[-2], q_ref.shape[-1]
    s = k_ref.shape[-2]

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [gp, hd]

    def body(j, carry):
        m, l_sum, acc = carry
        k = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * bk, bk), :]
        keep = keep_ref[0, 0, pl.ds(j * bk, bk)]  # [bk] int32

        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [gp, bk]
        sc = jnp.where((keep > 0)[None, :], sc, NEG_INF)

        m_new = jnp.maximum(m, sc.max(axis=1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l_sum * alpha + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((gp,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((gp,), jnp.float32)
    acc0 = jnp.zeros((gp, hd), jnp.float32)
    m, l_sum, acc = jax.lax.fori_loop(0, s // bk, body, (m0, l0, acc0))

    row_valid = m > NEG_INF / 2  # streams whose cache is still empty
    safe_l = jnp.where(l_sum > 0, l_sum, 1.0)
    out = jnp.where(row_valid[:, None], acc / safe_l[:, None], 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def flash_decode_attention(
    q: jnp.ndarray,        # [B, nq, hd]
    k_cache: jnp.ndarray,  # [B, S, nkv, hd]
    v_cache: jnp.ndarray,
    valid_mask: jnp.ndarray,  # [B, S] bool
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,  # [B] int32, with sliding_window
    block_k: int = DEFAULT_BK,
    interpret: bool = False,
) -> jnp.ndarray:
    b, nq, hd = q.shape
    s, nkv = k_cache.shape[1], k_cache.shape[2]
    group = nq // nkv
    scale = float(scale) if scale is not None else hd ** -0.5

    keep = valid_mask
    if sliding_window is not None:
        assert slot is not None, "sliding_window decode needs slot indices"
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        keep = keep & ((slot[:, None] - idx) < sliding_window)
    keep = keep.astype(jnp.int32)

    bk = min(block_k, s)
    pad_s = (-s) % bk
    if pad_s:
        zpad = jnp.zeros((b, pad_s, nkv, hd), k_cache.dtype)
        k_cache = jnp.concatenate([k_cache, zpad], axis=1)
        v_cache = jnp.concatenate([v_cache, zpad], axis=1)
        keep = jnp.concatenate(
            [keep, jnp.zeros((b, pad_s), jnp.int32)], axis=1)
        s += pad_s

    gp = max(SUBLANES, group)  # pad query group to the sublane tile
    qg = q.reshape(b, nkv, group, hd)
    if gp != group:
        qg = jnp.concatenate(
            [qg, jnp.zeros((b, nkv, gp - group, hd), q.dtype)], axis=2)
    kt = k_cache.transpose(0, 2, 1, 3)  # [B, nkv, S, hd]
    vt = v_cache.transpose(0, 2, 1, 3)
    keep_b = jnp.broadcast_to(keep[:, None, :], (b, SUBLANES, s))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bk=bk),
        out_shape=jax.ShapeDtypeStruct((b, nkv, gp, hd), q.dtype),
        grid=(b, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, gp, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, SUBLANES, s), lambda bi, h: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, hd),
                               lambda bi, h: (bi, h, 0, 0)),
        interpret=interpret,
    )(qg, kt, vt, keep_b)
    return out[:, :, :group, :].reshape(b, nq, hd)
