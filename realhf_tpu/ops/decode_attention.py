"""Pallas flash-decode attention against a padded KV cache (TPU).

Replaces the plain-XLA ``ops.attention.decode_attention`` on the hot
decode path (reference ``flash_attn_with_kvcache``, attn.py:238): one
query token per stream attends over the whole cache with a tiled
online softmax, never materializing the ``[B, nq, S]`` score tensor.
Decode is HBM-bandwidth bound -- the kernel makes a single pass over
K/V per step, with all query heads of a KV group (GQA) sharing each
loaded block.

Layout contract (HEAD-MAJOR, so no transpose sits on the hot path):
q [B, nq, hd], per-layer caches [B, nkv, S, hd], keep-mask [B, S]
(validity AND the sliding window -- precomputed in XLA, it is O(B*S)
elementwise). Two entry points:

- ``flash_decode_attention``: per-layer caches (unrolled decode loop;
  a static layer index into the stacked cache is a free view).
- ``flash_decode_attention_stacked``: the FULL stacked caches
  [nl, B, nkv, S, hd] plus a (traced) layer index, delivered to the
  kernel through scalar prefetch so only layer ``l``'s rows are ever
  streamed from HBM. This keeps the `lax.scan`-over-layers decode
  path at O(1) compile time without copying a layer's cache out per
  token (the round-3 decode bottleneck).

The query-group axis is padded up to the fp32 sublane count (8); hd
should be a multiple of 128 on real TPUs. S is padded to the K block.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from realhf_tpu.base import logging

logger = logging.getLogger("decode_attention")

NEG_INF = -2.0 ** 30
SUBLANES = 8
DEFAULT_BK = 512


def _decode_body(q, k_at, v_at, keep_at, o_ref, *, scale, bk, s):
    """Shared online-softmax body over one (stream, kv-head) cell.
    ``q``: loaded [gp, hd]; ``k_at(j)/v_at(j)``: [bk, hd] block loads;
    ``keep_at(j)``: [bk] int32; ``o_ref``: the output ref."""
    gp, hd = q.shape
    q = q.astype(jnp.float32) * scale

    def body(j, carry):
        m, l_sum, acc = carry
        k = k_at(j).astype(jnp.float32)
        v = v_at(j)
        keep = keep_at(j)  # [bk] int32

        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [gp, bk]
        sc = jnp.where((keep > 0)[None, :], sc, NEG_INF)

        m_new = jnp.maximum(m, sc.max(axis=1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l_sum * alpha + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((gp,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((gp,), jnp.float32)
    acc0 = jnp.zeros((gp, hd), jnp.float32)
    m, l_sum, acc = jax.lax.fori_loop(0, s // bk, body, (m0, l0, acc0))

    row_valid = m > NEG_INF / 2  # streams whose cache is still empty
    safe_l = jnp.where(l_sum > 0, l_sum, 1.0)
    out = jnp.where(row_valid[:, None], acc / safe_l[:, None], 0.0)
    o_ref[...] = out.reshape(o_ref.shape).astype(o_ref.dtype)


def _layer_kernel(q_ref, k_ref, v_ref, keep_ref, o_ref, *, scale, bk):
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s)


def _stacked_kernel(lidx_ref, q_ref, k_ref, v_ref, keep_ref, o_ref, *,
                    scale, bk):
    # lidx_ref is the scalar-prefetch operand; the index_map already
    # consumed it to select the layer block, so the body is identical.
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s)


def _pick_bk(s: int, block_k: int = DEFAULT_BK) -> int:
    """Largest K-block <= block_k that divides s (cache lengths are
    allocated as multiples of 128, so this normally succeeds and the
    concat-pad fallback never runs on the hot path)."""
    if s <= block_k:
        return s
    for bk in (512, 384, 256, 128):
        if bk <= block_k and s % bk == 0:
            return bk
    return block_k


def _window_keep(valid_mask, sliding_window, slot):
    keep = valid_mask
    if sliding_window is not None:
        assert slot is not None, "sliding_window decode needs slot indices"
        s = valid_mask.shape[1]
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        keep = keep & ((slot[:, None] - idx) < sliding_window)
    return keep.astype(jnp.int32)


def _pad_group(q, nkv, group, gp):
    b, _, hd = q.shape
    qg = q.reshape(b, nkv, group, hd)
    if gp != group:
        qg = jnp.concatenate(
            [qg, jnp.zeros((b, nkv, gp - group, hd), q.dtype)], axis=2)
    return qg


def flash_decode_attention(
    q: jnp.ndarray,        # [B, nq, hd]
    k_cache: jnp.ndarray,  # [B, nkv, S, hd]
    v_cache: jnp.ndarray,
    valid_mask: jnp.ndarray,  # [B, S] bool
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,  # [B] int32, with sliding_window
    block_k: int = DEFAULT_BK,
    interpret: bool = False,
) -> jnp.ndarray:
    b, nq, hd = q.shape
    nkv, s = k_cache.shape[1], k_cache.shape[2]
    group = nq // nkv
    scale = float(scale) if scale is not None else hd ** -0.5

    keep = _window_keep(valid_mask, sliding_window, slot)

    bk = _pick_bk(s, block_k)
    pad_s = (-s) % bk
    if pad_s:
        zpad = jnp.zeros((b, nkv, pad_s, hd), k_cache.dtype)
        k_cache = jnp.concatenate([k_cache, zpad], axis=2)
        v_cache = jnp.concatenate([v_cache, zpad], axis=2)
        keep = jnp.concatenate(
            [keep, jnp.zeros((b, pad_s), jnp.int32)], axis=1)
    s += pad_s

    gp = max(SUBLANES, group)  # pad query group to the sublane tile
    qg = _pad_group(q, nkv, group, gp)
    keep_b = jnp.broadcast_to(keep[:, None, :], (b, SUBLANES, s))

    out = pl.pallas_call(
        functools.partial(_layer_kernel, scale=scale, bk=bk),
        out_shape=jax.ShapeDtypeStruct((b, nkv, gp, hd), q.dtype),
        grid=(b, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, gp, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
            pl.BlockSpec((1, SUBLANES, s), lambda bi, h: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, hd),
                               lambda bi, h: (bi, h, 0, 0)),
        interpret=interpret,
    )(qg, k_cache, v_cache, keep_b)
    return out[:, :, :group, :].reshape(b, nq, hd)


def sharded_decode_attention(
    fn, mesh, q, caches, valid_mask, slot, layer_index=None, *,
    stacked: bool,
):
    """Partition a decode-attention kernel over a dp x tp mesh with
    `shard_map` (manual over the data/model axes): a bare pallas_call
    under GSPMD has no partitioning rule, so without this wrapper XLA
    would gather the full KV cache onto every device -- fatal for the
    tp16 70B decode story (docs/distributed.md).
    ``fn(q, k, v, valid, slot, lidx)`` runs on LOCAL shards: B over
    "data", heads over "model" (GQA grouping survives because nq and
    nkv shard together).

    Callers must check `decode_shardable` (B % dp, nq % tp, nkv % tp)
    and fall back to the XLA path otherwise."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    layer_lead = (None,) if stacked else ()
    kv_spec = P(*layer_lead, DATA_AXIS, MODEL_AXIS, None, None)
    slot_spec = P(DATA_AXIS) if slot is not None else P()
    has_slot = slot is not None
    # decode requires pipe=ctx=1, so go FULLY manual (partial-auto
    # meshes cannot host the interpret-mode kernel's callbacks)
    axis_names = {a for a in mesh.axis_names}

    @_partial(jax.shard_map, mesh=mesh,
              axis_names=axis_names,
              in_specs=(P(DATA_AXIS, MODEL_AXIS, None), kv_spec,
                        kv_spec, P(DATA_AXIS, None), slot_spec, P()),
              out_specs=P(DATA_AXIS, MODEL_AXIS, None),
              # pallas_call outputs carry no varying-axes metadata
              check_vma=False)
    def run(q_l, k_l, v_l, valid_l, slot_l, lidx):
        return fn(q_l, k_l, v_l, valid_l,
                  slot_l if has_slot else None, lidx)

    k_all, v_all = caches
    return run(q, k_all, v_all, valid_mask,
               slot if has_slot else jnp.zeros((), jnp.int32),
               (layer_index if layer_index is not None
                else jnp.zeros((), jnp.int32)))


def mesh_nontrivial(mesh) -> bool:
    """True when the mesh actually shards over data/model (the pallas
    kernels then need the shard_map wrappers)."""
    if mesh is None:
        return False
    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    return (mesh.shape.get(DATA_AXIS, 1)
            * mesh.shape.get(MODEL_AXIS, 1)) > 1


_warned_unshardable = set()


def decode_shardable(mesh, b: int, nq: int, nkv: int) -> bool:
    """Whether the pallas decode kernels can partition on this mesh.

    The limiting case is GQA at high TP (tp > n_kv_heads, e.g. 8
    kv-heads at tp16): KV heads cannot shard evenly over "model", so
    decode falls back to the GSPMD einsum path -- still sharded, but
    with partial KV replication and without the single-pass flash
    kernel. That fallback is a real throughput loss on the biggest
    decode configs, so it WARNS (once per shape) instead of silently
    downgrading; a query-group-axis sharded kernel is the planned
    lift (docs/distributed.md, 70B decode story)."""
    if mesh is None:
        return True
    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if dp == 1 and tp == 1:
        return True
    ok = b % dp == 0 and nq % tp == 0 and nkv % tp == 0
    if not ok:
        key = (dp, tp, b, nq, nkv)
        if key not in _warned_unshardable:
            _warned_unshardable.add(key)
            logger.warning(
                "Pallas decode kernel cannot partition on this mesh "
                "(dp=%d tp=%d, batch=%d, nq=%d, nkv=%d must divide "
                "evenly); decoding via the GSPMD einsum path instead "
                "-- expect lower decode throughput. GQA at tp > "
                "n_kv_heads is the usual cause; prefer gen_tp_size <= "
                "n_kv_heads when weights allow.", dp, tp, b, nq, nkv)
    return ok


def flash_decode_attention_stacked(
    q: jnp.ndarray,        # [B, nq, hd]
    k_all: jnp.ndarray,    # [nl, B, nkv, S, hd] -- the FULL stacked cache
    v_all: jnp.ndarray,
    valid_mask: jnp.ndarray,  # [B, S] bool
    layer_index: jnp.ndarray,  # scalar int32 (traced OK)
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,
    block_k: int = DEFAULT_BK,
    interpret: bool = False,
) -> jnp.ndarray:
    """Same math as `flash_decode_attention` but reads layer
    ``layer_index`` of the stacked cache directly via a scalar-prefetch
    index map -- HBM traffic is exactly one layer's K/V rows, with no
    per-layer slice copy. S must be a multiple of ``block_k`` (the
    generation path allocates caches pre-padded; see
    `transformer.init_kv_cache`)."""
    b, nq, hd = q.shape
    nl, _, nkv, s = k_all.shape[:4]
    group = nq // nkv
    scale = float(scale) if scale is not None else hd ** -0.5

    bk = _pick_bk(s, block_k)
    assert s % bk == 0, (
        f"stacked decode cache length {s} must be a multiple of the "
        f"K block {bk}; pad the cache at allocation time")

    keep = _window_keep(valid_mask, sliding_window, slot)
    gp = max(SUBLANES, group)
    qg = _pad_group(q, nkv, group, gp)
    keep_b = jnp.broadcast_to(keep[:, None, :], (b, SUBLANES, s))
    lidx = jnp.asarray(layer_index, jnp.int32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, gp, hd), lambda bi, h, lr: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, s, hd),
                         lambda bi, h, lr: (lr[0], bi, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, s, hd),
                         lambda bi, h, lr: (lr[0], bi, h, 0, 0)),
            pl.BlockSpec((1, SUBLANES, s), lambda bi, h, lr: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, hd),
                               lambda bi, h, lr: (bi, h, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_stacked_kernel, scale=scale, bk=bk),
        out_shape=jax.ShapeDtypeStruct((b, nkv, gp, hd), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lidx, qg, k_all, v_all, keep_b)
    return out[:, :, :group, :].reshape(b, nq, hd)
