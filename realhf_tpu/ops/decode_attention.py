"""Pallas flash-decode attention against a padded KV cache (TPU).

Replaces the plain-XLA ``ops.attention.decode_attention`` on the hot
decode path (reference ``flash_attn_with_kvcache``, attn.py:238): one
query token per stream attends over the whole cache with a tiled
online softmax, never materializing the ``[B, nq, S]`` score tensor.
Decode is HBM-bandwidth bound -- the kernel makes a single pass over
K/V per step, with all query heads of a KV group (GQA) sharing each
loaded block.

Layout contract (HEAD-MAJOR, so no transpose sits on the hot path):
q [B, nq, hd], per-layer caches [B, nkv, S, hd], keep-mask [B, S]
(validity AND the sliding window -- precomputed in XLA, it is O(B*S)
elementwise). Two entry points:

- ``flash_decode_attention``: per-layer caches (unrolled decode loop;
  a static layer index into the stacked cache is a free view).
- ``flash_decode_attention_stacked``: the FULL stacked caches
  [nl, B, nkv, S, hd] plus a (traced) layer index, delivered to the
  kernel through scalar prefetch so only layer ``l``'s rows are ever
  streamed from HBM. This keeps the `lax.scan`-over-layers decode
  path at O(1) compile time without copying a layer's cache out per
  token (the round-3 decode bottleneck).

The query-group axis is padded up to the fp32 sublane count (8); hd
should be a multiple of 128 on real TPUs. S is padded to the K block.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from realhf_tpu.base import logging

logger = logging.getLogger("decode_attention")

NEG_INF = -2.0 ** 30
SUBLANES = 8


def _default_bk() -> int:
    """K-block rows per kernel step; REALHF_TPU_DECODE_BK overrides
    for on-chip tuning sweeps (scripts/sweep_decode_bk.py) without a
    code edit. Validated here so a malformed value fails at the knob,
    not as a ZeroDivisionError deep inside the kernel."""
    raw = os.environ.get("REALHF_TPU_DECODE_BK")
    if not raw:
        return 512
    try:
        v = int(raw)
    except ValueError as e:
        raise ValueError(
            f"REALHF_TPU_DECODE_BK={raw!r} is not an integer") from e
    if v < 128 or v % 128:
        raise ValueError(
            "REALHF_TPU_DECODE_BK must be a positive multiple of 128 "
            f"(lane tiling), got {v}")
    return v


DEFAULT_BK = _default_bk()


def _decode_body(q, k_at, v_at, keep_at, o_ref, *, scale, bk, s,
                 m_ref=None, l_ref=None):
    """Shared online-softmax body over one (stream, kv-head) cell.
    ``q``: loaded [gp, hd]; ``k_at(j)/v_at(j)``: [bk, hd] block loads;
    ``keep_at(j)``: [bk] int32; ``o_ref``: the output ref.
    ``m_ref``/``l_ref`` (optional): per-row softmax max / normalizer
    outputs -- the partial stats a KV-sequence-split caller combines
    across shards (sharded_decode_attention_seqsplit)."""
    gp, hd = q.shape
    q = q.astype(jnp.float32) * scale

    def body(j, carry):
        m, l_sum, acc = carry
        k = k_at(j).astype(jnp.float32)
        v = v_at(j)
        keep = keep_at(j)  # [bk] int32

        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [gp, bk]
        sc = jnp.where((keep > 0)[None, :], sc, NEG_INF)

        m_new = jnp.maximum(m, sc.max(axis=1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l_sum * alpha + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((gp,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((gp,), jnp.float32)
    acc0 = jnp.zeros((gp, hd), jnp.float32)
    m, l_sum, acc = jax.lax.fori_loop(0, s // bk, body, (m0, l0, acc0))

    row_valid = m > NEG_INF / 2  # streams whose cache is still empty
    safe_l = jnp.where(l_sum > 0, l_sum, 1.0)
    out = jnp.where(row_valid[:, None], acc / safe_l[:, None], 0.0)
    o_ref[...] = out.reshape(o_ref.shape).astype(o_ref.dtype)
    if m_ref is not None:
        m_ref[...] = m.reshape(m_ref.shape)
        l_ref[...] = l_sum.reshape(l_ref.shape)


def _layer_kernel(q_ref, k_ref, v_ref, keep_ref, o_ref, *, scale, bk):
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s)


def _layer_kernel_stats(q_ref, k_ref, v_ref, keep_ref, o_ref, m_ref,
                        l_ref, *, scale, bk):
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s, m_ref=m_ref, l_ref=l_ref)


def _stacked_kernel(lidx_ref, q_ref, k_ref, v_ref, keep_ref, o_ref, *,
                    scale, bk):
    # lidx_ref is the scalar-prefetch operand; the index_map already
    # consumed it to select the layer block, so the body is identical.
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s)


def _stacked_kernel_stats(lidx_ref, q_ref, k_ref, v_ref, keep_ref,
                          o_ref, m_ref, l_ref, *, scale, bk):
    s = k_ref.shape[-2]
    _decode_body(
        q_ref[0, 0],
        lambda j: k_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: v_ref[0, 0, 0, pl.ds(j * bk, bk), :],
        lambda j: keep_ref[0, 0, pl.ds(j * bk, bk)],
        o_ref, scale=scale, bk=bk, s=s, m_ref=m_ref, l_ref=l_ref)


def _with_stats(kernel, kernel_stats, return_stats, o_shape, o_dtype,
                o_spec, stat_spec, **kw):
    """Pick the (kernel, out_shape, out_specs) triple for a decode
    pallas_call with or without the (m, l) stats outputs -- shared by
    the flat and stacked wrappers so their call setup cannot drift."""
    b, nkv, gp = o_shape[0], o_shape[1], o_shape[2]
    if return_stats:
        stat = jax.ShapeDtypeStruct((b, nkv, gp), jnp.float32)
        return (functools.partial(kernel_stats, **kw),
                (jax.ShapeDtypeStruct(o_shape, o_dtype), stat, stat),
                (o_spec, stat_spec, stat_spec))
    return (functools.partial(kernel, **kw),
            jax.ShapeDtypeStruct(o_shape, o_dtype), o_spec)


def _trim_stats(res, return_stats, b, nq, group):
    """Strip the padded query-group rows from a decode pallas_call's
    result(s) and flatten heads back to [B, nq, ...]."""
    if return_stats:
        out, m, l = res
        hd = out.shape[-1]
        return (out[:, :, :group, :].reshape(b, nq, hd),
                m[:, :, :group].reshape(b, nq),
                l[:, :, :group].reshape(b, nq))
    hd = res.shape[-1]
    return res[:, :, :group, :].reshape(b, nq, hd)


#: candidate K-blocks, descending (multiples of 128 for lane tiling)
_BK_LADDER = (4096, 2048, 1024, 512, 384, 256, 128)


def _pick_bk(s: int, block_k: int = DEFAULT_BK) -> int:
    """Largest K-block <= block_k that divides s (cache lengths are
    allocated as multiples of 128, so this normally succeeds and the
    concat-pad fallback never runs on the hot path). The ladder spans
    past 512 so a raised DEFAULT_BK actually takes effect."""
    if s <= block_k:
        return s
    for bk in _BK_LADDER:
        if bk <= block_k and s % bk == 0:
            return bk
    return block_k


def _window_keep(valid_mask, sliding_window, slot):
    keep = valid_mask
    if sliding_window is not None:
        assert slot is not None, "sliding_window decode needs slot indices"
        s = valid_mask.shape[1]
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        keep = keep & ((slot[:, None] - idx) < sliding_window)
    return keep.astype(jnp.int32)


# public alias: seqsplit callers precompute the keep mask GLOBALLY
# (window positions are global; shards see local indices)
window_keep = _window_keep


def _pad_group(q, nkv, group, gp):
    b, _, hd = q.shape
    qg = q.reshape(b, nkv, group, hd)
    if gp != group:
        qg = jnp.concatenate(
            [qg, jnp.zeros((b, nkv, gp - group, hd), q.dtype)], axis=2)
    return qg


def flash_decode_attention(
    q: jnp.ndarray,        # [B, nq, hd]
    k_cache: jnp.ndarray,  # [B, nkv, S, hd]
    v_cache: jnp.ndarray,
    valid_mask: jnp.ndarray,  # [B, S] bool
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,  # [B] int32, with sliding_window
    block_k: int = DEFAULT_BK,
    interpret: bool = False,
    return_stats: bool = False,  # also return (m, l) softmax partials
) -> jnp.ndarray:
    b, nq, hd = q.shape
    nkv, s = k_cache.shape[1], k_cache.shape[2]
    group = nq // nkv
    scale = float(scale) if scale is not None else hd ** -0.5

    keep = _window_keep(valid_mask, sliding_window, slot)

    bk = _pick_bk(s, block_k)
    pad_s = (-s) % bk
    if pad_s:
        zpad = jnp.zeros((b, nkv, pad_s, hd), k_cache.dtype)
        k_cache = jnp.concatenate([k_cache, zpad], axis=2)
        v_cache = jnp.concatenate([v_cache, zpad], axis=2)
        keep = jnp.concatenate(
            [keep, jnp.zeros((b, pad_s), jnp.int32)], axis=1)
    s += pad_s

    gp = max(SUBLANES, group)  # pad query group to the sublane tile
    qg = _pad_group(q, nkv, group, gp)
    keep_b = jnp.broadcast_to(keep[:, None, :], (b, SUBLANES, s))

    in_specs = [
        pl.BlockSpec((1, 1, gp, hd), lambda bi, h: (bi, h, 0, 0)),
        pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
        pl.BlockSpec((1, 1, s, hd), lambda bi, h: (bi, h, 0, 0)),
        pl.BlockSpec((1, SUBLANES, s), lambda bi, h: (bi, 0, 0)),
    ]
    o_spec = pl.BlockSpec((1, 1, gp, hd), lambda bi, h: (bi, h, 0, 0))
    kernel, out_shape, out_specs = _with_stats(
        _layer_kernel, _layer_kernel_stats, return_stats,
        (b, nkv, gp, hd), q.dtype, o_spec,
        pl.BlockSpec((1, 1, gp), lambda bi, h: (bi, h, 0)),
        scale=scale, bk=bk)
    res = pl.pallas_call(
        kernel, out_shape=out_shape, grid=(b, nkv),
        in_specs=in_specs, out_specs=out_specs, interpret=interpret,
    )(qg, k_cache, v_cache, keep_b)
    return _trim_stats(res, return_stats, b, nq, group)


def sharded_decode_attention(
    fn, mesh, q, caches, valid_mask, slot, layer_index=None, *,
    stacked: bool,
):
    """Partition a decode-attention kernel over a dp x tp mesh with
    `shard_map` (manual over the data/model axes): a bare pallas_call
    under GSPMD has no partitioning rule, so without this wrapper XLA
    would gather the full KV cache onto every device -- fatal for the
    tp16 70B decode story (docs/distributed.md).
    ``fn(q, k, v, valid, slot, lidx)`` runs on LOCAL shards: B over
    "data", heads over "model" (GQA grouping survives because nq and
    nkv shard together).

    Callers must check `decode_shardable` (B % dp, nq % tp, nkv % tp)
    and fall back to the XLA path otherwise."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    layer_lead = (None,) if stacked else ()
    kv_spec = P(*layer_lead, DATA_AXIS, MODEL_AXIS, None, None)
    slot_spec = P(DATA_AXIS) if slot is not None else P()
    has_slot = slot is not None
    # decode requires pipe=ctx=1, so go FULLY manual (partial-auto
    # meshes cannot host the interpret-mode kernel's callbacks)
    axis_names = {a for a in mesh.axis_names}

    @_partial(jax.shard_map, mesh=mesh,
              axis_names=axis_names,
              in_specs=(P(DATA_AXIS, MODEL_AXIS, None), kv_spec,
                        kv_spec, P(DATA_AXIS, None), slot_spec, P()),
              out_specs=P(DATA_AXIS, MODEL_AXIS, None),
              # pallas_call outputs carry no varying-axes metadata
              check_vma=False)
    def run(q_l, k_l, v_l, valid_l, slot_l, lidx):
        return fn(q_l, k_l, v_l, valid_l,
                  slot_l if has_slot else None, lidx)

    k_all, v_all = caches
    return run(q, k_all, v_all, valid_mask,
               slot if has_slot else jnp.zeros((), jnp.int32),
               (layer_index if layer_index is not None
                else jnp.zeros((), jnp.int32)))


def mesh_nontrivial(mesh) -> bool:
    """True when the mesh actually shards over data/model (the pallas
    kernels then need the shard_map wrappers)."""
    if mesh is None:
        return False
    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    return (mesh.shape.get(DATA_AXIS, 1)
            * mesh.shape.get(MODEL_AXIS, 1)) > 1


_warned_unshardable = set()


def decode_shardable(mesh, b: int, nq: int, nkv: int) -> bool:
    """Whether the pallas decode kernels can partition HEAD-wise on
    this mesh (B over "data", q/kv heads over "model")."""
    if mesh is None:
        return True
    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if dp == 1 and tp == 1:
        return True
    return b % dp == 0 and nq % tp == 0 and nkv % tp == 0


def choose_decode_partitioning(mesh, b: int, nq: int, nkv: int,
                               s: int) -> Optional[str]:
    """How the pallas decode kernel partitions on this mesh:
    ``"heads"`` (B over "data", heads over "model" -- the fast path),
    ``"seq"`` (KV sequence over "model" with a cross-shard flash
    combine -- GQA at tp > n_kv_heads, e.g. LLaMA-70B's 8 kv-heads at
    tp16), or ``None`` (nothing divides: GSPMD einsum fallback, with a
    one-time warning because the throughput loss is real)."""
    if mesh is None:
        return "heads"
    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if dp == 1 and tp == 1:
        return "heads"
    if decode_shardable(mesh, b, nq, nkv):
        return "heads"
    s_local = s // tp
    # the LOCAL shard length must satisfy the kernels' K-block
    # constraint (stacked kernel asserts s % bk == 0; _pick_bk finds a
    # divisor when s_local <= block_k or s_local % 128 == 0)
    if (b % dp == 0 and s % tp == 0
            and (s_local <= DEFAULT_BK or s_local % 128 == 0)):
        return "seq"
    key = (dp, tp, b, nq, nkv, s)
    if key not in _warned_unshardable:
        _warned_unshardable.add(key)
        logger.warning(
            "Pallas decode kernel cannot partition on this mesh "
            "(dp=%d tp=%d, batch=%d, nq=%d, nkv=%d, cache_len=%d: "
            "neither heads nor KV sequence divide evenly); decoding "
            "via the GSPMD einsum path instead -- expect lower decode "
            "throughput.", dp, tp, b, nq, nkv, s)
    return None


def run_decode_kernels(mesh, q, caches, valid_mask, slot, layer_index,
                       *, stacked: bool, scale=None,
                       sliding_window=None):
    """Single dispatcher for one decode-attention call onto the Pallas
    kernels: bare kernel on trivial meshes, head-sharded or
    KV-sequence-split shard_map per ``choose_decode_partitioning``.
    Returns ``None`` when no kernel partitioning applies -- the caller
    then takes its GSPMD/XLA fallback. Shared by the flat
    (``ops/attention.decode_attention``) and stacked
    (``models/transformer._stacked_decode_attention``) paths so the
    routing cannot drift between them. Traced scales (deep
    scale_attn_by_inverse_layer_idx models) fold into q here, since
    the kernels need a python-static scale."""
    if not (scale is None or isinstance(scale, (int, float))):
        q = (q.astype(jnp.float32) * scale).astype(q.dtype)
        scale = 1.0
    b, nq = q.shape[0], q.shape[1]
    if stacked:
        nkv, s = caches[0].shape[2], caches[0].shape[3]

        def plain(q_, k_, v_, valid_, slot_, lidx):
            return flash_decode_attention_stacked(
                q_, k_, v_, valid_, lidx, scale=scale,
                sliding_window=sliding_window, slot=slot_)

        def stats(q_, k_, v_, keep_, lidx):
            return flash_decode_attention_stacked(
                q_, k_, v_, keep_.astype(bool), lidx, scale=scale,
                return_stats=True)
    else:
        nkv, s = caches[0].shape[1], caches[0].shape[2]

        def plain(q_, k_, v_, valid_, slot_, lidx):
            return flash_decode_attention(
                q_, k_, v_, valid_, scale=scale,
                sliding_window=sliding_window, slot=slot_)

        def stats(q_, k_, v_, keep_, lidx):
            return flash_decode_attention(
                q_, k_, v_, keep_.astype(bool), scale=scale,
                return_stats=True)

    if not mesh_nontrivial(mesh):
        return plain(q, caches[0], caches[1], valid_mask, slot,
                     (layer_index if layer_index is not None
                      else jnp.zeros((), jnp.int32)))
    part = choose_decode_partitioning(mesh, b, nq, nkv, s)
    if part == "heads":
        return sharded_decode_attention(
            plain, mesh, q, caches, valid_mask, slot, layer_index,
            stacked=stacked)
    if part == "seq":
        keep = window_keep(valid_mask, sliding_window, slot)
        return sharded_decode_attention_seqsplit(
            stats, mesh, q, caches, keep, layer_index, stacked=stacked)
    return None


def sharded_decode_attention_seqsplit(
    fn_stats, mesh, q, caches, keep, layer_index=None, *,
    stacked: bool,
):
    """KV-SEQUENCE-split decode for GQA at tp > n_kv_heads (the
    LLaMA-70B tp16 case, docs/distributed.md): heads cannot shard
    16-ways, so each "model" shard instead holds a SLICE OF THE CACHE
    SEQUENCE, runs the flash kernel over its slice with partial
    softmax stats, and the shards combine with the standard
    flash-attention merge (``out = sum_i w_i out_i``,
    ``w_i = l_i exp(m_i - m)``) via psum over "model". Attention
    FLOPs and KV bytes split tp-ways evenly regardless of head
    counts; q (tiny at decode, [B, nq, hd]) is replicated over
    "model".

    ``fn_stats(q, k, v, keep, lidx) -> (out, m, l)`` runs on LOCAL
    shards and must apply any sliding window itself -- ``keep`` here
    is the PRE-COMPUTED global keep mask ([B, S] int32), since window
    positions are global while each shard sees local indices."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from realhf_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    layer_lead = (None,) if stacked else ()
    kv_spec = P(*layer_lead, DATA_AXIS, None, MODEL_AXIS, None)
    axis_names = {a for a in mesh.axis_names}

    @_partial(jax.shard_map, mesh=mesh,
              axis_names=axis_names,
              in_specs=(P(DATA_AXIS, None, None), kv_spec, kv_spec,
                        P(DATA_AXIS, MODEL_AXIS), P()),
              out_specs=P(DATA_AXIS, None, None),
              check_vma=False)
    def run(q_l, k_l, v_l, keep_l, lidx):
        out, m, l = fn_stats(q_l, k_l, v_l, keep_l, lidx)
        out = out.astype(jnp.float32)
        # flash merge across sequence shards; empty shards carry
        # m=NEG_INF / l=0 and must contribute weight 0, not NaN
        m_all = jax.lax.pmax(m, MODEL_AXIS)
        m_safe = jnp.where(m_all > NEG_INF / 2, m_all, 0.0)
        w = jnp.where(m > NEG_INF / 2, l * jnp.exp(m - m_safe), 0.0)
        # one fused psum for numerator and normalizer (this runs per
        # layer per decode token: collective count is latency)
        num, denom = jax.lax.psum((out * w[..., None], w), MODEL_AXIS)
        safe = jnp.where(denom > 0, denom, 1.0)
        out = jnp.where(denom[..., None] > 0,
                        num / safe[..., None], 0.0)
        return out.astype(q_l.dtype)

    k_all, v_all = caches
    return run(q, k_all, v_all, keep,
               (layer_index if layer_index is not None
                else jnp.zeros((), jnp.int32)))


def flash_decode_attention_stacked(
    q: jnp.ndarray,        # [B, nq, hd]
    k_all: jnp.ndarray,    # [nl, B, nkv, S, hd] -- the FULL stacked cache
    v_all: jnp.ndarray,
    valid_mask: jnp.ndarray,  # [B, S] bool
    layer_index: jnp.ndarray,  # scalar int32 (traced OK)
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    slot: Optional[jnp.ndarray] = None,
    block_k: int = DEFAULT_BK,
    interpret: bool = False,
    return_stats: bool = False,  # also return (m, l) softmax partials
) -> jnp.ndarray:
    """Same math as `flash_decode_attention` but reads layer
    ``layer_index`` of the stacked cache directly via a scalar-prefetch
    index map -- HBM traffic is exactly one layer's K/V rows, with no
    per-layer slice copy. S must be a multiple of ``block_k`` (the
    generation path allocates caches pre-padded; see
    `transformer.init_kv_cache`)."""
    b, nq, hd = q.shape
    nl, _, nkv, s = k_all.shape[:4]
    group = nq // nkv
    scale = float(scale) if scale is not None else hd ** -0.5

    bk = _pick_bk(s, block_k)
    assert s % bk == 0, (
        f"stacked decode cache length {s} must be a multiple of the "
        f"K block {bk}; pad the cache at allocation time")

    keep = _window_keep(valid_mask, sliding_window, slot)
    gp = max(SUBLANES, group)
    qg = _pad_group(q, nkv, group, gp)
    keep_b = jnp.broadcast_to(keep[:, None, :], (b, SUBLANES, s))
    lidx = jnp.asarray(layer_index, jnp.int32).reshape(1)

    in_specs = [
        pl.BlockSpec((1, 1, gp, hd), lambda bi, h, lr: (bi, h, 0, 0)),
        pl.BlockSpec((1, 1, 1, s, hd),
                     lambda bi, h, lr: (lr[0], bi, h, 0, 0)),
        pl.BlockSpec((1, 1, 1, s, hd),
                     lambda bi, h, lr: (lr[0], bi, h, 0, 0)),
        pl.BlockSpec((1, SUBLANES, s), lambda bi, h, lr: (bi, 0, 0)),
    ]
    o_spec = pl.BlockSpec((1, 1, gp, hd), lambda bi, h, lr: (bi, h, 0, 0))
    kernel, out_shape, out_specs = _with_stats(
        _stacked_kernel, _stacked_kernel_stats, return_stats,
        (b, nkv, gp, hd), q.dtype, o_spec,
        pl.BlockSpec((1, 1, gp), lambda bi, h, lr: (bi, h, 0)),
        scale=scale, bk=bk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(b, nkv),
        in_specs=in_specs, out_specs=out_specs)
    res = pl.pallas_call(
        kernel, out_shape=out_shape, grid_spec=grid_spec,
        interpret=interpret,
    )(lidx, qg, k_all, v_all, keep_b)
    return _trim_stats(res, return_stats, b, nq, group)
