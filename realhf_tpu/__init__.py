"""realhf_tpu: a TPU-native (JAX/XLA/Pallas) RLHF training framework.

Re-designed from scratch with the capabilities of ReaLHF
(openpsi-project/ReaLHF): dataflow-graph RLHF algorithms (SFT / RW /
DPO / PPO / GRPO / generation), per-model-function-call device meshes,
and dynamic parameter reallocation between training and generation
layouts -- expressed TPU-first via ``jax.sharding`` meshes, pjit/GSPMD
sharding, and Pallas kernels instead of NCCL/Megatron/CUDA-graphs.

Layer map (mirrors reference ``docs/source/arch.rst``):
  base/       -- logging, name-resolve, time/frequency control, packing
  api/        -- config, dataflow graph (MFCs), SequenceSample data model
  parallel/   -- mesh construction, sharding rules, cross-mesh resharding
  ops/        -- Pallas/XLA kernels: flash attention, GAE, sampling
  models/     -- the single transformer implementation + HF conversion
  engine/     -- train/inference/generation engines (pjit + jit)
  interfaces/ -- algorithm interfaces (SFT/RW/DPO/PPO/gen)
  datasets/   -- prompt / prompt-answer / paired-reward datasets
  system/     -- runtime: master/model workers, buffers, inline runner
  experiments/-- experiment configs translating CLI to worker configs
  apps/       -- entry points (quickstart CLI)
"""

__version__ = "0.1.0"
