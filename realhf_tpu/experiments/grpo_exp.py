"""GRPO experiment: critic-free group-relative RLHF.

Parity with the reference's GRPO example algorithm
(``examples/new_algorithms/grpo/grpo_interface.py`` + its experiment
registration): a 4-MFC dataflow graph -- actor_gen (group sampling) ->
{rew_inf, ref_inf} -> actor_train -- with no critic or value model in
the graph at all.
"""

import dataclasses
from typing import Optional

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class GRPOHyperparameters:
    group_size: int = 4
    kl_coef: float = 0.05
    max_new_tokens: int = 256
    min_new_tokens: int = 1
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0
    temperature: float = 1.0
    # GRPO replays no logits mask; keep sampling unwarped by default
    force_no_logits_mask: bool = True
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    discount: float = 1.0
    max_reward_clip: float = 20.0
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    adv_norm: bool = False


@dataclasses.dataclass
class GRPOConfig(CommonExperimentConfig):
    actor: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    ref: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    rew: ModelConfigCLI = dataclasses.field(
        default_factory=lambda: ModelConfigCLI(is_critic=True))
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    grpo: GRPOHyperparameters = dataclasses.field(
        default_factory=GRPOHyperparameters)
    actor_gen_n_mbs: int = 1
    actor_train_n_mbs: int = 1
    rew_inf_n_mbs: int = 1
    ref_inf_n_mbs: int = 1
    actor_gen_alloc: Optional[str] = None
    rew_inf_alloc: Optional[str] = None
    ref_inf_alloc: Optional[str] = None

    def build(self) -> ExperimentSpec:
        g = self.grpo
        gconfig = dict(
            max_new_tokens=g.max_new_tokens,
            min_new_tokens=g.min_new_tokens,
            greedy=g.greedy, top_p=g.top_p, top_k=g.top_k,
            temperature=g.temperature,
            force_no_logits_mask=g.force_no_logits_mask)
        itf = ModelInterfaceAbstraction("grpo", dict(
            group_size=g.group_size, kl_coef=g.kl_coef,
            gconfig=gconfig, n_minibatches=g.ppo_n_minibatches,
            eps_clip=g.eps_clip, discount=g.discount,
            max_reward_clip=g.max_reward_clip, adv_norm=g.adv_norm))
        rw_itf = ModelInterfaceAbstraction(
            "paired_rw", dict(output_scaling=g.reward_output_scaling,
                              output_bias=g.reward_output_bias,
                              enable_save=False))
        n = self.dataset.train_bs_n_seqs
        mfcs = [
            MFCDef(name="actor_gen", n_seqs=n,
                   interface_type=ModelInterfaceType.GENERATE,
                   interface_impl=itf, model_name="actor",
                   input_keys=("packed_prompts",),
                   output_keys=("seq_no_eos_mask", "packed_input_ids",
                                "packed_logprobs", "prompt_mask"),
                   n_mbs=self.actor_gen_n_mbs),
            MFCDef(name="rew_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=rw_itf, model_name="reward",
                   input_keys=("packed_input_ids",),
                   output_keys=("rewards",),
                   n_mbs=self.rew_inf_n_mbs),
            MFCDef(name="ref_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=itf, model_name="ref",
                   input_keys=("packed_input_ids",),
                   output_keys=("packed_ref_logprobs",),
                   n_mbs=self.ref_inf_n_mbs),
            MFCDef(name="actor_train", n_seqs=n,
                   interface_type=ModelInterfaceType.TRAIN_STEP,
                   interface_impl=itf, model_name="actor",
                   input_keys=("packed_input_ids", "packed_logprobs",
                               "packed_ref_logprobs", "rewards",
                               "prompt_mask"),
                   log_return_value=True,
                   n_mbs=self.actor_train_n_mbs),
        ]
        dataset = DatasetAbstraction(
            "prompt", args=dict(max_length=self.dataset.max_seqlen,
                                dataset_path=self.dataset.path))
        from realhf_tpu.parallel.mesh import parse_parallelism
        allocations = {}
        for mfc_name, alloc in (("actor_gen", self.actor_gen_alloc),
                                ("rew_inf", self.rew_inf_alloc),
                                ("ref_inf", self.ref_inf_alloc)):
            if alloc:
                allocations[mfc_name] = parse_parallelism(alloc)
        return ExperimentSpec(
            allocations=allocations,
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={
                "actor": self.actor.to_spec(train=True),
                "ref": self.ref.to_spec(train=False),
                "reward": dataclasses.replace(
                    self.rew.to_spec(train=False), is_critic=True),
            },
            mfcs=mfcs,
            dataset=dataset,
            tokenizer_path=self.tokenizer_path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            ctl=self.ctl())


register_experiment("grpo", GRPOConfig)
