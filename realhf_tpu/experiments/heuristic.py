"""Heuristic per-MFC allocation (allocation_mode=heuristic).

TPU-native counterpart of the reference's heuristic allocation
(``realhf/experiments/common/ppo_exp.py:419``): given the device count
and each role's model size, choose a decoupled layout per MFC without
running the MCMC search. The reference's rules-of-thumb translated to
TPU terms:

- **train MFCs** run on the role's primary layout: TP just big enough
  that weights + optimizer state (Adam: ~16 bytes/param fp32 m/v +
  master copy) fit comfortably in one chip's HBM, all remaining
  devices go to DP (grad accumulation handles batch; DP maximizes MXU
  utilization on TPU). When even TP = one ICI ring (TP_CAP) cannot
  fit the training state, layers are additionally sharded over
  pipeline stages (parallel/pipeline.py GPipe schedule).
- **generate MFCs** prefer wide DP with minimal TP (decode is
  HBM-bandwidth bound and batch-parallel; TP collectives per token are
  pure overhead at small per-chip batch): TP = weights-fit minimum.
- **inference MFCs** (reward/ref scoring) size TP to fit weights in
  bf16 (no optimizer), rest DP.

All sizes are derived from ``TransformerConfig.n_params()``; the
layout is returned as {mfc_name: ParallelismConfig} plus the per-role
primary, mirroring the (RPCAllocation, MFCConfig) output of the
reference.
"""

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from realhf_tpu.base import logging as _logging

logger = _logging.getLogger("heuristic")

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.parallel.mesh import ParallelismConfig

# Per-chip HBM budget in bytes (v5e: 16 GiB; leave headroom for
# activations and XLA workspace).
DEFAULT_HBM_BUDGET = int(16 * 1024 ** 3 * 0.6)


def _model_config_of(spec) -> TransformerConfig:
    """Config WITHOUT loading weights (sizes only)."""
    if spec.random_init_config is not None:
        return TransformerConfig(**spec.random_init_config,
                                 is_critic=spec.is_critic)
    from realhf_tpu.models.hf.registry import config_from_hf, detect_family
    family = spec.hf_family or detect_family(spec.path)
    with open(os.path.join(spec.path, "config.json")) as f:
        hf_config = json.load(f)
    return config_from_hf(family, hf_config, is_critic=spec.is_critic)


def _pow2_up_to(n: int) -> List[int]:
    out, p = [], 1
    while p <= n:
        out.append(p)
        p *= 2
    return out


def _min_tp(param_bytes: float, n_devices: int,
            hbm_budget: int) -> int:
    for tp in _pow2_up_to(n_devices):
        if param_bytes / tp <= hbm_budget:
            return tp
    return n_devices


# TP beyond one ICI ring scales poorly (per-layer collectives cross
# more hops); past this the heuristic prefers pipeline stages, whose
# ppermute traffic is one activation per tick.
TP_CAP = 8


def train_state_bytes_per_chip(n_params: int, tp: int, pp: int,
                               dp: int) -> float:
    """Per-chip training-state bytes with bf16 weights + ZeRO-1:
    bf16 params (2 B) shard over tp*pp; fp32 master copy (4 B), Adam
    m/v (8 B) and the fp32 grad accumulator (4 B, live during the
    step) additionally shard over dp (engine/optim.py
    with_master_weights + models/sharding.py opt_state_shardings;
    reference layout: Megatron DistributedOptimizer,
    megatron.py:823-940 -- previously modeled as 18 B/param over tp*pp
    only)."""
    return n_params * (2.0 + 16.0 / max(dp, 1)) / (tp * pp)


def pipeline_activation_bytes(hidden_dim: int, tokens_per_dp_rank: float,
                              n_stages: int,
                              n_microbatches: Optional[int] = None
                              ) -> float:
    """Resident pipeline activation bytes per stage under tick-level
    remat (transformer cfg pipeline_remat="tick"): the scan saves each
    tick's boundary activation (input carry + stacked output), bf16,
    for T = M + S - 1 ticks of one microbatch's tokens each --
    depth-independent (parallel/pipeline.py remat_tick; reference 1F1B
    keeps <= S microbatch sets, static_schedule.py:319)."""
    m = n_microbatches or 2 * n_stages
    t = m + n_stages - 1
    return 2.0 * t * (tokens_per_dp_rank / m) * hidden_dim * 2.0


def choose_layout(cfg: TransformerConfig, n_devices: int,
                  interface_type: ModelInterfaceType,
                  trainable: bool,
                  hbm_budget: int = DEFAULT_HBM_BUDGET,
                  tokens_per_batch: Optional[float] = None
                  ) -> ParallelismConfig:
    """One MFC's layout on ``n_devices`` chips. ``tokens_per_batch``
    (train batch seqs x seqlen, when known) lets the trainable fit
    check budget pipeline activations instead of weights-only (a pp
    allocation that ignores them can OOM on real shapes)."""
    n_params = cfg.n_params()

    if trainable:
        # ZeRO-1 changes the trade-off: moments shrink with dp, so the
        # fit check must use the dp each (tp, pp) candidate implies.
        def fits(tp, pp):
            dp = max(1, n_devices // (tp * pp))
            need = train_state_bytes_per_chip(n_params, tp, pp, dp)
            if pp > 1 and tokens_per_batch is not None:
                need += pipeline_activation_bytes(
                    cfg.hidden_dim, tokens_per_batch / dp, pp)
            return need <= hbm_budget

        tp = next((t for t in _pow2_up_to(n_devices) if fits(t, 1)),
                  n_devices)
        pp = 1
        if tp > TP_CAP:
            # Very large models: hold TP at one ICI ring and shard
            # layers over pipeline stages instead.
            tp = min(TP_CAP, n_devices)
            for cand in _pow2_up_to(max(1, n_devices // tp)):
                pp = cand
                if cfg.n_layers % cand == 0 and fits(tp, cand):
                    break
            while pp > 1 and cfg.n_layers % pp != 0:
                pp //= 2
        dp = max(1, n_devices // (tp * pp))
        per_chip = train_state_bytes_per_chip(n_params, tp, pp, dp)
        if pp > 1 and tokens_per_batch is not None:
            per_chip += pipeline_activation_bytes(
                cfg.hidden_dim, tokens_per_batch / dp, pp)
        if per_chip > hbm_budget:
            logger.warning(
                "Heuristic layout t%dp%d leaves %.1f GB/chip for a "
                "%.1f GB budget (n_layers=%d limits pipeline depth); "
                "expect OOM without remat/offload headroom or more "
                "devices.", tp, pp, per_chip / 1e9, hbm_budget / 1e9,
                cfg.n_layers)
        return ParallelismConfig(
            data_parallel_size=dp, tensor_parallel_size=tp,
            pipeline_parallel_size=pp, sequence_parallel=tp > 1)

    if interface_type == ModelInterfaceType.GENERATE:
        # bf16 weights + KV cache headroom
        bytes_needed = n_params * 2 * 1.5
    else:
        bytes_needed = n_params * 2 * 1.2
    tp = _min_tp(bytes_needed, n_devices, hbm_budget)
    pp = 1
    if (tp > TP_CAP and interface_type != ModelInterfaceType.GENERATE):
        tp = min(TP_CAP, n_devices)
        for cand in _pow2_up_to(max(1, n_devices // tp)):
            pp = cand
            if (cfg.n_layers % cand == 0
                    and bytes_needed / (tp * cand) <= hbm_budget):
                break
        while pp > 1 and cfg.n_layers % pp != 0:
            pp //= 2
    if bytes_needed / (tp * pp) > hbm_budget:
        logger.warning(
            "Heuristic layout t%dp%d leaves %.1f GB/chip for a %.1f GB "
            "budget (n_layers=%d limits pipeline depth); expect OOM "
            "without remat/offload headroom or more devices.",
            tp, pp, bytes_needed / (tp * pp) / 1e9, hbm_budget / 1e9,
            cfg.n_layers)
    dp = max(1, n_devices // (tp * pp))
    return ParallelismConfig(
        data_parallel_size=dp, tensor_parallel_size=tp,
        pipeline_parallel_size=pp,
        sequence_parallel=False)


def heuristic_allocations(
    spec, n_devices: int,
    hbm_budget: int = DEFAULT_HBM_BUDGET,
) -> Tuple[Dict[str, ParallelismConfig], Dict[str, ParallelismConfig]]:
    """(per-role primary layouts, per-MFC overrides) for an
    ExperimentSpec on ``n_devices`` chips.

    The primary layout of a role is its train MFC's layout when one
    exists (replicas have no optimizer), else its widest-TP MFC.
    MFC overrides are emitted only when they differ from the primary
    (each override creates a weight replica + realloc, reference
    resolve_replica_ids).
    """
    cfgs = {role: _model_config_of(ms) for role, ms in spec.models.items()}
    trainable_roles = {
        n.role for n in spec.mfcs
        if n.interface_type == ModelInterfaceType.TRAIN_STEP}

    # token estimate per train batch for the pipeline-activation
    # budget: dataset max_length bounds the PROMPT; RLHF train MFCs
    # consume prompt + generated tokens, so add the largest
    # max_new_tokens any generate MFC is configured with.
    max_len = (spec.dataset.args or {}).get("max_length") \
        if getattr(spec, "dataset", None) is not None else None
    gen_extra = 0
    for n in spec.mfcs:
        g = (n.interface_impl.args or {}).get("gconfig")
        if isinstance(g, dict):
            gen_extra = max(gen_extra, int(g.get("max_new_tokens", 0)))
    seq_est = (max_len + gen_extra) if max_len else None

    mfc_layouts: Dict[str, ParallelismConfig] = {}
    for node in spec.mfcs:
        trainable = (node.interface_type == ModelInterfaceType.TRAIN_STEP)
        tokens = (node.n_seqs * seq_est
                  if trainable and seq_est else None)
        mfc_layouts[node.name] = choose_layout(
            cfgs[node.role], n_devices, node.interface_type,
            trainable, hbm_budget, tokens_per_batch=tokens)

    primaries: Dict[str, ParallelismConfig] = {}
    for role in spec.models:
        role_nodes = [n for n in spec.mfcs if n.role == role]
        train = [n for n in role_nodes
                 if n.interface_type == ModelInterfaceType.TRAIN_STEP]
        if train:
            primaries[role] = mfc_layouts[train[0].name]
        elif role_nodes:
            primaries[role] = max(
                (mfc_layouts[n.name] for n in role_nodes),
                key=lambda p: p.tensor_parallel_size)
        else:
            primaries[role] = ParallelismConfig(
                data_parallel_size=n_devices)

    overrides = {
        n.name: mfc_layouts[n.name] for n in spec.mfcs
        if not mfc_layouts[n.name].same_layout(primaries[n.role])
    }
    return primaries, overrides


def apply_heuristic_allocations(spec, n_devices: int,
                                hbm_budget: int = DEFAULT_HBM_BUDGET):
    """Mutate an ExperimentSpec in place: set each role's primary
    parallelism and the per-MFC allocation overrides."""
    primaries, overrides = heuristic_allocations(spec, n_devices,
                                                 hbm_budget)
    for role, par in primaries.items():
        spec.models[role] = dataclasses.replace(spec.models[role],
                                                parallel=par)
    spec.allocations = dict(spec.allocations)
    spec.allocations.update(overrides)
    return spec
