"""Serving experiment: launch a standalone continuous-batching rollout
service (docs/serving.md) -- no dataflow graph, no master; just
``ServingSpec.n_servers`` GenServerWorker processes answering
RolloutClient traffic until stopped.

Standalone::

    python -m realhf_tpu.apps.quickstart serve \
        experiment_name=my-serve trial_name=t0 \
        model.path=/path/to/llama n_slots=16 max_new_tokens=512

Alongside a PPO trial as the asynchronous rollout producer: launch
with the same experiment/trial names so clients (and the trainer's
weight pushes) rendezvous through the shared name_resolve root, and
set ``max_staleness`` to the off-policyness bound the algorithm
tolerates.
"""

import dataclasses
from typing import Optional

from realhf_tpu.api.experiment import ExperimentSpec, ServingSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class ServeConfig(CommonExperimentConfig):
    model: ModelConfigCLI = dataclasses.field(
        default_factory=ModelConfigCLI)
    n_servers: int = 1
    n_slots: int = 4
    chunk_size: int = 8
    max_prompt_len: int = 512
    max_queue_depth: int = 256
    max_staleness: Optional[int] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    stream_tokens: bool = True
    drain_timeout_secs: float = 30.0
    # resilient fleet mode: front the n_servers replicas with a
    # health-aware FleetRouter (failover, hedging, circuit breakers --
    # docs/serving.md); clients then use server_name="router"
    fleet_router: bool = False
    lease_ttl_secs: float = 5.0
    router_hedge_delay_secs: Optional[float] = None
    router_max_hedges: int = 1
    router_breaker_failures: int = 3
    router_breaker_cooldown_secs: float = 5.0
    router_dispatch_timeout_secs: float = 10.0
    router_response_timeout_secs: Optional[float] = 60.0
    router_max_pending: int = 1024
    # sampling defaults for every request (per-request overrides ride
    # on the request itself in a future PR)
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0
    temperature: float = 1.0
    # how long run_serve keeps the service up before graceful drain;
    # None = until interrupted
    serve_duration_secs: Optional[float] = None

    def build(self) -> ExperimentSpec:
        serving = ServingSpec(
            model_role="default",
            n_servers=self.n_servers,
            n_slots=self.n_slots,
            chunk_size=self.chunk_size,
            max_prompt_len=self.max_prompt_len,
            max_queue_depth=self.max_queue_depth,
            max_staleness=self.max_staleness,
            eos_token_id=self.eos_token_id,
            pad_token_id=self.pad_token_id,
            stream_tokens=self.stream_tokens,
            drain_timeout_secs=self.drain_timeout_secs,
            fleet_router=self.fleet_router,
            lease_ttl_secs=self.lease_ttl_secs,
            router_hedge_delay_secs=self.router_hedge_delay_secs,
            router_max_hedges=self.router_max_hedges,
            router_breaker_failures=self.router_breaker_failures,
            router_breaker_cooldown_secs=self.router_breaker_cooldown_secs,
            router_dispatch_timeout_secs=self.router_dispatch_timeout_secs,
            router_response_timeout_secs=self.router_response_timeout_secs,
            router_max_pending=self.router_max_pending,
            gconfig=dict(
                max_new_tokens=self.max_new_tokens,
                min_new_tokens=self.min_new_tokens,
                greedy=self.greedy, top_p=self.top_p,
                top_k=self.top_k, temperature=self.temperature))
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={"default": self.model.to_spec(train=False)},
            mfcs=[],
            dataset=None,
            tokenizer_path=self.tokenizer_path or self.model.path,
            seed=self.seed,
            ctl=self.ctl(),
            serving=serving)


register_experiment("serve", ServeConfig)
