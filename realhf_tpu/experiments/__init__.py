"""Experiment configurations; importing registers them for the CLI."""

import realhf_tpu.experiments.sft_exp  # noqa: F401
import realhf_tpu.experiments.rw_exp  # noqa: F401
import realhf_tpu.experiments.dpo_exp  # noqa: F401
import realhf_tpu.experiments.ppo_exp  # noqa: F401
import realhf_tpu.experiments.gen_exp  # noqa: F401
import realhf_tpu.experiments.profile_exp  # noqa: F401
import realhf_tpu.experiments.grpo_exp  # noqa: F401
import realhf_tpu.experiments.serve_exp  # noqa: F401
import realhf_tpu.experiments.agentic_exp  # noqa: F401

from realhf_tpu.experiments.common import (  # noqa: F401
    ALL_EXPERIMENT_CLASSES,
    register_experiment,
)
