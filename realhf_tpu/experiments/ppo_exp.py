"""PPO experiment: the 6-MFC RLHF dataflow graph.

Parity with reference ``realhf/experiments/common/ppo_exp.py:230-377``:
actor_gen -> {rew_inf, ref_inf, critic_inf} -> {actor_train,
critic_train} over four model roles (actor, critic, ref, reward).
"""

import dataclasses
from typing import Optional

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class PPOHyperparameters:
    """Reference PPOHyperparameters (ppo_exp.py:33)."""
    max_new_tokens: int = 256
    min_new_tokens: int = 256
    greedy: bool = False
    top_p: float = 0.9
    top_k: int = 200
    temperature: float = 1.0
    force_no_logits_mask: bool = False
    ppo_n_minibatches: int = 4
    kl_ctl: float = 0.1
    discount: float = 1.0
    gae_lambda: float = 1.0
    eps_clip: float = 0.2
    value_eps_clip: float = 0.2
    max_reward_clip: float = 20.0
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    early_stop_imp_ratio: float = 5.0
    use_adaptive_kl_ctl: bool = False
    # async/off-policy consumption (docs/distributed.md "Async RLHF"):
    # drop sequences staler than this many trainer versions; bound the
    # clipped-IS correction for the stale remainder (None disables)
    max_staleness: Optional[int] = None
    staleness_is_clip: Optional[float] = 2.0
    adv_norm: bool = True
    value_norm: bool = True
    value_norm_type: str = "exp"
    value_norm_beta: float = 0.99995
    value_norm_eps: float = 1e-5


@dataclasses.dataclass
class PPOConfig(CommonExperimentConfig):
    actor: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    critic: ModelConfigCLI = dataclasses.field(
        default_factory=lambda: ModelConfigCLI(is_critic=True))
    ref: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    rew: ModelConfigCLI = dataclasses.field(
        default_factory=lambda: ModelConfigCLI(is_critic=True))
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    ppo: PPOHyperparameters = dataclasses.field(
        default_factory=PPOHyperparameters)
    actor_gen_n_mbs: int = 1
    actor_train_n_mbs: int = 1
    critic_inf_n_mbs: int = 1
    critic_train_n_mbs: int = 1
    rew_inf_n_mbs: int = 1
    ref_inf_n_mbs: int = 1
    # Per-MFC batch size (api/dfg.MFCDef.n_seqs): actor_gen may run at
    # a LARGER granularity than the train MFCs (e.g. 2x the train
    # batch) -- the per-sample buffer assembles each MFC's batch from
    # whichever ready samples exist, so generation streams ahead while
    # training drains at train_bs_n_seqs. None keeps the aligned
    # (lockstep) default.
    actor_gen_n_seqs: Optional[int] = None
    # Per-MFC layout overrides in the reference's "d4t2"-style shorthand
    # (decoupled allocation => weight replicas + parameter reallocation).
    actor_gen_alloc: Optional[str] = None
    rew_inf_alloc: Optional[str] = None
    ref_inf_alloc: Optional[str] = None
    critic_inf_alloc: Optional[str] = None

    def build(self) -> ExperimentSpec:
        p = self.ppo
        gconfig = dict(
            max_new_tokens=p.max_new_tokens,
            min_new_tokens=p.min_new_tokens,
            greedy=p.greedy, top_p=p.top_p, top_k=p.top_k,
            temperature=p.temperature,
            force_no_logits_mask=p.force_no_logits_mask)
        actor_args = dict(
            n_minibatches=p.ppo_n_minibatches, gconfig=gconfig,
            kl_ctl=p.kl_ctl, discount=p.discount, gae_lambda=p.gae_lambda,
            eps_clip=p.eps_clip, max_reward_clip=p.max_reward_clip,
            early_stop_imp_ratio=p.early_stop_imp_ratio,
            max_staleness=p.max_staleness,
            staleness_is_clip=p.staleness_is_clip,
            adv_norm=p.adv_norm,
            use_adaptive_kl_ctl=p.use_adaptive_kl_ctl,
            value_norm=p.value_norm, value_norm_type=p.value_norm_type,
            value_norm_beta=p.value_norm_beta,
            value_norm_eps=p.value_norm_eps)
        critic_args = dict(
            n_minibatches=p.ppo_n_minibatches, kl_ctl=p.kl_ctl,
            discount=p.discount, gae_lambda=p.gae_lambda,
            value_eps_clip=p.value_eps_clip,
            max_reward_clip=p.max_reward_clip,
            use_adaptive_kl_ctl=p.use_adaptive_kl_ctl,
            value_norm=p.value_norm, value_norm_type=p.value_norm_type,
            value_norm_beta=p.value_norm_beta,
            value_norm_eps=p.value_norm_eps)
        actor_itf = ModelInterfaceAbstraction("ppo_actor", actor_args)
        critic_itf = ModelInterfaceAbstraction("ppo_critic", critic_args)
        rw_itf = ModelInterfaceAbstraction(
            "paired_rw", dict(output_scaling=p.reward_output_scaling,
                              output_bias=p.reward_output_bias,
                              enable_save=False))
        n = self.dataset.train_bs_n_seqs
        # actor_gen (the source MFC) may run at its own granularity:
        # the dataset loader batches at the SOURCE n_seqs, and the
        # per-sample buffer lets the downstream MFCs drain at theirs
        n_gen = self.actor_gen_n_seqs or n
        gen_outputs = ["seq_no_eos_mask", "packed_input_ids",
                       "packed_logprobs", "prompt_mask"]
        if not p.force_no_logits_mask:
            gen_outputs.append("packed_logits_mask")
        ref_inputs = ["packed_input_ids"]
        if not p.force_no_logits_mask:
            ref_inputs.append("packed_logits_mask")
        train_inputs = ("packed_input_ids", "packed_logprobs",
                        "packed_ref_logprobs", "rewards", "values",
                        "prompt_mask", "seq_no_eos_mask")
        mfcs = [
            MFCDef(name="actor_gen", n_seqs=n_gen,
                   interface_type=ModelInterfaceType.GENERATE,
                   interface_impl=actor_itf, model_name="actor",
                   input_keys=("packed_prompts",),
                   output_keys=tuple(gen_outputs),
                   n_mbs=self.actor_gen_n_mbs),
            MFCDef(name="rew_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=rw_itf, model_name="reward",
                   input_keys=("packed_input_ids",),
                   output_keys=("rewards",),
                   n_mbs=self.rew_inf_n_mbs),
            MFCDef(name="ref_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=actor_itf, model_name="ref",
                   input_keys=tuple(ref_inputs),
                   output_keys=("packed_ref_logprobs",),
                   n_mbs=self.ref_inf_n_mbs),
            MFCDef(name="critic_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=critic_itf, model_name="critic",
                   input_keys=("packed_input_ids", "seq_no_eos_mask"),
                   output_keys=("values",),
                   n_mbs=self.critic_inf_n_mbs),
            MFCDef(name="actor_train", n_seqs=n,
                   interface_type=ModelInterfaceType.TRAIN_STEP,
                   interface_impl=actor_itf, model_name="actor",
                   input_keys=train_inputs + (
                       ("packed_logits_mask",)
                       if not p.force_no_logits_mask else ()),
                   log_return_value=True,
                   n_mbs=self.actor_train_n_mbs),
            MFCDef(name="critic_train", n_seqs=n,
                   interface_type=ModelInterfaceType.TRAIN_STEP,
                   interface_impl=critic_itf, model_name="critic",
                   input_keys=train_inputs,
                   log_return_value=True,
                   n_mbs=self.critic_train_n_mbs),
        ]
        dataset = DatasetAbstraction(
            "prompt", args=dict(max_length=self.dataset.max_seqlen,
                                dataset_path=self.dataset.path))
        from realhf_tpu.parallel.mesh import parse_parallelism
        allocations = {}
        for mfc_name, alloc in (("actor_gen", self.actor_gen_alloc),
                                ("rew_inf", self.rew_inf_alloc),
                                ("ref_inf", self.ref_inf_alloc),
                                ("critic_inf", self.critic_inf_alloc)):
            if alloc:
                allocations[mfc_name] = parse_parallelism(alloc)
        return ExperimentSpec(
            allocations=allocations,
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={
                "actor": self.actor.to_spec(train=True),
                "critic": dataclasses.replace(
                    self.critic.to_spec(train=True), is_critic=True),
                "ref": self.ref.to_spec(train=False),
                "reward": dataclasses.replace(
                    self.rew.to_spec(train=False), is_critic=True),
            },
            mfcs=mfcs,
            dataset=dataset,
            tokenizer_path=self.tokenizer_path or self.actor.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            max_concurrent_batches=self.max_concurrent_batches,
            max_head_offpolicyness=self.max_head_offpolicyness,
            ctl=self.ctl())


register_experiment("ppo", PPOConfig)
