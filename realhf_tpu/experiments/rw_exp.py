"""Reward-model experiment (reference ``rw_exp.py``): one critic-mode
model, one train_step MFC over paired data."""

import dataclasses

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class RWConfig(CommonExperimentConfig):
    model: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    max_pairs_per_prompt: int = 2
    n_mbs: int = 1

    def build(self) -> ExperimentSpec:
        self.model.is_critic = True
        mfc = MFCDef(
            name="trainDefault",
            n_seqs=self.dataset.train_bs_n_seqs,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("paired_rw"),
            model_name="default",
            input_keys=("packed_input_ids", "prompt_lens"),
            log_return_value=True,
            n_mbs=self.n_mbs)
        dataset = DatasetAbstraction(
            "rw_pair",
            args=dict(max_length=self.dataset.max_seqlen,
                      max_pairs_per_prompt=self.max_pairs_per_prompt,
                      dataset_path=self.dataset.path))
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={"default": self.model.to_spec(train=True)},
            mfcs=[mfc],
            dataset=dataset,
            tokenizer_path=self.tokenizer_path or self.model.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            ctl=self.ctl())


register_experiment("rw", RWConfig)
