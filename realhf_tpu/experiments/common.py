"""Common experiment config: the CLI surface shared by all algorithms.

Parity with reference ``realhf/experiments/common/common.py``
(CommonExperimentConfig:58): experiment/trial names, allocation mode,
model/dataset/optimizer settings, save/eval control. The quickstart
CLI builds one of these dataclasses from dotted key=value overrides
(the reference uses Hydra; the override syntax is the same
`a.b.c=value` style, reference ``apps/quickstart.py:34-76``).
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, get_args, get_origin

from realhf_tpu.api.experiment import ExperimentSpec, ModelSpec, SaveEvalControl
from realhf_tpu.engine.optim import OptimizerConfig
from realhf_tpu.parallel.mesh import ParallelismConfig


@dataclasses.dataclass
class ModelConfigCLI:
    """CLI view of one model (reference ModelTrainEvalConfig)."""
    type: str = "llama"
    path: Optional[str] = None
    is_critic: bool = False
    init_critic_from_actor: bool = False
    bf16: bool = True
    gradient_checkpointing: bool = True
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=OptimizerConfig)
    parallel: ParallelismConfig = dataclasses.field(
        default_factory=ParallelismConfig)
    # None = auto (stream checkpoints > 16 GB on single-process
    # meshes); True/False force (ModelSpec.streamed_load)
    streamed_load: Optional[bool] = None
    # Free the decode view's second weight copy after each generate
    # MFC on pp/ctx meshes (ModelSpec.drop_decode_view_after_rollout)
    drop_decode_view_after_rollout: bool = False

    def to_spec(self, train: bool = True,
                random_init_config: Optional[dict] = None) -> ModelSpec:
        return ModelSpec(
            hf_family=self.type,
            path=self.path,
            random_init_config=random_init_config,
            is_critic=self.is_critic,
            init_critic_from_actor=self.init_critic_from_actor,
            optimizer=self.optimizer if train else None,
            parallel=self.parallel,
            gradient_checkpointing=self.gradient_checkpointing,
            bf16=self.bf16,
            streamed_load=self.streamed_load,
            drop_decode_view_after_rollout=(
                self.drop_decode_view_after_rollout))


@dataclasses.dataclass
class DatasetConfigCLI:
    path: str = ""
    max_seqlen: int = 1024
    train_bs_n_seqs: int = 256
    pad_to_max_length: bool = False
    valid_path: Optional[str] = None


@dataclasses.dataclass
class CommonExperimentConfig:
    experiment_name: str = "exp"
    trial_name: str = "trial"
    seed: int = 1
    total_train_epochs: int = 1
    tokenizer_path: Optional[str] = None
    save_freq_epochs: Optional[int] = None
    save_freq_steps: Optional[int] = None
    save_freq_secs: Optional[float] = None
    eval_freq_epochs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
    benchmark_steps: Optional[int] = None
    # disabled | resume | auto (reference recover_mode, common.py:70-82;
    # "save" behavior -- dumping recover info -- is implied by resume;
    # "auto" additionally relaunches failed distributed trials)
    recover_mode: str = "disabled"
    recover_retries: int = 1
    # inline (single process) | distributed (master + model workers)
    mode: str = "inline"
    # distributed-mode pipelining (api/experiment.ExperimentSpec):
    # dataset batches in flight at once, and how many of its own
    # batches a non-train MFC may run ahead of its role's train MFCs
    # (the off-policyness budget of the per-sample buffer)
    max_concurrent_batches: int = 2
    max_head_offpolicyness: int = 0
    # manual (per-MFC *_alloc flags / role parallel configs) |
    # heuristic (size-based decoupled layouts, reference
    # ppo_exp.py:419; requires n_devices)
    allocation_mode: str = "manual"
    n_devices: Optional[int] = None
    n_model_workers: int = 1
    # "role:workerIdx,role:workerIdx" -- which model worker hosts each
    # role in distributed mode (unlisted roles land on worker 0).
    # "role:0+1" assigns a worker GROUP: the role's mesh spans both
    # workers' devices (multi-host model; leader = first index).
    worker_assignment: str = ""

    def parsed_worker_assignment(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        if self.worker_assignment:
            for part in self.worker_assignment.split(","):
                role, idx = part.split(":")
                members = [int(x) for x in idx.split("+")]
                out[role.strip()] = members[0] if len(members) == 1 \
                    else members
        return out

    def ctl(self) -> SaveEvalControl:
        return SaveEvalControl(
            save_freq_epochs=self.save_freq_epochs,
            save_freq_steps=self.save_freq_steps,
            save_freq_secs=self.save_freq_secs,
            eval_freq_epochs=self.eval_freq_epochs,
            eval_freq_steps=self.eval_freq_steps,
            benchmark_steps=self.benchmark_steps)

    def build(self) -> ExperimentSpec:
        raise NotImplementedError()


ALL_EXPERIMENT_CLASSES: Dict[str, Callable[[], CommonExperimentConfig]] = {}


def register_experiment(name: str, cls):
    if name in ALL_EXPERIMENT_CLASSES:
        raise ValueError(f"Experiment {name} already registered.")
    ALL_EXPERIMENT_CLASSES[name] = cls


# ----------------------------------------------------------------------
# Dotted key=value overrides onto nested dataclasses.
# ----------------------------------------------------------------------
def _convert(value: str, typ) -> Any:
    origin = get_origin(typ)
    if origin is not None:  # Optional[...] and friends
        args = [a for a in get_args(typ) if a is not type(None)]
        if value.lower() in ("none", "null"):
            return None
        return _convert(value, args[0]) if args else value
    if typ is bool or isinstance(typ, type) and issubclass(typ, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


def apply_overrides(cfg: Any, overrides: Dict[str, str]) -> Any:
    """Apply {'a.b.c': 'v'} onto a nested dataclass in place."""
    for dotted, raw in overrides.items():
        parts = dotted.split(".")
        obj = cfg
        for p in parts[:-1]:
            if not hasattr(obj, p):
                raise AttributeError(
                    f"Unknown config path `{dotted}` (no field `{p}` on "
                    f"{type(obj).__name__}).")
            obj = getattr(obj, p)
        leaf = parts[-1]
        fields = {f.name: f for f in dataclasses.fields(obj)}
        if leaf not in fields:
            raise AttributeError(
                f"Unknown config field `{dotted}` on {type(obj).__name__}; "
                f"valid fields: {sorted(fields)}")
        frozen = getattr(type(obj), "__dataclass_params__").frozen
        val = _convert(raw, fields[leaf].type
                       if not isinstance(fields[leaf].type, str)
                       else _resolve_type(obj, leaf))
        if frozen:
            # frozen dataclasses (e.g. ParallelismConfig) are replaced
            parent = cfg
            for p in parts[:-2]:
                parent = getattr(parent, p)
            setattr(parent, parts[-2],
                    dataclasses.replace(obj, **{leaf: val}))
        else:
            setattr(obj, leaf, val)
    return cfg


def _resolve_type(obj, field_name):
    import typing
    hints = typing.get_type_hints(type(obj))
    return hints.get(field_name, str)
