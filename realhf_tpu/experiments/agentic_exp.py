"""Agentic PPO experiment: the environment-in-the-loop dataflow graph.

The 5-MFC graph for env-rewarded multi-turn RL (docs/agentic.md):

    actor_gen (agentic_actor: episodes through the env, turn rewards)
        -> {ref_inf, critic_inf} -> {actor_train, critic_train}

Structurally a PPO graph with the reward-model MFC DELETED -- the
environment's programmatic checker IS the reward model, so ``rewards``
(episode total) and ``dense_rewards`` (per-turn placement) come out of
``actor_gen`` itself. Three model roles: actor, critic, ref. With
``agentic.turn_level_credit`` (default on) the PPO interfaces place
credit at each turn's last action token and let GAE bridge the masked
observation gaps; switching it off recovers the end-of-sequence
behavior on the same trajectories.
"""

import dataclasses
from typing import Dict, Optional

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)
from realhf_tpu.experiments.ppo_exp import PPOHyperparameters


@dataclasses.dataclass
class AgenticHyperparameters:
    """The env-in-the-loop knobs riding next to the PPO block."""
    #: registered env name (realhf_tpu.agentic.env)
    env: str = "checker_task"
    #: env constructor kwargs (vocab_size defaults to the model's)
    env_args: Dict = dataclasses.field(default_factory=dict)
    #: per-episode turn cap (multi-turn envs may finish earlier)
    max_turns: int = 4
    #: per-episode context cap in tokens (None = auto)
    max_context_len: Optional[int] = None
    #: concurrent episodes inside one generate MFC (0 = whole batch)
    max_concurrent: int = 0
    #: reward at each turn's last action token + GAE across masked
    #: gaps; False = episode-total reward at end of sequence
    turn_level_credit: bool = True
    #: dataset type feeding the episodes (checker_task | tool_game)
    dataset_type: str = "checker_task"
    #: synthetic dataset size (ignored when dataset.path is set)
    n_prompts: int = 128


@dataclasses.dataclass
class AgenticPPOConfig(CommonExperimentConfig):
    actor: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    critic: ModelConfigCLI = dataclasses.field(
        default_factory=lambda: ModelConfigCLI(is_critic=True))
    ref: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    ppo: PPOHyperparameters = dataclasses.field(
        default_factory=PPOHyperparameters)
    agentic: AgenticHyperparameters = dataclasses.field(
        default_factory=AgenticHyperparameters)
    actor_gen_n_mbs: int = 1
    actor_train_n_mbs: int = 1
    critic_inf_n_mbs: int = 1
    critic_train_n_mbs: int = 1
    ref_inf_n_mbs: int = 1
    #: generation granularity (episodes per actor_gen MFC); None =
    #: lockstep with the train batch. Per-sample buffer semantics are
    #: identical to single-turn PPO (MFCDef.n_seqs contract).
    actor_gen_n_seqs: Optional[int] = None

    def build(self) -> ExperimentSpec:
        p, a = self.ppo, self.agentic
        gconfig = dict(
            max_new_tokens=p.max_new_tokens,
            min_new_tokens=p.min_new_tokens,
            greedy=p.greedy, top_p=p.top_p, top_k=p.top_k,
            temperature=p.temperature,
            # the episode path never replays sampling logits masks
            force_no_logits_mask=True)
        actor_args = dict(
            n_minibatches=p.ppo_n_minibatches, gconfig=gconfig,
            kl_ctl=p.kl_ctl, discount=p.discount,
            gae_lambda=p.gae_lambda,
            eps_clip=p.eps_clip, max_reward_clip=p.max_reward_clip,
            early_stop_imp_ratio=p.early_stop_imp_ratio,
            max_staleness=p.max_staleness,
            staleness_is_clip=p.staleness_is_clip,
            adv_norm=p.adv_norm,
            use_adaptive_kl_ctl=p.use_adaptive_kl_ctl,
            value_norm=p.value_norm, value_norm_type=p.value_norm_type,
            value_norm_beta=p.value_norm_beta,
            value_norm_eps=p.value_norm_eps,
            turn_level_credit=a.turn_level_credit)
        gen_args = dict(actor_args, env=a.env, env_args=dict(a.env_args),
                        max_turns=a.max_turns,
                        max_context_len=a.max_context_len,
                        max_concurrent=a.max_concurrent)
        critic_args = dict(
            n_minibatches=p.ppo_n_minibatches, kl_ctl=p.kl_ctl,
            discount=p.discount, gae_lambda=p.gae_lambda,
            value_eps_clip=p.value_eps_clip,
            max_reward_clip=p.max_reward_clip,
            use_adaptive_kl_ctl=p.use_adaptive_kl_ctl,
            value_norm=p.value_norm, value_norm_type=p.value_norm_type,
            value_norm_beta=p.value_norm_beta,
            value_norm_eps=p.value_norm_eps,
            turn_level_credit=a.turn_level_credit)
        gen_itf = ModelInterfaceAbstraction("agentic_actor", gen_args)
        actor_itf = ModelInterfaceAbstraction("ppo_actor", actor_args)
        critic_itf = ModelInterfaceAbstraction("ppo_critic", critic_args)
        n = self.dataset.train_bs_n_seqs
        n_gen = self.actor_gen_n_seqs or n
        gen_outputs = ("seq_no_eos_mask", "packed_input_ids",
                       "packed_logprobs", "prompt_mask", "rewards",
                       "dense_rewards")
        train_inputs = ("packed_input_ids", "packed_logprobs",
                        "packed_ref_logprobs", "rewards",
                        "dense_rewards", "values", "prompt_mask",
                        "seq_no_eos_mask")
        mfcs = [
            MFCDef(name="actor_gen", n_seqs=n_gen,
                   interface_type=ModelInterfaceType.GENERATE,
                   interface_impl=gen_itf, model_name="actor",
                   input_keys=("packed_prompts",),
                   output_keys=gen_outputs,
                   n_mbs=self.actor_gen_n_mbs),
            MFCDef(name="ref_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=actor_itf, model_name="ref",
                   input_keys=("packed_input_ids",),
                   output_keys=("packed_ref_logprobs",),
                   n_mbs=self.ref_inf_n_mbs),
            MFCDef(name="critic_inf", n_seqs=n,
                   interface_type=ModelInterfaceType.INFERENCE,
                   interface_impl=critic_itf, model_name="critic",
                   input_keys=("packed_input_ids", "seq_no_eos_mask"),
                   output_keys=("values",),
                   n_mbs=self.critic_inf_n_mbs),
            MFCDef(name="actor_train", n_seqs=n,
                   interface_type=ModelInterfaceType.TRAIN_STEP,
                   interface_impl=gen_itf, model_name="actor",
                   input_keys=train_inputs,
                   log_return_value=True,
                   n_mbs=self.actor_train_n_mbs),
            MFCDef(name="critic_train", n_seqs=n,
                   interface_type=ModelInterfaceType.TRAIN_STEP,
                   interface_impl=critic_itf, model_name="critic",
                   input_keys=train_inputs,
                   log_return_value=True,
                   n_mbs=self.critic_train_n_mbs),
        ]
        ds_args = dict(n_prompts=a.n_prompts)
        if self.dataset.path:
            ds_args = dict(dataset_path=self.dataset.path)
        dataset = DatasetAbstraction(a.dataset_type, args=ds_args)
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={
                "actor": self.actor.to_spec(train=True),
                "critic": dataclasses.replace(
                    self.critic.to_spec(train=True), is_critic=True),
                "ref": self.ref.to_spec(train=False),
            },
            mfcs=mfcs,
            dataset=dataset,
            tokenizer_path=self.tokenizer_path or self.actor.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            max_concurrent_batches=self.max_concurrent_batches,
            max_head_offpolicyness=self.max_head_offpolicyness,
            ctl=self.ctl())


register_experiment("agentic", AgenticPPOConfig)
