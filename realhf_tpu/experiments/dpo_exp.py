"""DPO experiment (reference ``dpo_exp.py``): ref-model inference MFC
feeding the policy train MFC."""

import dataclasses

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class DPOConfig(CommonExperimentConfig):
    actor: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    ref: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    beta: float = 0.1
    max_pairs_per_prompt: int = 2
    n_mbs: int = 1

    def build(self) -> ExperimentSpec:
        itf = ModelInterfaceAbstraction("dpo", dict(beta=self.beta))
        ref_inf = MFCDef(
            name="ref_inf",
            n_seqs=self.dataset.train_bs_n_seqs,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=itf,
            model_name="ref",
            input_keys=("packed_input_ids", "prompt_lens"),
            output_keys=("seqlogp",))
        train = MFCDef(
            name="actor_train",
            n_seqs=self.dataset.train_bs_n_seqs,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=itf,
            model_name="actor",
            input_keys=("packed_input_ids", "prompt_lens", "seqlogp"),
            log_return_value=True,
            n_mbs=self.n_mbs)
        dataset = DatasetAbstraction(
            "rw_pair",
            args=dict(max_length=self.dataset.max_seqlen,
                      max_pairs_per_prompt=self.max_pairs_per_prompt,
                      dataset_path=self.dataset.path))
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={"actor": self.actor.to_spec(train=True),
                    "ref": self.ref.to_spec(train=False)},
            mfcs=[ref_inf, train],
            dataset=dataset,
            tokenizer_path=self.tokenizer_path or self.actor.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            ctl=self.ctl())


register_experiment("dpo", DPOConfig)
