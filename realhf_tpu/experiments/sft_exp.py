"""SFT experiment (reference ``realhf/experiments/common/sft_exp.py``):
one model, one train_step MFC over prompt-answer data."""

import dataclasses

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class SFTConfig(CommonExperimentConfig):
    model: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    n_mbs: int = 1

    def build(self) -> ExperimentSpec:
        mfc = MFCDef(
            name="trainDefault",
            n_seqs=self.dataset.train_bs_n_seqs,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("sft"),
            model_name="default",
            input_keys=("packed_input_ids", "prompt_mask"),
            log_return_value=True,
            n_mbs=self.n_mbs)
        dataset = DatasetAbstraction(
            "prompt_answer",
            args=dict(max_length=self.dataset.max_seqlen,
                      dataset_path=self.dataset.path,
                      pad_to_max_length=self.dataset.pad_to_max_length))
        eval_dataset = None
        if self.dataset.valid_path:
            eval_dataset = DatasetAbstraction(
                "prompt_answer",
                args=dict(max_length=self.dataset.max_seqlen,
                          dataset_path=self.dataset.valid_path))
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={"default": self.model.to_spec(train=True)},
            mfcs=[mfc],
            dataset=dataset,
            eval_dataset=eval_dataset,
            tokenizer_path=self.tokenizer_path or self.model.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            ctl=self.ctl())


register_experiment("sft", SFTConfig)
