"""Generation experiment (reference ``gen_exp.py``): batch generation
over a prompt dataset, dumped to JSONL."""

import dataclasses
from typing import Optional

from realhf_tpu.api.config import (
    DatasetAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
)
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.experiments.common import (
    CommonExperimentConfig,
    DatasetConfigCLI,
    ModelConfigCLI,
    register_experiment,
)


@dataclasses.dataclass
class GenerationConfig(CommonExperimentConfig):
    model: ModelConfigCLI = dataclasses.field(default_factory=ModelConfigCLI)
    dataset: DatasetConfigCLI = dataclasses.field(
        default_factory=DatasetConfigCLI)
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = 0
    temperature: float = 1.0
    output_file: Optional[str] = None
    n_mbs: int = 1

    def build(self) -> ExperimentSpec:
        output_file = self.output_file
        gconfig = dict(
            max_new_tokens=self.max_new_tokens,
            min_new_tokens=self.min_new_tokens,
            greedy=self.greedy, top_p=self.top_p, top_k=self.top_k,
            temperature=self.temperature, force_no_logits_mask=True)
        mfc = MFCDef(
            name="gen",
            n_seqs=self.dataset.train_bs_n_seqs,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=ModelInterfaceAbstraction(
                "generation", dict(gconfig=gconfig,
                                   output_file=output_file)),
            model_name="default",
            input_keys=("packed_prompts",),
            n_mbs=self.n_mbs)
        dataset = DatasetAbstraction(
            "prompt", args=dict(max_length=self.dataset.max_seqlen,
                                dataset_path=self.dataset.path))
        return ExperimentSpec(
            experiment_name=self.experiment_name,
            trial_name=self.trial_name,
            models={"default": self.model.to_spec(train=False)},
            mfcs=[mfc],
            dataset=dataset,
            tokenizer_path=self.tokenizer_path or self.model.path,
            total_train_epochs=self.total_train_epochs,
            seed=self.seed,
            ctl=self.ctl())


register_experiment("gen", GenerationConfig)
