"""Profile experiment: the full runtime on synthetic data.

Parity with reference ``realhf/experiments/benchmark/profile_exp.py``
(+ ``ModelInterface.mock``): run the 6-MFC PPO dataflow graph with
random-init models and random prompts through the real runtime (inline
or distributed), with per-MFC timing from the TimeMarkDB and optional
``jax.profiler`` trace dumps (REALHF_TPU_DUMP_TRACE=1 /
REALHF_TPU_DUMP_MEMORY=1, base/monitor.py). Serves as both a system
test (everything wired, nothing real needed) and the measurement rig
for allocation decisions.

    python -m realhf_tpu.apps.quickstart profile \
        model_size=7b n_prompts=256 max_new_tokens=256 \
        benchmark_steps=3 actor_gen_alloc=d8t1
"""

import dataclasses
from typing import Dict

from realhf_tpu.api.config import DatasetAbstraction
from realhf_tpu.api.experiment import ExperimentSpec, ModelSpec
from realhf_tpu.engine.optim import OptimizerConfig
from realhf_tpu.experiments.common import register_experiment
from realhf_tpu.experiments.ppo_exp import PPOConfig

#: named model sizes (llama lineage; "tiny" for CI)
MODEL_SIZES: Dict[str, dict] = {
    "tiny": dict(n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
                 intermediate_dim=64, vocab_size=1000),
    "125m": dict(n_layers=12, n_kv_heads=12, n_q_heads=12,
                 hidden_dim=768, intermediate_dim=3072, vocab_size=32000),
    "1b": dict(n_layers=22, n_kv_heads=4, n_q_heads=32,
               hidden_dim=2048, intermediate_dim=5632, vocab_size=32000),
    "7b": dict(n_layers=32, n_kv_heads=32, n_q_heads=32,
               hidden_dim=4096, intermediate_dim=11008, vocab_size=32000),
}

_COMMON = dict(apply_rotary=True, layer_norm_type="rms", mlp_type="llama",
               use_attention_bias=False, use_attn_proj_bias=False,
               use_mlp_bias=False, activation_function="silu")


@dataclasses.dataclass
class ProfileConfig(PPOConfig):
    """PPO graph on synthetic data (inherits the 6 MFCs + per-MFC
    alloc/n_mbs knobs from PPOConfig)."""
    model_size: str = "tiny"
    n_prompts: int = 64
    prompt_len_min: int = 16
    prompt_len_max: int = 64
    bf16: bool = True
    lr: float = 1e-5

    def build(self) -> ExperimentSpec:
        if not self.benchmark_steps:
            self.benchmark_steps = 3
        spec = super().build()
        size = dict(MODEL_SIZES[self.model_size], **_COMMON)
        vocab = size["vocab_size"]
        for role, mspec in spec.models.items():
            is_critic = mspec.is_critic or role in ("critic", "reward")
            spec.models[role] = ModelSpec(
                hf_family="llama", path=None,
                random_init_config=dict(size),
                is_critic=is_critic,
                optimizer=(OptimizerConfig(
                    lr=self.lr, warmup_steps_proportion=0.0,
                    lr_scheduler_type="constant")
                    if mspec.optimizer is not None else None),
                parallel=mspec.parallel,
                bf16=self.bf16)
        spec.dataset = DatasetAbstraction(
            "random_prompt",
            args=dict(n_prompts=self.n_prompts,
                      prompt_len_min=self.prompt_len_min,
                      prompt_len_max=self.prompt_len_max,
                      vocab_size=vocab,
                      max_length=self.dataset.max_seqlen))
        # synthetic ids need no tokenizer beyond pad/eos conventions
        from realhf_tpu.base.testing import IntegerTokenizer
        spec.tokenizer = IntegerTokenizer(vocab_size=vocab - 2)
        return spec


register_experiment("profile", ProfileConfig)


def mfc_timing_summary() -> Dict[str, float]:
    """Per-MFC wall-clock totals recorded by the runtime's
    mfc_profile_region spans (seconds)."""
    from realhf_tpu.base import monitor
    return {k: v for k, v in monitor.tmark_db().summary().items()
            if k.startswith("mfc/")}
