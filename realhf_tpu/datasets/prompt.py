"""Prompt-only dataset for PPO / generation.

Parity with reference ``realhf/impl/dataset/prompt_dataset.py``: JSONL
records with unique "id" and "prompt"; each item yields a
SequenceSample with key ``packed_prompts``.
"""

import uuid
from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.api import data as data_api
from realhf_tpu.base import logging

logger = logging.getLogger("PromptDataset")


class PromptDataset:

    def __init__(self, util: data_api.DatasetUtility,
                 max_length: Optional[int] = None,
                 dataset_path: Optional[str] = None,
                 dataset_builder: Optional[Callable[[], List[Dict]]] = None,
                 pad_to_max_length: bool = False):
        self._util = util
        self.max_length = max_length

        records = data_api.load_shuffle_split_dataset(
            util, dataset_path, dataset_builder)
        data_api.require_record_fields(
            records, ("prompt",), "PromptDataset",
            hint=" Expected JSONL objects with a unique `id` and a "
                 "text `prompt`.")
        self.ids = [x["id"] for x in records]
        util.tokenizer.padding_side = "left"
        enc = util.tokenizer(
            [x["prompt"] for x in records],
            truncation=True,
            max_length=max_length,
            padding="max_length" if pad_to_max_length else False,
            return_length=True,
            return_attention_mask=False)
        self.prompt_lengths = [int(l) for l in enc["length"]]
        self.prompts = enc["input_ids"]
        logger.info("Loaded %d prompts.", len(self.prompts))

    @property
    def util(self):
        return self._util

    def __len__(self):
        return len(self.prompts)

    def __getitem__(self, idx):
        return data_api.SequenceSample.from_default(
            ids=[self.ids[idx]],
            seqlens=[self.prompt_lengths[idx]],
            data=dict(packed_prompts=np.asarray(self.prompts[idx], dtype=np.int32)),
            metadata=dict(random_id=[uuid.uuid4()]),
        )


data_api.register_dataset("prompt", PromptDataset)
