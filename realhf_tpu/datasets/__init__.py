"""Dataset implementations; importing this module registers them.

Parity with reference ``realhf/impl/dataset/__init__.py``: registered
names are "prompt", "prompt_answer", "rw_pair", and "random_prompt"
(synthetic data for profile/mock mode); plus the agentic task
datasets "checker_task" and "tool_game" (docs/agentic.md).
"""

import realhf_tpu.datasets.prompt  # noqa: F401
import realhf_tpu.datasets.prompt_answer  # noqa: F401
import realhf_tpu.datasets.rw_paired  # noqa: F401
import realhf_tpu.datasets.random_prompt  # noqa: F401
import realhf_tpu.datasets.agentic  # noqa: F401
