"""Agentic task datasets: checker-task prompts and tool-game seeds.

Token-level synthetic datasets feeding the agentic envs
(``realhf_tpu/agentic/env.py``). Both are deterministic in
``(seed, dp_rank)`` -- the same experiment seed always yields the same
task set, sharded per DP rank -- and need no tokenizer or files.
Records may also come from a JSONL file whose objects carry
``prompt_tokens`` (a token-id list); malformed records fail load with
the offending record named (``api.data.require_record_fields``)."""

import json
from typing import List, Optional

import numpy as np

from realhf_tpu.api import data as data_api
from realhf_tpu.base import logging

logger = logging.getLogger("AgenticDataset")


def _load_token_records(util: data_api.DatasetUtility, path: str,
                        loader: str) -> List[np.ndarray]:
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    data_api.require_record_fields(
        records, ("prompt_tokens",), loader,
        hint=" Records must carry token-id lists, not text: agentic "
             "envs speak token ids.")
    for rec in records:
        toks = rec["prompt_tokens"]
        if not isinstance(toks, list) or not toks or not all(
                isinstance(t, int) and t >= 0 for t in toks):
            raise ValueError(
                f"{loader}: record {rec.get('id', '?')!r}: "
                f"prompt_tokens must be a non-empty list of "
                f"non-negative ints, got {toks!r}.")
    rng = np.random.default_rng(util.seed)
    idx = np.arange(len(records))
    rng.shuffle(idx)
    shard = np.array_split(idx, util.world_size)[util.dp_rank]
    return [np.asarray(records[i]["prompt_tokens"], np.int32)
            for i in shard]


class _AgenticPromptBase:
    """Map-style dataset of ``packed_prompts`` samples over raw token
    prompts (mirrors RandomPromptDataset's shape)."""

    def __init__(self, util: data_api.DatasetUtility,
                 prompts: List[np.ndarray]):
        self._util = util
        self.prompts = prompts

    @property
    def util(self):
        return self._util

    def __len__(self):
        return len(self.prompts)

    def __getitem__(self, idx):
        return data_api.SequenceSample.from_default(
            ids=[idx],
            seqlens=[len(self.prompts[idx])],
            data=dict(packed_prompts=self.prompts[idx]),
        )


class CheckerTaskDataset(_AgenticPromptBase):
    """Prompts for the verifiable-reward ``checker_task`` env: random
    payload tokens whose last one/two tokens define the checked answer
    (CheckerEnv derives the target from the prompt, so prompt == full
    task specification)."""

    def __init__(self, util: data_api.DatasetUtility,
                 n_prompts: int = 128, prompt_len_min: int = 4,
                 prompt_len_max: int = 8, vocab_size: int = 97,
                 dataset_path: Optional[str] = None):
        if dataset_path:
            prompts = _load_token_records(util, dataset_path,
                                          "CheckerTaskDataset")
        else:
            from realhf_tpu.agentic.env import PAYLOAD_BASE
            rng = np.random.default_rng(util.seed * 7919 + util.dp_rank)
            lo = min(prompt_len_min, prompt_len_max)
            lens = rng.integers(lo, prompt_len_max + 1, size=n_prompts)
            prompts = [rng.integers(PAYLOAD_BASE, vocab_size, size=l)
                       .astype(np.int32) for l in lens]
        super().__init__(util, prompts)
        logger.info("Loaded %d checker-task prompts.", len(prompts))


class ToolGameDataset(_AgenticPromptBase):
    """Seeds for the multi-turn ``tool_game`` env: short random
    prompts whose tokens seed the hidden target sequence (ToolGameEnv
    derives targets from prompt + seed, so distinct prompts are
    distinct games)."""

    def __init__(self, util: data_api.DatasetUtility,
                 n_prompts: int = 128, prompt_len: int = 4,
                 vocab_size: int = 97,
                 dataset_path: Optional[str] = None):
        if dataset_path:
            prompts = _load_token_records(util, dataset_path,
                                          "ToolGameDataset")
        else:
            from realhf_tpu.agentic.env import PAYLOAD_BASE
            rng = np.random.default_rng(util.seed * 6271 + util.dp_rank)
            prompts = [rng.integers(PAYLOAD_BASE, vocab_size,
                                    size=prompt_len).astype(np.int32)
                       for _ in range(n_prompts)]
        super().__init__(util, prompts)
        logger.info("Loaded %d tool-game seeds.", len(prompts))


data_api.register_dataset("checker_task", CheckerTaskDataset)
data_api.register_dataset("tool_game", ToolGameDataset)
