"""Prompt + answer dataset for SFT.

Parity with reference ``realhf/impl/dataset/prompt_answer_dataset.py``:
JSONL records with "id", "prompt", "answer". Items yield
``packed_input_ids`` (prompt+answer+eos) and a boolean ``prompt_mask``
(True over prompt tokens, excluded from the SFT loss).
"""

from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.api import data as data_api
from realhf_tpu.base import logging

logger = logging.getLogger("PromptAnswerDataset")


class PromptAnswerDataset:

    def __init__(self, util: data_api.DatasetUtility, max_length: int,
                 dataset_path: Optional[str] = None,
                 dataset_builder: Optional[Callable[[], List[Dict]]] = None,
                 pad_to_max_length: bool = False):
        self._util = util
        tokenizer = util.tokenizer

        records = data_api.load_shuffle_split_dataset(
            util, dataset_path, dataset_builder)
        data_api.require_record_fields(
            records, ("prompt", "answer"), "PromptAnswerDataset",
            hint=" Expected JSONL objects with `id`, text `prompt` "
                 "and text `answer`.")
        self.ids = [x["id"] for x in records]
        seqs = [x["prompt"] + x["answer"] + tokenizer.eos_token for x in records]
        self.tokens = tokenizer(
            seqs, truncation=True, max_length=max_length, return_length=True,
            return_attention_mask=False,
            padding="max_length" if pad_to_max_length else False)
        prompt_tokens = tokenizer(
            [x["prompt"] for x in records], truncation=True,
            max_length=max_length, return_length=True,
            return_attention_mask=False, padding=False)

        self.prompt_masks = []
        for plen, slen in zip(prompt_tokens["length"], self.tokens["length"]):
            plen, slen = int(plen), int(slen)
            assert slen >= plen, (slen, plen)
            self.prompt_masks.append(
                np.array([True] * plen + [False] * (slen - plen)))
        logger.info("Loaded %d prompt-answer sequences.", len(self.ids))

    @property
    def util(self):
        return self._util

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, idx):
        ids = np.asarray(self.tokens["input_ids"][idx], dtype=np.int32)
        mask = self.prompt_masks[idx]
        assert len(ids) == len(mask)
        return data_api.SequenceSample.from_default(
            ids=[self.ids[idx]],
            seqlens=[len(ids)],
            data=dict(packed_input_ids=ids, prompt_mask=mask),
        )


data_api.register_dataset("prompt_answer", PromptAnswerDataset)
