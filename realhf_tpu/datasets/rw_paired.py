"""Paired positive/negative answer dataset for reward modeling.

Parity with reference ``realhf/impl/dataset/rw_paired_dataset.py``:
JSONL records with "id", "prompt", "pos_answers", "neg_answers" (paired
one-to-one). Each item packs up to ``max_pairs_per_prompt`` interleaved
(pos, neg) full sequences into ``packed_input_ids`` plus the prompt
length (used to mask prompt tokens in the Bradley-Terry loss).
"""

import itertools
from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.api import data as data_api
from realhf_tpu.base import logging

logger = logging.getLogger("RewardPairedDataset")


class RewardModelingPairedDataset:

    def __init__(self, util: data_api.DatasetUtility, max_length: int,
                 max_pairs_per_prompt: int = 2,
                 dataset_path: Optional[str] = None,
                 dataset_builder: Optional[Callable[[], List[Dict]]] = None):
        self._util = util
        tokenizer = util.tokenizer
        self.max_pairs_per_prompt = max_pairs_per_prompt
        self.rng = np.random.RandomState(seed=util.seed)

        records = data_api.load_shuffle_split_dataset(
            util, dataset_path, dataset_builder)
        data_api.require_record_fields(
            records, ("prompt", "pos_answers", "neg_answers"),
            "RewardModelingPairedDataset",
            hint=" Expected JSONL objects with `id`, text `prompt`, "
                 "and paired `pos_answers`/`neg_answers` lists.")
        self.ids = [x["id"] for x in records]

        pos = [[x["prompt"] + c + tokenizer.eos_token for c in x["pos_answers"]]
               for x in records]
        neg = [[x["prompt"] + c + tokenizer.eos_token for c in x["neg_answers"]]
               for x in records]
        for a, b in zip(pos, neg):
            if len(a) != len(b):
                raise RuntimeError("pos_answers and neg_answers must be paired.")
            if not a:
                raise RuntimeError("pos_answers and neg_answers must be non-empty.")
        group_sizes = [len(x) for x in pos]

        self.prompt_lengths = [
            int(l) for l in tokenizer(
                [x["prompt"] for x in records], max_length=max_length,
                truncation=True, padding=False, return_length=True)["length"]]

        def _group(flat_tokens):
            grouped, off = [], 0
            for g in group_sizes:
                grouped.append(flat_tokens["input_ids"][off:off + g])
                off += g
            return grouped

        tok_kw = dict(max_length=max_length, truncation=True, padding=False,
                      return_length=True)
        self.pos_tokens = _group(tokenizer(
            list(itertools.chain.from_iterable(pos)), **tok_kw))
        self.neg_tokens = _group(tokenizer(
            list(itertools.chain.from_iterable(neg)), **tok_kw))
        logger.info("Loaded %d reward-modeling prompts.", len(self.ids))

    @property
    def util(self):
        return self._util

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, idx):
        n_pairs = len(self.pos_tokens[idx])
        group_size = min(self.max_pairs_per_prompt, n_pairs)
        chosen = self.rng.choice(n_pairs, group_size, replace=False)

        packed, input_lens = [], []
        for i in chosen:
            packed += list(self.pos_tokens[idx][i])
            packed += list(self.neg_tokens[idx][i])
            input_lens += [len(self.pos_tokens[idx][i]),
                           len(self.neg_tokens[idx][i])]

        return data_api.SequenceSample(
            keys=["packed_input_ids", "prompt_lens"],
            data=dict(
                packed_input_ids=np.asarray(packed, dtype=np.int32),
                prompt_lens=np.asarray([self.prompt_lengths[idx]], dtype=np.int32),
            ),
            dtypes=dict(packed_input_ids=np.int32, prompt_lens=np.int32),
            trailing_shapes=dict(packed_input_ids=(), prompt_lens=()),
            ids=[self.ids[idx]],
            seqlens=dict(packed_input_ids=[input_lens], prompt_lens=[[1]]),
        )


data_api.register_dataset("rw_pair", RewardModelingPairedDataset)
