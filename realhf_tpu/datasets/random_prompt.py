"""Synthetic prompt dataset for profile/mock mode.

The reference's profile experiment feeds MFCs synthetic data through
the full runtime (``experiments/benchmark/profile_exp.py:61`` +
``ModelInterface.mock``, model_api.py:619); this dataset is the
TPU-native data side of that: random token prompts with configurable
size distribution, no files or tokenizer involved.
"""

from typing import Optional

import numpy as np

from realhf_tpu.api import data as data_api


class RandomPromptDataset:

    def __init__(self, util: data_api.DatasetUtility, n_prompts: int = 256,
                 prompt_len_min: int = 32, prompt_len_max: int = 256,
                 vocab_size: int = 32000, max_length: Optional[int] = None):
        self._util = util
        rng = np.random.default_rng(util.seed + util.dp_rank)
        hi = min(prompt_len_max, max_length or prompt_len_max)
        lo = min(prompt_len_min, hi)
        self.lengths = rng.integers(lo, hi + 1,
                                    size=n_prompts).astype(int)
        # ids >= 2: 0/1 are conventionally pad/eos
        self.prompts = [rng.integers(2, vocab_size, size=l)
                        .astype(np.int32) for l in self.lengths]

    @property
    def util(self):
        return self._util

    def __len__(self):
        return len(self.prompts)

    def __getitem__(self, idx):
        return data_api.SequenceSample.from_default(
            ids=[idx],
            seqlens=[int(self.lengths[idx])],
            data=dict(packed_prompts=self.prompts[idx]),
        )


data_api.register_dataset("random_prompt", RandomPromptDataset)
