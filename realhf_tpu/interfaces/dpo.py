"""Direct Preference Optimization interface.

Parity with reference ``realhf/impl/model/interface/dpo_interface.py``
(DPOInterface:99) + ``utils/dpo_functional.py:7``: the ref model's
`inference` produces per-sequence answer logprob sums ("seqlogp"); the
train step maximizes log sigmoid(beta * (pi_logratio - ref_logratio))
over (pos, neg) pairs.
"""

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.interfaces import common
from realhf_tpu.models import transformer as T
from realhf_tpu.ops import functional as F

logger = logging.getLogger("DPOInterface")


def _answer_masks(sb: common.StreamBatch, seqlens: List[int],
                  prompt_lens_per_seq: List[int]) -> np.ndarray:
    """[S, L] mask of shifted positions covering answer tokens: for a
    sequence at (stream, off) with length l and prompt p, positions
    off+p-1 .. off+l-2 (predicting tokens p..l-1)."""
    s, l = sb.arrays["seg_ids"].shape
    mask = np.zeros((s, l), np.float32)
    for i, (ln, pl) in enumerate(zip(seqlens, prompt_lens_per_seq)):
        row, off = sb.info.stream[i], sb.info.offset[i]
        mask[row, off + pl - 1: off + ln - 1] = 1.0
    return mask


def _make_loss_fn(cfg, n_seqs: int, beta: float, attention_fn=None,
                  pipeline=None, moe_constraint=None):

    def loss_fn(params, mb):
        h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                         mb["seg_ids"], attention_fn,
                                         pipeline, moe_constraint)
        lp = F.shifted_logprobs_from_hidden(
            cfg, params, h, mb["input_ids"], mb["seg_ids"])
        masked = (lp * mb["answer_mask"]).reshape(-1)
        sums = jax.ops.segment_sum(masked, mb["seq_index"].reshape(-1),
                                   num_segments=n_seqs + 1)[:n_seqs]
        pi_pos = sums[mb["pos_seq"]]
        pi_neg = sums[mb["neg_seq"]]
        ref_pos = mb["ref_pos"]
        ref_neg = mb["ref_neg"]
        valid = mb["pair_valid"]
        denom = jnp.maximum(valid.sum(), 1)
        logits = beta * ((pi_pos - pi_neg) - (ref_pos - ref_neg))
        loss = (-jax.nn.log_sigmoid(logits) * valid).sum() / denom
        pos_score = (beta * (pi_pos - ref_pos) * valid).sum() / denom
        neg_score = (beta * (pi_neg - ref_neg) * valid).sum() / denom
        kl = (-(pi_pos - ref_pos + pi_neg - ref_neg) * valid).sum() / denom
        return loss + sum(aux.values()), {
            "loss": loss, "pos_score": pos_score,
            "neg_score": neg_score, "kl": kl, **aux}

    return loss_fn


@dataclasses.dataclass
class DPOInterface(model_api.ModelInterface):
    beta: float = 0.1
    enable_save: bool = True

    def _prompt_lens_per_seq(self, input_: SequenceSample) -> List[int]:
        out = []
        for lens, pl in zip(input_.seqlens["packed_input_ids"],
                            input_.data["prompt_lens"].reshape(-1).tolist()):
            out.extend([int(pl)] * len(lens))
        return out

    def _seq_logp(self, model, input_: SequenceSample) -> np.ndarray:
        """Per-sequence answer logprob sums under the model."""
        seqlens = common.flat_seqlens(input_)
        sb = common.build_stream_batch(
            seqlens,
            token_keys=dict(input_ids=input_.data["packed_input_ids"]),
            n_streams=model.engine.n_streams)
        lp = np.asarray(model.engine.forward_logprobs(
            sb.arrays["input_ids"], sb.arrays["seg_ids"]))
        mask = _answer_masks(sb, seqlens, self._prompt_lens_per_seq(input_))
        sums = np.zeros(len(seqlens), np.float64)
        masked = lp * mask
        for i, ln in enumerate(seqlens):
            row, off = sb.info.stream[i], sb.info.offset[i]
            sums[i] = masked[row, off:off + ln].sum()
        return sums.astype(np.float32)

    def inference(self, model: model_api.Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        sums = self._seq_logp(model, input_)
        n_per_elem = [len(l) for l in input_.seqlens["packed_input_ids"]]
        return SequenceSample(
            keys=["seqlogp"],
            trailing_shapes=dict(seqlogp=()),
            dtypes=dict(seqlogp=np.float32),
            ids=input_.ids,
            seqlens=dict(seqlogp=[[1] * n for n in n_per_elem]),
            data=dict(seqlogp=sums),
        )

    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        mbs = common.split_minibatches(input_, n_mbs or 1)
        batches, weights, n_seqs_max = [], [], 0
        for mb in mbs:
            seqlens = common.flat_seqlens(mb)
            n_seqs_max = max(n_seqs_max, len(seqlens))
        for mb in mbs:
            seqlens = common.flat_seqlens(mb)
            sb = common.build_stream_batch(
                seqlens,
                token_keys=dict(input_ids=mb.data["packed_input_ids"]),
                n_streams=engine.n_streams)
            sb.arrays["answer_mask"] = _answer_masks(
                sb, seqlens, self._prompt_lens_per_seq(mb))
            # map pads to index n_seqs_max (one shared dustbin segment)
            seg = sb.arrays["seg_ids"]
            sb.arrays["seq_index"] = np.where(
                seg > 0, seg - 1, n_seqs_max).astype(np.int32)
            ref = mb.data["seqlogp"].reshape(-1)
            pos_seq, neg_seq, rp, rn, valid = [], [], [], [], []
            si = 0
            for lens in mb.seqlens["packed_input_ids"]:
                for p in range(len(lens) // 2):
                    pos_seq.append(si + 2 * p)
                    neg_seq.append(si + 2 * p + 1)
                    rp.append(ref[si + 2 * p])
                    rn.append(ref[si + 2 * p + 1])
                    valid.append(1.0)
                si += len(lens)
            sb.arrays["pos_seq"] = np.asarray(pos_seq, np.int32)
            sb.arrays["neg_seq"] = np.asarray(neg_seq, np.int32)
            sb.arrays["ref_pos"] = np.asarray(rp, np.float32)
            sb.arrays["ref_neg"] = np.asarray(rn, np.float32)
            sb.arrays["pair_valid"] = np.asarray(valid, np.float32)
            batches.append(sb)
            weights.append(len(valid))
        batches = common.pad_stream_batches(batches)
        npair = max(b.arrays["pos_seq"].shape[0] for b in batches)
        for b in batches:
            for k in ("pos_seq", "neg_seq", "ref_pos", "ref_neg",
                      "pair_valid"):
                v = b.arrays[k]
                b.arrays[k] = np.pad(v, (0, npair - v.shape[0]))
        stats = engine.train_batch(
            [b.arrays for b in batches],
            _make_loss_fn(model.config, n_seqs_max, self.beta,
                          engine.attention_fn,
                          engine.pipeline_ctx, engine.moe_constraint),
            loss_weights=weights, loss_fn_key=("dpo", n_seqs_max, self.beta))
        model.inc_version()
        return stats

    def save(self, model: model_api.Model, save_dir: str,
             host_params=None, writer: bool = True):
        if not self.enable_save:
            return
        common.save_checkpoint(model, save_dir, host_params,
                               writer=writer)


model_api.register_interface("dpo", DPOInterface)
