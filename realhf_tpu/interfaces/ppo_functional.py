"""PPO math: KL controllers, losses, rewards, value normalization.

Parity with reference ``realhf/impl/model/utils/ppo_functional.py``
(actor_loss_fn:49, critic_loss_fn:135, compute/get_packed_rewards:206/
291, KL controllers:21-46) and ``modules/rms.py`` (running mean-std
for value/return normalization). Losses are jittable over [S, L]
stream arrays; reward/GAE prep runs host-side on flat packed arrays.
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------------------
# KL controllers (host-side state)
# ----------------------------------------------------------------------
class KLController:
    value: float

    def update(self, current: float, n_steps: int):
        raise NotImplementedError()


class FixedKLController(KLController):

    def __init__(self, kl_coef: float):
        self.value = kl_coef

    def update(self, current, n_steps):
        pass


class AdaptiveKLController(KLController):
    """arXiv 1909.08593 adaptive controller (reference :21)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: float):
        self.value = init_kl_coef
        self.target = target
        self.horizon = horizon

    def update(self, current, n_steps):
        proportional_error = float(np.clip(current / self.target - 1,
                                           -0.2, 0.2))
        self.value = self.value * (1 + proportional_error * n_steps /
                                   self.horizon)


# ----------------------------------------------------------------------
# Losses (jittable, [.,.] shapes with a boolean loss mask)
# ----------------------------------------------------------------------
def actor_loss_fn(logprobs: jnp.ndarray, old_logprobs: jnp.ndarray,
                  advantages: jnp.ndarray, eps_clip: float,
                  loss_mask: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped PPO surrogate (reference actor_loss_fn:49)."""
    m = loss_mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    ratio = jnp.where(loss_mask, jnp.exp(logprobs - old_logprobs), 0.0)
    clipped = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + eps_clip)
    pg1 = -advantages * ratio
    pg2 = -advantages * clipped
    loss = (jnp.where(loss_mask, jnp.maximum(pg1, pg2), 0.0)).sum() / denom
    clip_mask = (jax.lax.stop_gradient(pg1) < jax.lax.stop_gradient(pg2))
    stats = {
        "importance_weight": (jax.lax.stop_gradient(ratio) * m).sum() / denom,
        "clip_ratio": (clip_mask & loss_mask).sum() / denom,
        "approx_kl": (jax.lax.stop_gradient(logprobs - old_logprobs)
                      * m).sum() / denom,
    }
    return loss, stats


def critic_loss_fn(value: jnp.ndarray, old_value: jnp.ndarray,
                   target_value: jnp.ndarray, value_eps_clip: float,
                   loss_mask: jnp.ndarray, loss_fn_type: str = "mse"
                   ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Value loss with clipping (reference critic_loss_fn:135)."""
    if loss_fn_type == "mse":
        f = lambda x, y: 0.5 * (x - y) ** 2
    elif loss_fn_type == "huber":
        delta = 10.0
        f = lambda x, y: jnp.where(
            jnp.abs(x - y) < delta, 0.5 * (x - y) ** 2,
            delta * (jnp.abs(x - y) - 0.5 * delta))
    else:
        raise NotImplementedError(loss_fn_type)
    m = loss_mask.astype(jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    orig = f(value, target_value)
    value_clipped = old_value + jnp.clip(value - old_value, -value_eps_clip,
                                         value_eps_clip)
    clip = f(value_clipped, target_value)
    loss = (jnp.where(loss_mask, jnp.maximum(orig, clip), 0.0)).sum() / denom
    clip_mask = jax.lax.stop_gradient(clip) > jax.lax.stop_gradient(orig)
    return loss, {"value_clip_ratio": (clip_mask & loss_mask).sum() / denom}


# ----------------------------------------------------------------------
# Rewards over flat packed arrays (host-side numpy; O(T) trivial work)
# ----------------------------------------------------------------------
def get_packed_rewards(
    kl_ctl: float,
    clip_reward_value: float,
    log_probs: np.ndarray,      # flat, per-seq length l-1
    ref_log_probs: np.ndarray,
    reward_score: np.ndarray,   # [n_seqs]
    short1cu_seqlens: np.ndarray,  # [n_seqs+1] boundaries of the l-1 arrays
    seq_no_eos_mask: np.ndarray,   # [n_seqs] bool
) -> Tuple[np.ndarray, np.ndarray]:
    """KL penalty + terminal score at each sequence's last reward slot
    (reference get_packed_rewards:291)."""
    kl_rewards = -kl_ctl * (log_probs - ref_log_probs)
    tot = kl_rewards.copy()
    score = np.clip(reward_score, -clip_reward_value, clip_reward_value)
    ends = short1cu_seqlens[1:] - 1
    tot[ends] += np.where(seq_no_eos_mask, 0.0, score)
    return kl_rewards, tot


def get_packed_dense_rewards(
    kl_ctl: float,
    clip_reward_value: float,
    log_probs: np.ndarray,       # flat, per-seq length l-1
    ref_log_probs: np.ndarray,
    dense_rewards: np.ndarray,   # flat l-1: reward at turn boundaries
) -> Tuple[np.ndarray, np.ndarray]:
    """Turn-level variant of :func:`get_packed_rewards` for agentic
    trajectories (docs/agentic.md): instead of one terminal score per
    sequence, ``dense_rewards`` already places each turn's reward at
    that turn's last action token's prediction slot
    (``agentic/trajectory.py``), so the total reward is simply KL
    penalty + clipped dense rewards. Environment rewards are granted
    by the checker/tool regardless of how the sequence ended, so no
    ``seq_no_eos_mask`` gating applies (truncation only zeroes the
    bootstrap value, in GAE)."""
    kl_rewards = -kl_ctl * (log_probs - ref_log_probs)
    tot = kl_rewards + np.clip(dense_rewards, -clip_reward_value,
                               clip_reward_value)
    return kl_rewards, tot


# ----------------------------------------------------------------------
# Running mean-std (value normalization, reference modules/rms.py)
# ----------------------------------------------------------------------
class ExponentialRunningMeanStd:

    def __init__(self, beta: float = 0.999, epsilon: float = 1e-5,
                 high_precision: bool = True):
        self.beta = beta
        self.eps = epsilon
        self._mean = 0.0
        self._mean_sq = 0.0
        self._debias = 0.0

    def update(self, x: np.ndarray, mask: Optional[np.ndarray] = None):
        x = np.asarray(x, np.float64)
        if mask is not None:
            mask = np.asarray(mask, np.float64)
            factor = max(mask.sum(), 1.0)
            mean = (x * mask).sum() / factor
            mean_sq = (x ** 2 * mask).sum() / factor
        else:
            mean = x.mean()
            mean_sq = (x ** 2).mean()
        self._mean = self.beta * self._mean + (1 - self.beta) * mean
        self._mean_sq = self.beta * self._mean_sq + (1 - self.beta) * mean_sq
        self._debias = self.beta * self._debias + (1 - self.beta)

    def mean_std(self) -> Tuple[float, float]:
        if self._debias == 0:
            return 0.0, 1.0
        mean = self._mean / self._debias
        var = max(self._mean_sq / self._debias - mean ** 2, 0.0)
        return mean, float(np.sqrt(var + self.eps))

    def normalize(self, x: np.ndarray) -> np.ndarray:
        mean, std = self.mean_std()
        return ((np.asarray(x, np.float64) - mean) / std).astype(np.float32)

    def denormalize(self, x: np.ndarray) -> np.ndarray:
        mean, std = self.mean_std()
        return (np.asarray(x, np.float64) * std + mean).astype(np.float32)


class MovingAverageRunningMeanStd:

    def __init__(self, epsilon: float = 1e-5):
        self.eps = epsilon
        self._sum = 0.0
        self._sum_sq = 0.0
        self._count = 0.0

    def update(self, x: np.ndarray, mask: Optional[np.ndarray] = None):
        x = np.asarray(x, np.float64)
        if mask is not None:
            mask = np.asarray(mask, np.float64)
            self._sum += (x * mask).sum()
            self._sum_sq += (x ** 2 * mask).sum()
            self._count += mask.sum()
        else:
            self._sum += x.sum()
            self._sum_sq += (x ** 2).sum()
            self._count += x.size

    def mean_std(self) -> Tuple[float, float]:
        if self._count == 0:
            return 0.0, 1.0
        mean = self._sum / self._count
        var = max(self._sum_sq / self._count - mean ** 2, 0.0)
        return mean, float(np.sqrt(var + self.eps))

    def normalize(self, x):
        mean, std = self.mean_std()
        return ((np.asarray(x, np.float64) - mean) / std).astype(np.float32)

    def denormalize(self, x):
        mean, std = self.mean_std()
        return (np.asarray(x, np.float64) * std + mean).astype(np.float32)
