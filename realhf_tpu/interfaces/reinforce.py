"""REINFORCE / ReMax: critic-free policy gradient with a greedy
baseline.

Parity with reference ``examples/new_algorithms/reinforce/
reinforce_interface.py``: each prompt samples one response AND decodes
one greedy response; the greedy response's reward is the variance
baseline (ReMax), so the per-prompt advantage is
``r_sampled - r_greedy`` broadcast over the sampled response tokens,
and the loss is plain REINFORCE ``-adv * logpi`` (no clipping, no
critic, no GAE). Both responses live as two nested sequences inside
each batch element (sampled first, greedy second), so ids are
preserved and the runtime's data merge works unchanged -- the same
grouping device as GRPO.
"""

import dataclasses
from typing import Dict, Optional

import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.interfaces import common
from realhf_tpu.interfaces.ppo import PPOActorInterface, _shifted_loss_mask

logger = logging.getLogger("ReinforceInterface")


@dataclasses.dataclass
class ReinforceInterface(PPOActorInterface):
    """Reuses the PPO actor's generate/inference plumbing; overrides
    sampling (paired sampled+greedy decode) and the loss."""
    kl_coef: float = 0.0  # optional k3 penalty vs the reference policy

    def __post_init__(self):
        super().__post_init__()
        if self.gconfig.greedy:
            raise ValueError(
                "ReinforceInterface needs a SAMPLED rollout; the greedy "
                "baseline decode is issued internally.")
        if not self.gconfig.force_no_logits_mask:
            # the greedy baseline has no logits mask, so the sampled
            # half's mask cannot ride the interleaved layout; without
            # replay, warped sampling would make recomputed logprobs
            # inconsistent with the rollout distribution
            raise ValueError(
                "ReinforceInterface does not replay the sampling "
                "logits mask; set force_no_logits_mask=True (and "
                "disable top-k/top-p if exact logprob consistency "
                "matters).")

    # ------------------------------------------------------------------
    def generate(self, model: model_api.Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        import copy

        sampled = super().generate(model, input_, n_mbs=n_mbs)
        # shallow-copy twin with a greedy gconfig (dataclasses.replace
        # would re-run __post_init__, which rejects greedy configs)
        greedy_itf = copy.copy(self)
        greedy_itf.gconfig = dataclasses.replace(
            self.gconfig, greedy=True, force_no_logits_mask=True)
        greedy = PPOActorInterface.generate(greedy_itf, model, input_,
                                            n_mbs=n_mbs)

        # interleave: element i holds [sampled_i, greedy_i]
        keys = [k for k in sampled.keys if k in greedy.keys]
        s_parts = sampled.select(keys).unpack()
        g_parts = greedy.select(keys).unpack()

        def nest(key):
            return [s.seqlens[key][0] + g.seqlens[key][0]
                    for s, g in zip(s_parts, g_parts)]

        data = {}
        for k in keys:
            pieces = []
            for s, g in zip(s_parts, g_parts):
                pieces.append(np.concatenate(
                    [np.atleast_1d(s.data[k]), np.atleast_1d(g.data[k])]))
            data[k] = np.concatenate(pieces)
        with SequenceSample.disable_validation():
            return SequenceSample(
                keys=keys,
                trailing_shapes={k: sampled.trailing_shapes[k]
                                 for k in keys},
                dtypes={k: sampled.dtypes[k] for k in keys},
                ids=list(input_.ids),
                seqlens={k: nest(k) for k in keys},
                data=data,
                metadata={})

    # ------------------------------------------------------------------
    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        seqlens = common.flat_seqlens(input_)
        n_seqs = len(seqlens)
        assert n_seqs % 2 == 0, "sampled+greedy pairs expected"

        prompt_mask = np.asarray(input_.data["prompt_mask"], bool)
        rewards = np.asarray(input_.data["rewards"], np.float32)
        has_ref = "packed_ref_logprobs" in input_.keys and self.kl_coef > 0

        # ReMax advantage: r_sampled - r_greedy per pair; greedy
        # sequences get advantage 0 (they only serve as the baseline
        # and contribute no gradient).
        pairs = rewards.reshape(-1, 2)
        adv_seq = np.zeros_like(rewards)
        adv_seq[0::2] = np.clip(pairs[:, 0] - pairs[:, 1],
                                -self.max_reward_clip,
                                self.max_reward_clip)

        loss_mask = _shifted_loss_mask(prompt_mask, seqlens)
        lens_m1 = np.asarray(seqlens) - 1
        advantages = np.repeat(adv_seq, lens_m1).astype(np.float32)
        # zero out greedy-sequence tokens entirely
        keep = np.repeat(np.tile([True, False], n_seqs // 2), lens_m1)
        loss_mask = loss_mask & keep
        advantages = advantages * loss_mask

        global_stats = dict(
            task_reward=float(pairs[:, 0].mean()),
            greedy_reward=float(pairs[:, 1].mean()),
            advantage=float(adv_seq[0::2].mean()),
            n_seqs=n_seqs)

        nested = input_.seqlens["packed_input_ids"]
        nested_m1 = [[l - 1 for l in lens] for lens in nested]
        data = dict(
            packed_input_ids=input_.data["packed_input_ids"],
            advantages=advantages,
            ppo_loss_mask=loss_mask)
        keys = list(data)
        if has_ref:
            data["ref_logp"] = np.asarray(
                input_.data["packed_ref_logprobs"], np.float32)
            keys.append("ref_logp")
        with SequenceSample.disable_validation():
            sample = SequenceSample(
                keys=keys,
                trailing_shapes={k: () for k in keys},
                dtypes=dict(packed_input_ids=np.int32,
                            advantages=np.float32,
                            ppo_loss_mask=np.bool_,
                            **({"ref_logp": np.float32} if has_ref
                               else {})),
                ids=list(input_.ids),
                seqlens=dict(
                    packed_input_ids=nested,
                    advantages=nested_m1,
                    ppo_loss_mask=nested_m1,
                    **({"ref_logp": nested_m1} if has_ref else {})),
                data=data,
                metadata={})
        mbs = common.split_minibatches(sample, self.n_minibatches)

        cfg = model.config
        temperature = self.gconfig.temperature
        kl_coef = self.kl_coef
        attention_fn = engine.attention_fn
        pipeline = engine.pipeline_ctx
        moe_constraint = engine.moe_constraint

        def loss_fn(params, mb):
            import jax.numpy as jnp

            from realhf_tpu.ops import functional as F
            h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                             mb["seg_ids"], attention_fn,
                                             pipeline, moe_constraint)
            lp = F.shifted_logprobs_from_hidden(
                cfg, params, h, mb["input_ids"], mb["seg_ids"],
                temperature=temperature)
            m = mb["loss_mask"]
            denom = jnp.maximum(m.sum(), 1.0)
            pg = -(mb["advantages"] * lp * m).sum() / denom
            total = pg + sum(aux.values())
            stats = dict(reinforce_loss=pg, **aux)
            if has_ref:
                diff = mb["ref_logp"] - lp
                kl = (jnp.where(m > 0, jnp.exp(diff) - diff - 1.0,
                                0.0)).sum() / denom
                total = total + kl_coef * kl
                stats["ref_kl"] = kl
            return total, stats

        def build_sb(minibatch):
            mb_lens = common.flat_seqlens(minibatch)
            shifted = dict(
                advantages=minibatch.data["advantages"],
                loss_mask=minibatch.data["ppo_loss_mask"]
                .astype(np.float32))
            if has_ref:
                shifted["ref_logp"] = minibatch.data["ref_logp"]
            return common.build_stream_batch(
                mb_lens,
                token_keys=dict(
                    input_ids=minibatch.data["packed_input_ids"]),
                shifted_keys=shifted,
                n_streams=engine.n_streams)

        all_stats = [
            common.run_train_microbatched(
                engine, minibatch, build_sb, loss_fn,
                ("reinforce", temperature, kl_coef, has_ref), n_mbs)
            for minibatch in mbs
        ]
        model.inc_version()
        agg = {k: float(np.mean([s[k] for s in all_stats]))
               for k in all_stats[0]}
        agg.update(global_stats)
        return agg


model_api.register_interface("reinforce", ReinforceInterface)
