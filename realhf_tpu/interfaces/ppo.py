"""PPO actor and critic interfaces.

Parity with reference ``realhf/impl/model/interface/ppo_interface.py``
(PPOActorInterface:110, PPOCriticInterface:639): the actor's three
handlers (generate / inference / train_step) and the critic's two
(inference / train_step), including KL-penalized rewards, GAE,
advantage/value normalization, dual-clip PPO losses, adaptive KL
control, logits-mask replay, and early stopping.
"""

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.base.datapack import flat2d
from realhf_tpu.engine import packing
from realhf_tpu.interfaces import common, ppo_functional
from realhf_tpu.models import transformer as T
from realhf_tpu.ops import functional as F
from realhf_tpu.ops.gae import gae_packed_numpy
from realhf_tpu.ops.sampling import GenerationHyperparameters

logger = logging.getLogger("PPOInterface")


def _base_key() -> jax.Array:
    """Deterministic PRNG root: the EXPERIMENT seed when set, else 0.
    (Python hash() is process-salted and must not feed SPMD RNG; the
    per-worker ambient seed must not either -- every member of a
    worker group needs identical sampling keys.)"""
    from realhf_tpu.base import seeding
    try:
        seed = seeding.get_shared_seed()
    except RuntimeError:
        seed = 0
    return jax.random.PRNGKey(seed % (2 ** 31))


def _shifted_loss_mask(prompt_mask: np.ndarray,
                       seqlens: List[int]) -> np.ndarray:
    """Flat l-1 mask per sequence: True where the *predicted* token is
    a non-prompt token (reference ppo_interface.py:330-344)."""
    out, off = [], 0
    for l in seqlens:
        pm = prompt_mask[off:off + l]
        out.append(~pm[1:])
        off += l
    return np.concatenate(out)


def _make_rms(norm_type: str, beta: float, eps: float):
    if norm_type == "exp":
        return ppo_functional.ExponentialRunningMeanStd(beta=beta,
                                                        epsilon=eps)
    if norm_type == "ma":
        return ppo_functional.MovingAverageRunningMeanStd(epsilon=eps)
    raise NotImplementedError(norm_type)


@dataclasses.dataclass
class PPOActorInterface(model_api.ModelInterface):
    n_minibatches: int = 4
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters)
    kl_ctl: float = 0.1
    discount: float = 1.0
    gae_lambda: float = 1.0
    eps_clip: float = 0.2
    max_reward_clip: float = 20.0
    early_stop_kl: Optional[float] = None
    early_stop_imp_ratio: Optional[float] = None
    adv_norm: bool = True
    use_adaptive_kl_ctl: bool = False
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    value_norm: bool = False
    value_norm_type: str = "exp"
    value_norm_beta: float = 0.99995
    value_norm_eps: float = 1e-5
    enable_save: bool = True
    # -- async / off-policy consumption (docs/distributed.md "Async
    # RLHF") ----------------------------------------------------------
    #: drop sequences whose generation weight version lags the
    #: trainer's current version by more than this (the training-side
    #: mirror of ServingSpec.max_staleness); None keeps everything
    max_staleness: Optional[int] = None
    #: truncated importance-sampling bound for STALE sequences: each
    #: stale token's advantage is scaled by
    #: clip(pi_current/pi_behavior, 1/c, c) with the ratio
    #: stop-gradiented (decoupled-PPO style -- the ordinary PPO ratio
    #: still does the proximal clipping on top). None disables the
    #: correction; fresh (staleness 0) sequences are never touched.
    staleness_is_clip: Optional[float] = 2.0
    # -- agentic / multi-turn credit assignment (docs/agentic.md) ------
    #: place reward at each turn's last action token (the
    #: ``dense_rewards`` key packed by agentic trajectories) instead
    #: of at end-of-sequence; GAE then propagates credit across the
    #: masked observation gaps. Default False = existing
    #: end-of-sequence behavior, also used when the batch carries no
    #: ``dense_rewards``.
    turn_level_credit: bool = False

    def __post_init__(self):
        if isinstance(self.gconfig, dict):
            self.gconfig = GenerationHyperparameters(**self.gconfig)
        if self.use_adaptive_kl_ctl:
            self.kl_adapter = ppo_functional.AdaptiveKLController(
                self.kl_ctl, self.adaptive_kl_target, self.adaptive_kl_horizon)
        else:
            self.kl_adapter = ppo_functional.FixedKLController(self.kl_ctl)
        if self.value_norm:
            self.rms = _make_rms(self.value_norm_type, self.value_norm_beta,
                                 self.value_norm_eps)
        self._gen_calls = 0

    # ------------------------------------------------------------------
    def generate(self, model: model_api.Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        engine = model.engine
        tok = model.tokenizer
        prompt_lens = flat2d(input_.seqlens["packed_prompts"])
        flat = input_.data["packed_prompts"]
        prompts, off = [], 0
        for l in prompt_lens:
            prompts.append(np.asarray(flat[off:off + l]))
            off += l

        ids, seg, pos = packing.left_padded_prompts(
            prompts, pad_id=tok.pad_token_id)
        self._gen_calls += 1
        key = jax.random.fold_in(_base_key(), self._gen_calls)
        out = engine.generate(ids, seg, pos, key, self.gconfig,
                              eos_token_id=tok.eos_token_id,
                              pad_token_id=tok.pad_token_id)
        out = out.to_host()  # one bundled D2H round-trip for all fields
        gen_tokens = np.asarray(out.tokens)
        gen_lp = np.asarray(out.logprobs)
        gen_lens = np.asarray(out.lengths)
        no_eos = np.asarray(out.no_eos_mask)
        mask = None
        if out.logits_mask is not None:
            mask = np.asarray(out.logits_mask)  # [B, T, V], True=allowed

        seqlens, in_ids, logprobs, prompt_mask, logits_masks = [], [], [], [], []
        vocab = model.config.vocab_size
        for i, p in enumerate(prompts):
            g = int(gen_lens[i])
            l = len(p) + g
            seqlens.append(l)
            in_ids.append(np.concatenate([p, gen_tokens[i, :g]]))
            lp = np.zeros(l - 1, np.float32)
            lp[len(p) - 1:] = gen_lp[i, :g]
            logprobs.append(lp)
            prompt_mask.append(np.concatenate(
                [np.ones(len(p), bool), np.zeros(g, bool)]))
            if mask is not None:
                # True = masked out (reference convention, genstep:131)
                m = np.zeros((l, vocab), bool)
                m[len(p) - 1:len(p) - 1 + g] = ~mask[i, :g]
                logits_masks.append(m)

        data = dict(
            seq_no_eos_mask=no_eos,
            packed_input_ids=np.concatenate(in_ids).astype(np.int32),
            packed_logprobs=np.concatenate(logprobs).astype(np.float32),
            prompt_mask=np.concatenate(prompt_mask),
        )
        if mask is not None and not self.gconfig.force_no_logits_mask:
            data["packed_logits_mask"] = np.concatenate(logits_masks)
        return SequenceSample.from_default(
            ids=input_.ids, seqlens=seqlens, data=data)

    # ------------------------------------------------------------------
    def inference(self, model: model_api.Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        """Recompute logprobs under this model (used for ref_inf and
        actor_inf MFCs; reference ppo_interface.py:255). ``n_mbs``
        chunks the batch so a ref_inf that does not fit HBM at once
        still runs (reference microbatch contract)."""
        has_mask = ("packed_logits_mask" in input_.keys and
                    input_.data.get("packed_logits_mask") is not None)
        pieces = []
        # split() is contiguous and order-preserving: chunk outputs
        # concatenate back into the input order.
        for chunk in common.split_minibatches(input_, n_mbs or 1):
            seqlens = common.flat_seqlens(chunk)
            sb = common.build_stream_batch(
                seqlens,
                token_keys=dict(input_ids=chunk.data["packed_input_ids"]),
                n_streams=model.engine.n_streams)
            lmask = None
            if has_mask:
                # stored True=masked-out; engine wants True=allowed
                allowed = ~chunk.data["packed_logits_mask"]
                lmask = packing.pack_tokens(sb.info, allowed, fill=True)
            lp = np.asarray(model.engine.forward_logprobs(
                sb.arrays["input_ids"], sb.arrays["seg_ids"],
                temperature=self.gconfig.temperature, logits_mask=lmask))
            pieces.append(packing.unpack_tokens(
                sb.info, lp, seqlens=[l - 1 for l in seqlens]))
        flat_lp = np.concatenate(pieces)
        # Preserve per-element nesting (GRPO groups several sequences
        # inside one batch element).
        nested_m1 = [[l - 1 for l in lens]
                     for lens in input_.seqlens["packed_input_ids"]]
        with SequenceSample.disable_validation():
            return SequenceSample(
                keys=["packed_ref_logprobs"],
                trailing_shapes=dict(packed_ref_logprobs=()),
                dtypes=dict(packed_ref_logprobs=np.float32),
                ids=list(input_.ids),
                seqlens=dict(packed_ref_logprobs=nested_m1),
                data=dict(packed_ref_logprobs=flat_lp.astype(np.float32)),
                metadata={})

    # ------------------------------------------------------------------
    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        seqlens = common.flat_seqlens(input_)
        n_seqs = len(seqlens)
        cu = np.concatenate([[0], np.cumsum(seqlens)]).astype(np.int64)
        short1 = cu - np.arange(n_seqs + 1)

        old_logp = np.asarray(input_.data["packed_logprobs"], np.float32)
        ref_logp = np.asarray(input_.data["packed_ref_logprobs"], np.float32)
        prompt_mask = np.asarray(input_.data["prompt_mask"], bool)
        reward_score = np.asarray(input_.data["rewards"], np.float32)
        values = np.asarray(input_.data["values"], np.float32).copy()
        seq_no_eos = np.asarray(input_.data["seq_no_eos_mask"], bool)

        if self.value_norm:
            denorm_values = self.rms.denormalize(values)
        else:
            denorm_values = values.copy()
        # zero the value at EOS of terminated sequences (reference :321)
        ends = cu[1:] - 1
        denorm_values[ends] = np.where(seq_no_eos, denorm_values[ends], 0.0)

        loss_mask = _shifted_loss_mask(prompt_mask, seqlens)

        # -- staleness accounting (docs/distributed.md "Async RLHF"):
        # async rollouts stamp each sample's generation weight_version
        # into metadata; staleness = trainer version - that stamp.
        # Over-stale sequences drop out of the loss entirely; the rest
        # get the clipped-IS correction inside the loss fn below.
        versions = input_.metadata.get("weight_version")
        cur_version = model.version.global_step
        seq_staleness = np.zeros(n_seqs, np.int64)
        if versions:
            seq_staleness = np.array(
                [max(0, cur_version - int(v)) for v in versions],
                np.int64)
        n_dropped = 0
        if versions and self.max_staleness is not None:
            drop = seq_staleness > self.max_staleness
            if drop.any():
                off = 0
                for i, l in enumerate(seqlens):
                    if drop[i]:
                        loss_mask[off:off + l - 1] = False
                    off += l - 1
                n_dropped = int(drop.sum())

        old_logp = old_logp * loss_mask
        ref_logp = ref_logp * loss_mask

        dense = None
        if self.turn_level_credit and "dense_rewards" in input_.keys \
                and input_.data.get("dense_rewards") is not None:
            dense = np.asarray(input_.data["dense_rewards"],
                               np.float32)
        if dense is not None:
            kl_rewards, rewards = \
                ppo_functional.get_packed_dense_rewards(
                    kl_ctl=self.kl_adapter.value,
                    clip_reward_value=self.max_reward_clip,
                    log_probs=old_logp, ref_log_probs=ref_logp,
                    dense_rewards=dense)
        else:
            kl_rewards, rewards = ppo_functional.get_packed_rewards(
                kl_ctl=self.kl_adapter.value,
                clip_reward_value=self.max_reward_clip,
                log_probs=old_logp, ref_log_probs=ref_logp,
                reward_score=reward_score, short1cu_seqlens=short1,
                seq_no_eos_mask=seq_no_eos)
        advantages, returns = gae_packed_numpy(
            rewards, denorm_values, short1, seq_no_eos.astype(np.float32),
            gamma=self.discount, lam=self.gae_lambda)

        if self.value_norm:
            self.rms.update(returns, mask=loss_mask)
        if self.adv_norm:
            m = loss_mask.astype(np.float64)
            denom = max(m.sum(), 1.0)  # every seq dropped as stale
            mean = (advantages * m).sum() / denom
            var = ((advantages - mean) ** 2 * m).sum() / denom
            advantages = ((advantages - mean) /
                          np.sqrt(var + 1e-5)).astype(np.float32) * loss_mask

        n_tokens = int(loss_mask.sum())
        mean_ref_kl = float((kl_rewards * loss_mask).sum())
        self.kl_adapter.update(mean_ref_kl / max(n_tokens, 1),
                               n_steps=n_seqs)

        global_stats = dict(
            task_reward=float(reward_score.mean()),
            kl_reward=mean_ref_kl / max(n_tokens, 1),
            advantage=float(advantages.sum() / max(n_tokens, 1)),
            avg_seq_len=float(np.mean(seqlens)),
            avg_prompt_len=float(prompt_mask.sum() / n_seqs),
            n_tokens=n_tokens,
            n_seqs=n_seqs,
        )
        if versions:
            global_stats.update(
                staleness_mean=float(seq_staleness.mean()),
                staleness_max=int(seq_staleness.max()),
                stale_seq_frac=float((seq_staleness > 0).mean()),
                n_dropped_stale=n_dropped)
        if dense is not None:
            global_stats["dense_reward_sum"] = float(dense.sum())
        if input_.metadata.get("n_turns"):
            global_stats["avg_turns"] = float(
                np.mean(input_.metadata["n_turns"]))

        train_data = dict(
            advantages=advantages,
            old_logp=old_logp,
            ppo_loss_mask=loss_mask,
            packed_input_ids=input_.data["packed_input_ids"],
            kl_rewards=kl_rewards,
        )
        # per-token staleness (shifted, length l-1) rides the
        # minibatch so the clipped-IS correction runs inside the loss
        has_stale = bool(versions) and self.staleness_is_clip is not None
        if has_stale:
            train_data["staleness"] = np.repeat(
                seq_staleness, [l - 1 for l in seqlens]
            ).astype(np.float32)
        has_mask = ("packed_logits_mask" in input_.keys and
                    input_.data.get("packed_logits_mask") is not None)
        if has_mask:
            train_data["packed_logits_mask"] = \
                input_.data["packed_logits_mask"]
        sample = SequenceSample.from_default(
            ids=input_.ids, seqlens=[[l] for l in
                                     common.seqlens_of(input_)],
            data=train_data)

        mbs = common.split_minibatches(sample, self.n_minibatches)
        cfg = model.config
        temperature = self.gconfig.temperature
        eps_clip = self.eps_clip
        early_kl = self.early_stop_kl
        early_imp = self.early_stop_imp_ratio

        attention_fn = engine.attention_fn
        pipeline = engine.pipeline_ctx
        moe_constraint = engine.moe_constraint

        is_clip = self.staleness_is_clip

        def loss_fn(params, mb):
            h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                             mb["seg_ids"], attention_fn,
                                             pipeline, moe_constraint)
            lmask = mb.get("logits_mask")
            lp = F.shifted_logprobs_from_hidden(
                cfg, params, h, mb["input_ids"], mb["seg_ids"],
                temperature=temperature, logits_mask=lmask)
            adv = mb["advantages"]
            stale_stats = {}
            if has_stale:
                # staleness-aware truncated IS (decoupled-PPO style):
                # stale tokens' advantages scale by
                # clip(pi_current/pi_behavior, 1/c, c), stop-gradiented
                # so the ordinary PPO ratio still does the proximal
                # clipping; fresh tokens keep weight 1
                behav_ratio = jnp.exp(
                    jax.lax.stop_gradient(lp) - mb["old_logp"])
                w = jnp.where(
                    mb["staleness"] > 0,
                    jnp.clip(behav_ratio, 1.0 / is_clip, is_clip),
                    1.0)
                adv = adv * w
                lm = mb["loss_mask"] > 0
                stale_stats["stale_is_weight"] = (
                    (w * lm).sum() / jnp.maximum(lm.sum(), 1))
            loss, stats = ppo_functional.actor_loss_fn(
                logprobs=lp, old_logprobs=mb["old_logp"],
                advantages=adv, eps_clip=eps_clip,
                loss_mask=mb["loss_mask"] > 0)
            # Early stop SKIPS the whole optimizer update (reference
            # semantics) via the engine's reserved stat -- a zeroed
            # loss would still apply AdamW weight decay and MoE aux
            # gradients.
            skip = jnp.zeros(())
            if early_imp is not None:
                skip = jnp.maximum(
                    skip, (stats["importance_weight"] > early_imp)
                    .astype(jnp.float32))
            if early_kl is not None:
                skip = jnp.maximum(
                    skip, (stats["approx_kl"] > early_kl)
                    .astype(jnp.float32))
            out_stats = dict(
                actor_loss=loss,
                ppo_approx_kl=stats["approx_kl"],
                actor_clip_ratio=stats["clip_ratio"],
                importance_weight=stats["importance_weight"],
                **stale_stats, **aux)
            if early_imp is not None or early_kl is not None:
                out_stats["__skip_update__"] = skip
            return loss + sum(aux.values()), out_stats

        loss_key = ("ppo_actor", has_mask, temperature, eps_clip,
                    early_kl, early_imp, has_stale, is_clip)

        def build_sb(minibatch):
            mb_lens = common.flat_seqlens(minibatch)
            shifted = dict(
                advantages=minibatch.data["advantages"],
                old_logp=minibatch.data["old_logp"],
                loss_mask=minibatch.data["ppo_loss_mask"]
                .astype(np.float32))
            if has_stale:
                shifted["staleness"] = minibatch.data["staleness"]
            sb = common.build_stream_batch(
                mb_lens,
                token_keys=dict(
                    input_ids=minibatch.data["packed_input_ids"]),
                shifted_keys=shifted,
                n_streams=engine.n_streams)
            if has_mask:
                sb.arrays["logits_mask"] = packing.pack_tokens(
                    sb.info, ~minibatch.data["packed_logits_mask"],
                    fill=True)
            return sb

        # MFCDef.n_mbs: memory microbatching WITHIN each PPO minibatch
        # -- gradients accumulate over n_mbs scanned microbatches in a
        # single optimizer step; the minibatch loop itself runs fused
        # in one dispatch (common.run_train_minibatches).
        all_stats = common.run_train_minibatches(
            engine, mbs, build_sb, loss_fn, loss_key, n_mbs)
        model.inc_version()

        agg = {k: float(np.mean([s[k] for s in all_stats]))
               for k in all_stats[0]}
        agg.update(global_stats)
        return agg

    def save(self, model: model_api.Model, save_dir: str,
             host_params=None, writer: bool = True):
        if not self.enable_save:
            return
        common.save_checkpoint(model, save_dir, host_params,
                               writer=writer)


@dataclasses.dataclass
class PPOCriticInterface(model_api.ModelInterface):
    n_minibatches: int = 4
    kl_ctl: float = 0.1
    discount: float = 1.0
    gae_lambda: float = 0.95
    value_eps_clip: float = 0.2
    max_reward_clip: float = 20.0
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    use_adaptive_kl_ctl: bool = False
    value_norm: bool = False
    value_norm_type: str = "exp"
    value_norm_beta: float = 0.99995
    value_norm_eps: float = 1e-5
    enable_save: bool = True
    #: must match the actor's knob: the critic's regression target is
    #: computed from the same reward placement (docs/agentic.md)
    turn_level_credit: bool = False

    def __post_init__(self):
        if self.use_adaptive_kl_ctl:
            self.kl_adapter = ppo_functional.AdaptiveKLController(
                self.kl_ctl, self.adaptive_kl_target, self.adaptive_kl_horizon)
        else:
            self.kl_adapter = ppo_functional.FixedKLController(self.kl_ctl)
        if self.value_norm:
            self.rms = _make_rms(self.value_norm_type, self.value_norm_beta,
                                 self.value_norm_eps)

    def inference(self, model: model_api.Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        """Produce values for every token (reference
        PPOCriticInterface.inference). ``n_mbs`` chunks the batch for
        HBM headroom."""
        pieces = []
        for chunk in common.split_minibatches(input_, n_mbs or 1):
            seqlens = common.flat_seqlens(chunk)
            sb = common.build_stream_batch(
                seqlens,
                token_keys=dict(input_ids=chunk.data["packed_input_ids"]),
                n_streams=model.engine.n_streams)
            values = np.asarray(model.engine.forward_values(
                sb.arrays["input_ids"], sb.arrays["seg_ids"]))
            pieces.append(packing.unpack_tokens(sb.info, values))
        flat = np.concatenate(pieces)
        return SequenceSample.from_default(
            ids=input_.ids, seqlens=common.flat_seqlens(input_),
            data=dict(values=flat.astype(np.float32)))

    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        seqlens = common.flat_seqlens(input_)
        n_seqs = len(seqlens)
        cu = np.concatenate([[0], np.cumsum(seqlens)]).astype(np.int64)
        short1 = cu - np.arange(n_seqs + 1)

        old_logp = np.asarray(input_.data["packed_logprobs"], np.float32)
        ref_logp = np.asarray(input_.data["packed_ref_logprobs"], np.float32)
        prompt_mask = np.asarray(input_.data["prompt_mask"], bool)
        reward_score = np.asarray(input_.data["rewards"], np.float32)
        values = np.asarray(input_.data["values"], np.float32).copy()
        seq_no_eos = np.asarray(input_.data["seq_no_eos_mask"], bool)

        if self.value_norm:
            denorm_values = self.rms.denormalize(values)
        else:
            denorm_values = values.copy()
        ends = cu[1:] - 1
        denorm_values[ends] = np.where(seq_no_eos, denorm_values[ends], 0.0)
        values[ends] = np.where(seq_no_eos, values[ends], 0.0)

        loss_mask = _shifted_loss_mask(prompt_mask, seqlens)
        old_logp = old_logp * loss_mask
        ref_logp = ref_logp * loss_mask

        dense = None
        if self.turn_level_credit and "dense_rewards" in input_.keys \
                and input_.data.get("dense_rewards") is not None:
            dense = np.asarray(input_.data["dense_rewards"],
                               np.float32)
        if dense is not None:
            kl_rewards, rewards = \
                ppo_functional.get_packed_dense_rewards(
                    kl_ctl=self.kl_adapter.value,
                    clip_reward_value=self.max_reward_clip,
                    log_probs=old_logp, ref_log_probs=ref_logp,
                    dense_rewards=dense)
        else:
            kl_rewards, rewards = ppo_functional.get_packed_rewards(
                kl_ctl=self.kl_adapter.value,
                clip_reward_value=self.max_reward_clip,
                log_probs=old_logp, ref_log_probs=ref_logp,
                reward_score=reward_score, short1cu_seqlens=short1,
                seq_no_eos_mask=seq_no_eos)
        # Keep the critic's adaptive KL coefficient in sync with the
        # actor's (reference updates it inside the critic loss too,
        # ppo_interface.py:629).
        n_tokens = max(int(loss_mask.sum()), 1)
        self.kl_adapter.update(float((kl_rewards * loss_mask).sum())
                               / n_tokens, n_steps=n_seqs)
        _, returns = gae_packed_numpy(
            rewards, denorm_values, short1, seq_no_eos.astype(np.float32),
            gamma=self.discount, lam=self.gae_lambda)

        if self.value_norm:
            self.rms.update(returns, mask=loss_mask)
            target = self.rms.normalize(returns)
        else:
            target = returns

        # per-position old values: values[t] for t in 0..l-2 (flat l-1)
        old_values_short = np.concatenate(
            [values[cu[i]:cu[i + 1] - 1] for i in range(n_seqs)])

        sample = SequenceSample.from_default(
            ids=input_.ids,
            seqlens=[[l] for l in common.seqlens_of(input_)],
            data=dict(
                packed_input_ids=input_.data["packed_input_ids"],
                returns=target.astype(np.float32),
                # note: "values"-style keys resolve to length l; these
                # are l-1, so reuse minus-1 key names
                old_logp=old_values_short.astype(np.float32),
                ppo_loss_mask=loss_mask,
            ))
        mbs = common.split_minibatches(sample, self.n_minibatches)

        cfg = model.config
        eps = self.value_eps_clip

        attention_fn = engine.attention_fn
        pipeline = engine.pipeline_ctx
        moe_constraint = engine.moe_constraint

        def loss_fn(params, mb):
            h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                             mb["seg_ids"], attention_fn,
                                             pipeline, moe_constraint)
            new_values = T.critic_values(cfg, params, h)
            loss, stats = ppo_functional.critic_loss_fn(
                value=new_values, old_value=mb["old_values"],
                target_value=mb["returns"], value_eps_clip=eps,
                loss_mask=mb["loss_mask"] > 0)
            return loss + sum(aux.values()), dict(
                value_loss=loss,
                value_clip_ratio=stats["value_clip_ratio"], **aux)

        def build_sb(minibatch):
            mb_lens = common.flat_seqlens(minibatch)
            return common.build_stream_batch(
                mb_lens,
                token_keys=dict(
                    input_ids=minibatch.data["packed_input_ids"]),
                shifted_keys=dict(
                    returns=minibatch.data["returns"],
                    old_values=minibatch.data["old_logp"],
                    loss_mask=minibatch.data["ppo_loss_mask"]
                    .astype(np.float32)),
                n_streams=engine.n_streams)

        all_stats = common.run_train_minibatches(
            engine, mbs, build_sb, loss_fn, ("ppo_critic", eps), n_mbs)
        model.inc_version()

        agg = {k: float(np.mean([s[k] for s in all_stats]))
               for k in all_stats[0]}
        agg["returns"] = float(returns.mean())
        return agg

    def save(self, model: model_api.Model, save_dir: str,
             host_params=None, writer: bool = True):
        if not self.enable_save:
            return
        common.save_checkpoint(model, save_dir, host_params,
                               writer=writer)


model_api.register_interface("ppo_actor", PPOActorInterface)
model_api.register_interface("ppo_critic", PPOCriticInterface)
