"""GRPO: group-relative policy optimization (critic-free PPO).

Parity with reference ``examples/new_algorithms/grpo/
grpo_interface.py``: each prompt samples a group of responses; the
advantage of every response token is the group-normalized reward
(r - mean_group) / (std_group + eps); the PPO clipped surrogate is
applied with a direct per-token KL penalty (the unbiased k3 estimator)
against the reference policy instead of KL-shaped rewards. No critic
model exists in the dataflow graph. Groups live as multiple sequences
inside one batch element (nested seqlens), so ids are preserved and
the DFG executor's data merge works unchanged.
"""

import dataclasses
from typing import Dict, Optional

import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.interfaces import common, ppo_functional
from realhf_tpu.interfaces.ppo import PPOActorInterface, _shifted_loss_mask

logger = logging.getLogger("GRPOInterface")


@dataclasses.dataclass
class GRPOInterface(PPOActorInterface):
    """Reuses the PPO actor's generate/inference plumbing; overrides
    advantage computation and the loss to the GRPO form."""
    group_size: int = 4
    kl_coef: float = 0.05

    def __post_init__(self):
        super().__post_init__()
        if self.use_adaptive_kl_ctl or self.early_stop_kl is not None \
                or self.early_stop_imp_ratio is not None:
            raise ValueError(
                "GRPOInterface does not implement adaptive KL control or "
                "early stopping; unset use_adaptive_kl_ctl/early_stop_*.")
        warping = (not self.gconfig.greedy
                   and (self.gconfig.top_k > 0 or self.gconfig.top_p < 1.0))
        if warping and not self.gconfig.force_no_logits_mask:
            raise ValueError(
                "GRPO does not replay the sampling logits mask; either "
                "disable top-k/top-p or set force_no_logits_mask=True "
                "(accepting the warped-vs-raw logprob mismatch).")

    # ------------------------------------------------------------------
    def generate(self, model: model_api.Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        """Sample `group_size` responses per prompt. The output keeps
        the INPUT ids with `group_size` sequences nested per element,
        so the runner's data merge (`update_`) is untouched."""
        g = self.group_size
        reps = []
        for piece in input_.unpack():
            for j in range(g):
                reps.append(SequenceSample(
                    keys=piece.keys,
                    trailing_shapes=piece.trailing_shapes,
                    dtypes=piece.dtypes,
                    ids=[f"{piece.ids[0]}#g{j}"],
                    seqlens=piece.seqlens,
                    data=piece.data,
                    metadata={}))
        flat = super().generate(model, SequenceSample.gather(reps),
                                n_mbs=n_mbs)

        # regroup: bs*g flat elements -> bs elements with nested seqlens
        bs = input_.bs

        def nest(key):
            per = flat.seqlens[key]
            return [sum((per[i * g + j] for j in range(g)), [])
                    for i in range(bs)]

        with SequenceSample.disable_validation():
            return SequenceSample(
                keys=flat.keys,
                trailing_shapes=flat.trailing_shapes,
                dtypes=flat.dtypes,
                ids=list(input_.ids),
                seqlens={k: nest(k) for k in flat.keys},
                data=flat.data,
                metadata={})

    # ------------------------------------------------------------------
    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        seqlens = common.flat_seqlens(input_)
        n_seqs = len(seqlens)
        g = self.group_size
        assert n_seqs % g == 0, (n_seqs, g)

        old_logp = np.asarray(input_.data["packed_logprobs"], np.float32)
        ref_logp = np.asarray(input_.data["packed_ref_logprobs"], np.float32)
        prompt_mask = np.asarray(input_.data["prompt_mask"], bool)
        rewards = np.asarray(input_.data["rewards"], np.float32)

        loss_mask = _shifted_loss_mask(prompt_mask, seqlens)
        old_logp = old_logp * loss_mask
        ref_logp = ref_logp * loss_mask

        # group-relative advantages: one scalar per sequence, broadcast
        # over its response tokens (unbiased std, reference parity).
        # Clipping applies to the NORMALIZED advantage (reference
        # grpo_interface.py:379), not the raw reward.
        grp = rewards.reshape(-1, g)
        lens_m1 = np.asarray(seqlens) - 1
        dense = None
        if self.turn_level_credit and "dense_rewards" in input_.keys \
                and input_.data.get("dense_rewards") is not None:
            dense = np.asarray(input_.data["dense_rewards"], np.float32)
        if dense is not None:
            # turn-level credit (docs/agentic.md): per-token
            # discounted reward-to-go over the turn rewards, centered
            # and scaled by the GROUP's total-reward statistics -- at
            # the first slot this reduces to the seq-level form, and
            # tokens after a turn boundary stop being credited for
            # rewards already banked
            rtg = np.zeros_like(dense)
            off = 0
            for l in lens_m1:
                acc = 0.0
                for t in range(l - 1, -1, -1):
                    acc = float(dense[off + t]) + self.discount * acc
                    rtg[off + t] = acc
                off += l
            mean_seq = np.repeat(
                np.repeat(grp.mean(axis=1), g), lens_m1)
            std_seq = np.repeat(
                np.repeat(grp.std(axis=1, ddof=1), g), lens_m1)
            advantages = ((rtg - mean_seq) / (std_seq + 1e-5)) \
                .astype(np.float32)
            advantages = np.clip(advantages, -self.max_reward_clip,
                                 self.max_reward_clip)
        else:
            adv_seq = ((grp - grp.mean(axis=1, keepdims=True))
                       / (grp.std(axis=1, ddof=1, keepdims=True)
                          + 1e-5)).reshape(-1)
            adv_seq = np.clip(adv_seq, -self.max_reward_clip,
                              self.max_reward_clip)
            advantages = np.repeat(adv_seq, lens_m1).astype(np.float32)
            if self.discount != 1.0:
                # spread the terminal advantage backwards with
                # discount^(T-1-t) decay (the reference reuses its GAE
                # spreader with lam=discount on a terminal-only reward)
                decay = np.concatenate([
                    self.discount ** np.arange(l - 1, -1, -1,
                                               dtype=np.float32)
                    for l in lens_m1])
                advantages = advantages * decay
        advantages = advantages * loss_mask
        if self.adv_norm:
            m = loss_mask.astype(np.float64)
            mean = (advantages * m).sum() / max(m.sum(), 1)
            var = ((advantages - mean) ** 2 * m).sum() / max(m.sum(), 1)
            advantages = ((advantages - mean) /
                          np.sqrt(var + 1e-5)).astype(np.float32) * loss_mask

        n_tokens = max(int(loss_mask.sum()), 1)
        global_stats = dict(
            task_reward=float(rewards.mean()),
            advantage=float(advantages.sum() / n_tokens),
            avg_seq_len=float(np.mean(seqlens)),
            n_seqs=n_seqs)

        nested = input_.seqlens["packed_input_ids"]
        nested_m1 = [[l - 1 for l in lens] for lens in nested]
        with SequenceSample.disable_validation():
            sample = SequenceSample(
                keys=["packed_input_ids", "advantages", "old_logp",
                      "ref_logp", "ppo_loss_mask"],
                trailing_shapes={k: () for k in (
                    "packed_input_ids", "advantages", "old_logp",
                    "ref_logp", "ppo_loss_mask")},
                dtypes=dict(packed_input_ids=np.int32,
                            advantages=np.float32, old_logp=np.float32,
                            ref_logp=np.float32, ppo_loss_mask=np.bool_),
                ids=list(input_.ids),
                seqlens=dict(packed_input_ids=nested,
                             advantages=nested_m1, old_logp=nested_m1,
                             ref_logp=nested_m1, ppo_loss_mask=nested_m1),
                data=dict(
                    packed_input_ids=input_.data["packed_input_ids"],
                    advantages=advantages, old_logp=old_logp,
                    ref_logp=ref_logp, ppo_loss_mask=loss_mask),
                metadata={})
        mbs = common.split_minibatches(sample, self.n_minibatches)

        cfg = model.config
        temperature = self.gconfig.temperature
        eps_clip = self.eps_clip
        kl_coef = self.kl_coef
        attention_fn = engine.attention_fn
        pipeline = engine.pipeline_ctx
        moe_constraint = engine.moe_constraint

        def loss_fn(params, mb):
            import jax.numpy as jnp
            from realhf_tpu.ops import functional as F
            h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                             mb["seg_ids"], attention_fn,
                                             pipeline, moe_constraint)
            lp = F.shifted_logprobs_from_hidden(
                cfg, params, h, mb["input_ids"], mb["seg_ids"],
                temperature=temperature)
            loss, stats = ppo_functional.actor_loss_fn(
                logprobs=lp, old_logprobs=mb["old_logp"],
                advantages=mb["advantages"], eps_clip=eps_clip,
                loss_mask=mb["loss_mask"] > 0)
            # unbiased per-token KL estimate vs the ref policy (k3):
            # exp(ref - pi) - (ref - pi) - 1
            m = mb["loss_mask"]
            diff = mb["ref_logp"] - lp
            kl = (jnp.where(m > 0, jnp.exp(diff) - diff - 1.0, 0.0)).sum() \
                / jnp.maximum(m.sum(), 1.0)
            total = loss + kl_coef * kl + sum(aux.values())
            return total, dict(
                grpo_loss=loss, grpo_kl=kl,
                importance_weight=stats["importance_weight"],
                clip_ratio=stats["clip_ratio"], **aux)

        def build_sb(minibatch):
            mb_lens = common.flat_seqlens(minibatch)
            return common.build_stream_batch(
                mb_lens,
                token_keys=dict(
                    input_ids=minibatch.data["packed_input_ids"]),
                shifted_keys=dict(
                    advantages=minibatch.data["advantages"],
                    old_logp=minibatch.data["old_logp"],
                    ref_logp=minibatch.data["ref_logp"],
                    loss_mask=minibatch.data["ppo_loss_mask"]
                    .astype(np.float32)),
                n_streams=engine.n_streams)

        all_stats = [
            common.run_train_microbatched(
                engine, minibatch, build_sb, loss_fn,
                ("grpo", temperature, eps_clip, kl_coef), n_mbs)
            for minibatch in mbs
        ]
        model.inc_version()
        agg = {k: float(np.mean([s[k] for s in all_stats]))
               for k in all_stats[0]}
        agg.update(global_stats)
        return agg


model_api.register_interface("grpo", GRPOInterface)
