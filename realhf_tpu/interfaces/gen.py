"""Batch generation interface: generate and dump to JSONL.

Parity with reference ``realhf/impl/model/interface/gen_interface.py``
(GenerationInterface:39) including the locked append-only output file.
"""

import dataclasses
import fcntl
import json
import os
from typing import Optional

import jax
import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.base.datapack import flat2d
from realhf_tpu.engine import packing
from realhf_tpu.ops.sampling import GenerationHyperparameters

logger = logging.getLogger("GenerationInterface")


@dataclasses.dataclass
class GenerationInterface(model_api.ModelInterface):
    output_file: Optional[str] = None
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters)
    # Continuous batching: slots refill from the prompt queue as
    # sequences finish (engine/inflight.py) -- higher throughput for
    # length-skewed batches; requires force_no_logits_mask.
    use_inflight_batching: bool = False
    inflight_slots: int = 0  # 0 = batch size

    def __post_init__(self):
        if isinstance(self.gconfig, dict):
            self.gconfig = GenerationHyperparameters(**self.gconfig)
        self._calls = 0
        self._inflight = None

    def generate(self, model: model_api.Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        tok = model.tokenizer
        prompt_lens = flat2d(input_.seqlens["packed_prompts"])
        flat = input_.data["packed_prompts"]
        prompts, off = [], 0
        for l in prompt_lens:
            prompts.append(np.asarray(flat[off:off + l]))
            off += l
        self._calls += 1
        from realhf_tpu.interfaces.ppo import _base_key
        key = jax.random.fold_in(_base_key(), self._calls)

        if self.use_inflight_batching:
            if model.engine.multiproc:
                # InflightBatchingGenerator keeps process-local jnp
                # state and reads arrays host-side (np.asarray), both
                # invalid when the mesh spans worker processes.
                raise NotImplementedError(
                    "Inflight-batching generation on a multi-process "
                    "(worker-group) mesh is not supported; run the "
                    "generation MFC on a single-process allocation or "
                    "disable use_inflight_batching.")
            # On a pipeline- or context-parallel mesh, decode runs on
            # the collapsed dp x tp decode view (weights resharded per
            # version, engine.decode_engine) -- same path the batch
            # generate takes.
            eng = model.engine.decode_engine()
            from realhf_tpu.engine.inflight import (
                InflightBatchingGenerator,
            )
            from realhf_tpu.engine.inflight import _bucket
            # bucket the cache size so slowly-growing prompt lengths
            # reuse the compiled decode/prefill programs instead of
            # rebuilding the generator every batch
            need = _bucket(max(64, max(len(p) for p in prompts)))
            n_slots = self.inflight_slots or len(prompts)
            if (self._inflight is None
                    or self._inflight.cache_len
                    - self.gconfig.max_new_tokens < need
                    or self._inflight.n_slots != n_slots):
                # (re)build: a later batch may carry longer prompts
                # than the first one sized the cache for, or (with
                # inflight_slots=0 = "track batch size") a different
                # prompt count than the slots were built for
                self._inflight = InflightBatchingGenerator(
                    model.config, eng.params, self.gconfig,
                    n_slots=n_slots,
                    max_prompt_len=need,
                    eos_token_id=tok.eos_token_id,
                    pad_token_id=tok.pad_token_id,
                    moe_constraint=eng.moe_constraint,
                    mesh=eng.mesh,
                    attention_fn=eng.attention_fn)
            self._inflight.params = eng.params  # fresh weights
            finished = self._inflight.generate_all(prompts, key)
            # do not pin the weights pytree (train_batch donates its
            # buffers; a stale reference would keep a second full model
            # resident in HBM between calls)
            self._inflight.params = None
            lengths = np.asarray([len(f.tokens) for f in finished])
            maxg = max(1, int(lengths.max()))
            gen_tokens = np.full((len(prompts), maxg),
                                 tok.pad_token_id, np.int32)
            for i, f in enumerate(finished):
                gen_tokens[i, :len(f.tokens)] = f.tokens
        else:
            ids, seg, pos = packing.left_padded_prompts(
                prompts, pad_id=tok.pad_token_id)
            out = model.engine.generate(
                ids, seg, pos, key, self.gconfig,
                eos_token_id=tok.eos_token_id,
                pad_token_id=tok.pad_token_id)
            out = out.to_host()  # one bundled D2H round-trip
            gen_tokens = np.asarray(out.tokens)
            lengths = np.asarray(out.lengths)

        if self.output_file is not None:
            path = self.output_file
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            records = []
            for i, p in enumerate(prompts):
                g = int(lengths[i])
                records.append(dict(
                    id=str(input_.ids[i]),
                    prompt=tok.decode(p.tolist()),
                    answer=tok.decode(gen_tokens[i, :g].tolist(),
                                      skip_special_tokens=True)))
            with open(path, "a") as f:
                fcntl.flock(f, fcntl.LOCK_EX)
                for r in records:
                    f.write(json.dumps(r, ensure_ascii=False) + "\n")
                fcntl.flock(f, fcntl.LOCK_UN)

        seqlens, in_ids = [], []
        for i, p in enumerate(prompts):
            g = int(lengths[i])
            seqlens.append(len(p) + g)
            in_ids.append(np.concatenate([p, gen_tokens[i, :g]]))
        return SequenceSample.from_default(
            ids=input_.ids, seqlens=seqlens,
            data=dict(packed_input_ids=np.concatenate(in_ids)
                      .astype(np.int32)))


model_api.register_interface("generation", GenerationInterface)
