"""Paired reward modeling interface (Bradley-Terry).

Parity with reference ``realhf/impl/model/interface/rw_interface.py``
(PairedRewardInterface:103, _paired_rw_loss_from_model_outputs:25):
each batch element packs interleaved (pos, neg) full sequences; the
score is the critic head's value at each sequence's final token; loss
is -log sigmoid(score_pos - score_neg) averaged over pairs. The
`inference` handler scores sequences for PPO's rew_inf MFC.
"""

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.engine import packing
from realhf_tpu.interfaces import common
from realhf_tpu.models import transformer as T

logger = logging.getLogger("PairedRewardInterface")


def _make_loss_fn(cfg, attention_fn=None, pipeline=None,
                  moe_constraint=None):

    def loss_fn(params, mb):
        h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                         mb["seg_ids"], attention_fn,
                                         pipeline, moe_constraint)
        values = T.critic_values(cfg, params, h)  # [S, L]
        # Gather per-pair (pos, neg) end-of-sequence scores via (row,
        # col) coordinates (stable under stream padding), plus a pair
        # validity mask (groups may have fewer than max_pairs pairs).
        pos = values[mb["pos_row"], mb["pos_col"]]
        neg = values[mb["neg_row"], mb["neg_col"]]
        valid = mb["pair_valid"]
        denom = jnp.maximum(valid.sum(), 1)
        losses = -jax.nn.log_sigmoid(pos - neg)
        loss = (losses * valid).sum() / denom
        acc = ((pos > neg) & (valid > 0)).sum() / denom
        return loss + sum(aux.values()), {
            "loss": loss,
            "acc": acc.astype(jnp.float32),
            "pos_score": (pos * valid).sum() / denom,
            "neg_score": (neg * valid).sum() / denom,
            **aux,
        }

    return loss_fn


@dataclasses.dataclass
class PairedRewardInterface(model_api.ModelInterface):
    enable_save: bool = True
    output_scaling: float = 1.0
    output_bias: float = 0.0

    def _score_batch(self, model, input_: SequenceSample) -> np.ndarray:
        """Value at the final token of every sequence (flattened)."""
        seqlens = common.flat_seqlens(input_)
        sb = common.build_stream_batch(
            seqlens,
            token_keys=dict(input_ids=input_.data["packed_input_ids"]),
            n_streams=model.engine.n_streams)
        values = np.asarray(model.engine.forward_values(
            sb.arrays["input_ids"], sb.arrays["seg_ids"]))
        scores = packing.per_seq_gather(
            sb.info, values, [l - 1 for l in seqlens])
        return (scores - self.output_bias) * self.output_scaling

    def inference(self, model: model_api.Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        scores = self._score_batch(model, input_)
        # One score per batch element: elements holding multiple
        # sequences (paired data) keep per-sequence scores concatenated.
        n_per_elem = [len(l) for l in input_.seqlens["packed_input_ids"]]
        assert sum(n_per_elem) == len(scores)
        return SequenceSample(
            keys=["rewards"],
            trailing_shapes=dict(rewards=()),
            dtypes=dict(rewards=np.float32),
            ids=input_.ids,
            seqlens=dict(rewards=[[1] * n for n in n_per_elem]),
            data=dict(rewards=scores.astype(np.float32)),
        )

    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        mbs = common.split_minibatches(input_, n_mbs or 1)
        batches, weights = [], []
        for mb in mbs:
            seqlens = common.flat_seqlens(mb)
            sb = common.build_stream_batch(
                seqlens,
                token_keys=dict(input_ids=mb.data["packed_input_ids"]),
                n_streams=engine.n_streams)
            # (row, col) of each sequence's final token
            ends = [(sb.info.stream[i], sb.info.offset[i] + ln - 1)
                    for i, ln in enumerate(seqlens)]
            pr, pc, nr, nc, valid = [], [], [], [], []
            si = 0
            n_pairs_total = sum(
                len(lens) // 2 for lens in mb.seqlens["packed_input_ids"])
            for lens in mb.seqlens["packed_input_ids"]:
                for p in range(len(lens) // 2):
                    pr.append(ends[si + 2 * p][0])
                    pc.append(ends[si + 2 * p][1])
                    nr.append(ends[si + 2 * p + 1][0])
                    nc.append(ends[si + 2 * p + 1][1])
                    valid.append(1.0)
                si += len(lens)
            sb.arrays["pos_row"] = np.asarray(pr, np.int32)
            sb.arrays["pos_col"] = np.asarray(pc, np.int32)
            sb.arrays["neg_row"] = np.asarray(nr, np.int32)
            sb.arrays["neg_col"] = np.asarray(nc, np.int32)
            sb.arrays["pair_valid"] = np.asarray(valid, np.float32)
            batches.append(sb)
            weights.append(n_pairs_total)
        batches = common.pad_stream_batches(batches)
        # pair vectors are 1D (pad_stream_batches leaves them); pad to a
        # common pair count so microbatches stack
        npair = max(b.arrays["pos_row"].shape[0] for b in batches)
        for b in batches:
            for k in ("pos_row", "pos_col", "neg_row", "neg_col",
                      "pair_valid"):
                v = b.arrays[k]
                b.arrays[k] = np.pad(v, (0, npair - v.shape[0]))
        stats = engine.train_batch(
            [b.arrays for b in batches],
            _make_loss_fn(model.config, engine.attention_fn,
                          engine.pipeline_ctx, engine.moe_constraint),
            loss_weights=weights, loss_fn_key="paired_rw")
        model.inc_version()
        return stats

    def save(self, model: model_api.Model, save_dir: str,
             host_params=None, writer: bool = True):
        if not self.enable_save:
            return
        common.save_checkpoint(model, save_dir, host_params,
                               writer=writer)


model_api.register_interface("paired_rw", PairedRewardInterface)
