"""Algorithm interfaces; importing registers them by name.

Registered names match the reference (``impl/model/interface/``):
"sft", "paired_rw", "dpo", "ppo_actor", "ppo_critic", "generation",
"grpo"; plus the TPU-native "agentic_actor" (realhf_tpu/agentic/).
"""

import realhf_tpu.interfaces.sft  # noqa: F401
import realhf_tpu.interfaces.rw  # noqa: F401
import realhf_tpu.interfaces.dpo  # noqa: F401
import realhf_tpu.interfaces.ppo  # noqa: F401
import realhf_tpu.interfaces.gen  # noqa: F401
import realhf_tpu.interfaces.grpo  # noqa: F401
import realhf_tpu.interfaces.reinforce  # noqa: F401
import realhf_tpu.agentic.interface  # noqa: F401 - "agentic_actor"
