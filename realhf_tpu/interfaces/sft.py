"""Supervised fine-tuning interface.

Parity with reference ``realhf/impl/model/interface/sft_interface.py``
(SFTInterface:87, compute_packed_sft_loss:19): next-token NLL over
non-prompt tokens of packed sequences.
"""

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.interfaces import common
from realhf_tpu.models import transformer as T
from realhf_tpu.ops import functional as F

logger = logging.getLogger("SFTInterface")


def _make_loss_fn(cfg, attention_fn=None, pipeline=None,
                  moe_constraint=None):

    def loss_fn(params, mb):
        h, aux = common.forward_with_aux(cfg, params, mb["input_ids"],
                                         mb["seg_ids"], attention_fn,
                                         pipeline, moe_constraint)
        lp = F.shifted_logprobs_from_hidden(
            cfg, params, h, mb["input_ids"], mb["seg_ids"])
        # loss_mask[t] gates predicting token t+1: valid next-token
        # positions that are not prompt tokens (reference
        # compute_packed_sft_loss:19 shifts the prompt mask by one).
        seg = mb["seg_ids"]
        next_same = jnp.concatenate(
            [(seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0),
             jnp.zeros_like(seg[:, :1], bool)], axis=1)
        next_is_prompt = jnp.concatenate(
            [mb["prompt_mask"][:, 1:], jnp.zeros_like(seg[:, :1], bool)],
            axis=1)
        mask = next_same & ~next_is_prompt
        denom = jnp.maximum(mask.sum(), 1)
        nll = -(lp * mask).sum() / denom
        loss = nll + sum(aux.values())
        return loss, {"nll": nll, "n_tokens": denom.astype(jnp.float32),
                      **aux}

    return loss_fn


@dataclasses.dataclass
class SFTInterface(model_api.ModelInterface):
    token_normalize_scope: str = "dp"  # kept for config parity

    def train_step(self, model: model_api.Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        engine = model.engine
        n_mbs = n_mbs or 1
        mbs = common.split_minibatches(input_, n_mbs)
        batches = []
        for mb in mbs:
            seqlens = common.flat_seqlens(mb)
            batches.append(common.build_stream_batch(
                seqlens,
                token_keys=dict(
                    input_ids=mb.data["packed_input_ids"],
                    prompt_mask=mb.data["prompt_mask"]),
                n_streams=engine.n_streams))
        batches = common.pad_stream_batches(batches)
        # weight by ANSWER tokens (what each microbatch loss averages
        # over), so grad accumulation equals the one-big-batch gradient
        weights = [float((~b.arrays["prompt_mask"].astype(bool)
                          & (b.arrays["seg_ids"] != 0)).sum())
                   for b in batches]
        if not any(w > 0 for w in weights):
            weights = [float(b.n_tokens) for b in batches]
        stats = engine.train_batch(
            [b.arrays for b in batches],
            _make_loss_fn(model.config, engine.attention_fn,
                          engine.pipeline_ctx, engine.moe_constraint),
            loss_weights=weights, loss_fn_key="sft")
        model.inc_version()
        return stats

    def evaluate(self, model: model_api.Model, eval_dataloader) -> Dict:
        losses, tokens = [], []
        for batch in eval_dataloader:
            seqlens = common.flat_seqlens(batch)
            sb = common.build_stream_batch(
                seqlens,
                token_keys=dict(
                    input_ids=batch.data["packed_input_ids"],
                    prompt_mask=batch.data["prompt_mask"]),
                n_streams=model.engine.n_streams)
            lp = np.asarray(model.engine.forward_logprobs(
                sb.arrays["input_ids"], sb.arrays["seg_ids"]))
            seg = sb.arrays["seg_ids"]
            next_same = np.concatenate(
                [(seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0),
                 np.zeros_like(seg[:, :1], bool)], axis=1)
            next_is_prompt = np.concatenate(
                [sb.arrays["prompt_mask"][:, 1:],
                 np.zeros_like(seg[:, :1], bool)], axis=1)
            mask = next_same & ~next_is_prompt
            losses.append(-(lp * mask).sum())
            tokens.append(mask.sum())
        if not tokens:
            return {}
        loss = float(np.sum(losses) / max(1, np.sum(tokens)))
        return {"loss": loss, "ppl": float(np.exp(loss))}

    def save(self, model: model_api.Model, save_dir: str,
             host_params=None, writer: bool = True):
        common.save_checkpoint(model, save_dir, host_params,
                               writer=writer)


model_api.register_interface("sft", SFTInterface)
