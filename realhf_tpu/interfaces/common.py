"""Shared interface plumbing: SequenceSample <-> stream-array batches.

Each interface packs its minibatch of ragged sequences into [S, L]
stream arrays (see engine/packing.py) before handing them to the
jitted engine, and unpacks engine outputs back into flat packed
arrays for the data plane.
"""

import dataclasses
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base.datapack import flat2d
from realhf_tpu.engine import packing


def seqlens_of(input_: SequenceSample, key: str = "packed_input_ids") -> List[int]:
    """Total sequence length per batch element for a key (elements may
    hold several sequences, e.g. reward pairs)."""
    return [sum(l) for l in input_.seqlens[key]]


def flat_seqlens(input_: SequenceSample, key: str = "packed_input_ids") -> List[int]:
    """Per-sequence lengths, flattened over batch elements."""
    return flat2d(input_.seqlens[key])


@dataclasses.dataclass
class StreamBatch:
    """One packed minibatch ready for the engine."""
    info: packing.PackInfo
    arrays: Dict[str, np.ndarray]
    n_tokens: int


def build_stream_batch(
    seqlens: Sequence[int],
    token_keys: Dict[str, np.ndarray],
    shifted_keys: Optional[Dict[str, np.ndarray]] = None,
    n_streams: int = 1,
    bucket: int = packing.DEFAULT_BUCKET,
    min_len: Optional[int] = None,
) -> StreamBatch:
    """Pack flat per-token arrays into stream layout.

    ``token_keys`` have per-sequence length l; ``shifted_keys`` have
    length l-1 (logprobs/advantages/...) and are aligned to the
    sequence start so that index t corresponds to predicting token t+1.
    """
    info = packing.plan_packing(seqlens, n_streams, bucket, min_len)
    arrays = {"seg_ids": packing.segment_ids(info)}
    for k, v in token_keys.items():
        arrays[k] = packing.pack_tokens(info, v)
    if shifted_keys:
        short = [l - 1 for l in seqlens]
        for k, v in shifted_keys.items():
            arrays[k] = packing.pack_tokens(info, v, seqlens=short)
    return StreamBatch(info=info, arrays=arrays,
                       n_tokens=int(np.sum(seqlens)))


def split_minibatches(input_: SequenceSample, n: int,
                      min_size: int = 1) -> List[SequenceSample]:
    """Token-balanced minibatch split (SequenceSample.split), clamped
    so tiny batches still work."""
    n = max(1, min(n, input_.bs // max(1, min_size)))
    if n <= 1:
        return [input_]
    return input_.split(n, min_size=min_size)


def forward_with_aux(cfg, params, input_ids, seg_ids, attention_fn=None,
                     pipeline=None, moe_constraint=None):
    """Model forward returning (hidden, aux-loss dict). For MoE models
    the dict carries router load-balancing/z losses that MUST be added
    to the training objective (the reference applies them automatically
    via MoEAuxLossAutoScaler, utils/moe.py:395); dense models return
    an empty dict. ``pipeline`` is the engine's PipelineContext when
    the model mesh is pipeline-parallel; ``moe_constraint`` is the
    engine's expert-parallel sharding hook."""
    from realhf_tpu.models import transformer as _T
    if cfg.mlp_type == "moe":
        h, _, aux = _T.forward(cfg, params, input_ids, seg_ids,
                               return_aux=True, attention_fn=attention_fn,
                               moe_constraint=moe_constraint,
                               pipeline=pipeline)
        return h, aux
    h, _ = _T.forward(cfg, params, input_ids, seg_ids,
                      attention_fn=attention_fn, pipeline=pipeline)
    return h, {}


def run_train_microbatched(engine, sample: SequenceSample, build_sb,
                           loss_fn, loss_fn_key, n_mbs: Optional[int],
                           weight_key: str = "loss_mask") -> Dict:
    """One optimizer step over ``n_mbs`` memory microbatches of
    ``sample`` (MFCDef.n_mbs; reference model_api.py:305-463).

    Gradients are combined with weights equal to each microbatch's
    LOSS-MASK token count, which makes the accumulated gradient exactly
    the one-big-batch gradient (each microbatch loss is a mean over its
    own masked tokens). Weighting by total tokens would over-weight
    response tokens in prompt-heavy microbatches.
    """
    sbs = pad_stream_batches(
        [build_sb(m) for m in split_minibatches(sample, n_mbs or 1)])
    weights = [float(np.asarray(sb.arrays[weight_key]).sum()) for sb in sbs]
    if not any(w > 0 for w in weights):  # degenerate batch: avoid 0/0
        weights = [float(sb.n_tokens) for sb in sbs]
    return engine.train_batch([sb.arrays for sb in sbs], loss_fn,
                              loss_weights=weights, loss_fn_key=loss_fn_key)


def run_train_minibatches(engine, minibatch_samples, build_sb, loss_fn,
                          loss_fn_key, n_mbs: Optional[int],
                          weight_key: str = "loss_mask") -> List[Dict]:
    """The PPO-style minibatch loop: one optimizer step per minibatch
    sample, each accumulating over ``n_mbs`` memory microbatches.

    By default the WHOLE loop runs inside one jitted dispatch
    (``Engine.train_minibatches``: lax.scan threads params/opt state
    through the per-minibatch step), so a remote-attached chip pays one
    dispatch+sync round-trip instead of one per minibatch -- identical
    update order and numerics to sequential ``train_batch`` calls.
    ``REALHF_TPU_FUSE_MINIBATCHES=0`` restores the sequential calls
    (e.g. when length-skewed minibatches would over-pad the common
    bucket the fused path stacks into)."""
    fused = os.environ.get("REALHF_TPU_FUSE_MINIBATCHES", "1") != "0"
    splits = [split_minibatches(s, n_mbs or 1) for s in minibatch_samples]
    if (not fused or len(minibatch_samples) == 1
            or len({len(g) for g in splits}) != 1):
        # uneven microbatch counts cannot stack into one [N, M, ...];
        # counts are checked BEFORE any packing so the fallback does
        # not redo build_sb work
        return [run_train_microbatched(engine, m, build_sb, loss_fn,
                                       loss_fn_key, n_mbs, weight_key)
                for m in minibatch_samples]
    per_mb = [[build_sb(m) for m in group] for group in splits]
    flat = pad_stream_batches([sb for g in per_mb for sb in g])
    it = iter(flat)
    groups = [[next(it) for _ in g] for g in per_mb]
    stacks, weights = [], []
    for g in groups:
        w = [float(np.asarray(sb.arrays[weight_key]).sum()) for sb in g]
        if not any(x > 0 for x in w):
            w = [float(sb.n_tokens) for sb in g]
        stacks.append([sb.arrays for sb in g])
        weights.append(w)
    return engine.train_minibatches(stacks, loss_fn, weights,
                                    loss_fn_key)


def pad_stream_batches(batches: List[StreamBatch]) -> List[StreamBatch]:
    """Pad a list of stream batches to a common [S, L] so they can be
    stacked and scanned as microbatches in one jitted step."""
    s = max(b.arrays["seg_ids"].shape[0] for b in batches)
    l = max(b.arrays["seg_ids"].shape[1] for b in batches)
    out = []
    for b in batches:
        arrays = {}
        for k, v in b.arrays.items():
            if v.ndim < 2:  # per-pair/per-seq vectors, not [S, L] grids
                arrays[k] = v
                continue
            pad = [(0, s - v.shape[0]), (0, l - v.shape[1])] + \
                [(0, 0)] * (v.ndim - 2)
            arrays[k] = np.pad(v, pad)
        out.append(StreamBatch(info=b.info, arrays=arrays,
                               n_tokens=b.n_tokens))
    return out


def save_checkpoint(model, save_dir: str, host_params=None,
                    writer: bool = True):
    """Shared interface-save body (reference interfaces all end in the
    same ``api.save_hf(...)`` call).

    Default path: stream one layer at a time straight from the device
    arrays (``save_hf_checkpoint_streamed``), never materializing the
    full model on host. On a PROCESS-SPANNING mesh the per-layer
    slices are collective gathers every group member must join --
    ModelHost.save_role calls this on all members with
    ``writer=True`` only on the group leader, which alone writes
    files. ``host_params`` (a pre-gathered host copy) keeps the eager
    non-streamed path available for external callers."""
    from realhf_tpu.models.hf import (
        save_hf_checkpoint,
        save_hf_checkpoint_streamed,
    )
    if host_params is not None:
        if writer:
            save_hf_checkpoint(save_dir, model.hf_family, model.config,
                               host_params, tokenizer=model.tokenizer)
    else:
        save_hf_checkpoint_streamed(save_dir, model.hf_family,
                                    model.config, model.engine.params,
                                    tokenizer=model.tokenizer,
                                    writer=writer)
