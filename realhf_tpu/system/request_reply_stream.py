"""Master <-> worker control plane: the request-reply stream.

Parity with reference ``realhf/system/request_reply_stream.py``: the
master holds one PUB socket (broadcast requests, subscriber-filtered
by handler name) and one PULL socket (replies); each worker holds a
SUB + PUSH pair. Addresses rendezvous through name_resolve. Payloads
carry metadata only (pickled) -- tensors move on the device data plane
(ICI/DCN), never through here. The TCP-like syn -> ack -> request
protocol guarantees every addressed worker has received a request
before any of them starts executing it (reference
``model_worker.py:891-896``), which keeps collective-issuing workers
in lockstep without a barrier on the data plane.
"""

import collections
import dataclasses
import pickle
import time
import uuid
from typing import Any, Dict, List, Optional

import zmq

from realhf_tpu.base import fault_injection, logging, name_resolve, \
    names, network
from realhf_tpu.obs import tracing

logger = logging.getLogger("request_reply_stream")

PUBSUB_BARRIER_NAME = "__pubsub_barrier__"


class ReplyTimeoutError(TimeoutError):
    """gather_replies timed out: names the handlers that never replied
    and the request ids still outstanding (satellite of the
    fault-tolerance work: a bare TimeoutError after 600 s gave the
    operator nothing to act on)."""

    def __init__(self, missing: Dict[str, tuple], timeout: float):
        #: request_id -> (handler, handle_name)
        self.missing = dict(missing)
        self.handlers = sorted({h for h, _ in missing.values()})
        self.request_ids = sorted(missing)
        handles = sorted({hn for _, hn in missing.values() if hn})
        super().__init__(
            f"No reply within {timeout:.1f}s from handlers "
            f"{self.handlers} (requests {handles or '?'}); outstanding "
            f"request ids: {self.request_ids}.")


@dataclasses.dataclass
class Payload:
    """One control-plane message (reference Payload:33)."""
    handler: str = ""          # addressed worker, e.g. "model_worker/3"
    handle_name: str = ""      # request type: inference/train_step/...
    request_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex)
    syn_reply_id: str = ""
    ack_reply_id: str = ""
    no_syn: bool = True        # skip the syn-ack handshake
    data: Any = None           # pickled metadata (SequenceSample.meta() etc.)
    # trace-context carrier (obs/tracing.py): {trace_id, span_id} of
    # the sender-side span this request causally descends from; the
    # receiving worker parents its spans there so one PPO step renders
    # as a single cross-process timeline. None when tracing is off.
    trace: Optional[Dict] = None
    # pre/post hook descriptors (param_realloc / offload / data_transfer)
    pre_hooks: List[Any] = dataclasses.field(default_factory=list)
    post_hooks: List[Any] = dataclasses.field(default_factory=list)


class NameResolvingRequestClient:
    """Master side (reference NameResolvingRequestClient:62)."""

    def __init__(self, experiment_name: str, trial_name: str,
                 stream_name: str = "master"):
        self._reply_backlog = collections.deque()
        # request_id -> (handler, handle_name) of every request()ed
        # payload still awaiting its reply; lets timeouts name who is
        # silent. Entries clear on reply arrival or discard().
        self._outstanding: Dict[str, tuple] = {}
        self._ctx = zmq.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        host = network.gethostip()
        pub_port = self._pub.bind_to_random_port(f"tcp://*")
        self._pull = self._ctx.socket(zmq.PULL)
        pull_port = self._pull.bind_to_random_port(f"tcp://*")
        key = names.request_reply_stream(experiment_name, trial_name,
                                         stream_name)
        name_resolve.add(f"{key}/pub", f"tcp://{host}:{pub_port}",
                         replace=True)
        name_resolve.add(f"{key}/pull", f"tcp://{host}:{pull_port}",
                         replace=True)
        logger.info("Request client bound pub=%s pull=%s", pub_port,
                    pull_port)

    def wait_subscribers(self, handlers: List[str], timeout: float = 60.0,
                         check_liveness: Optional[callable] = None):
        """ZMQ PUB drops messages sent before SUB connects; workers ack
        a barrier message until all confirm (the pubsub barrier).
        ``check_liveness`` may raise to abort the wait early when a
        pending worker is known dead."""
        pending = set(handlers)
        deadline = time.monotonic() + timeout
        while pending:
            if check_liveness is not None:
                check_liveness()
            for h in list(pending):
                self.post(Payload(handler=h,
                                  handle_name=PUBSUB_BARRIER_NAME))
            t_end = min(deadline, time.monotonic() + 0.2)
            for p in self.poll_batch(timeout=max(0.0, t_end -
                                                 time.monotonic())):
                if p.handle_name == PUBSUB_BARRIER_NAME:
                    pending.discard(p.handler)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"Subscribers never connected: {sorted(pending)}")

    def post(self, payload: Payload) -> str:
        # network chaos shim (base/fault_injection.py net_* kinds):
        # the worker name is the TARGET, so a spec like
        # `partition:model_worker/1:*:1:5` cuts the master->worker
        # direction for that worker
        chaos = fault_injection.default_net_chaos()
        if chaos is not None and chaos.check(
                payload.handler,
                f"post.{payload.handle_name}") == "drop":
            logger.warning("Chaos dropped request %s -> %s (%s).",
                           payload.request_id, payload.handler,
                           payload.handle_name)
            return payload.request_id
        # NUL-terminated topic: ZMQ SUB matches by prefix, so a bare
        # "x/1" subscription would also receive "x/10".."x/19".
        self._pub.send_multipart([
            payload.handler.encode() + b"\0", pickle.dumps(payload)])
        return payload.request_id

    def _recv(self) -> Payload:
        p: Payload = pickle.loads(self._pull.recv())
        self._outstanding.pop(p.request_id, None)
        return p

    def discard(self, request_ids: List[str]):
        """Forget outstanding requests whose replies will never come
        (their worker was declared LOST); late replies still drain
        harmlessly through poll paths."""
        for r in request_ids:
            self._outstanding.pop(r, None)

    def outstanding_handlers(self, request_ids: List[str]) -> List[str]:
        """Handlers still owing replies among ``request_ids``."""
        return sorted({self._outstanding[r][0] for r in request_ids
                       if r in self._outstanding})

    def request(self, handlers: List[str], handle_name: str,
                datas: Optional[List[Any]] = None,
                no_syn: bool = True,
                syn_timeout: float = 300.0,
                trace_ctx: Optional[Dict] = None) -> List[str]:
        """Send one request to several workers; with syn-ack, all
        workers hold until everyone acked (reference
        master_worker.py:438-451). Raises TimeoutError naming the
        workers whose syn never arrived.

        ``trace_ctx`` overrides the propagated span context; by
        default the caller thread's current span (if tracing is on)
        rides along so worker-side spans nest under it."""
        datas = datas or [None] * len(handlers)
        if trace_ctx is None:
            trace_ctx = tracing.inject()
        payloads = [
            Payload(handler=h, handle_name=handle_name, data=d,
                    no_syn=no_syn, trace=trace_ctx,
                    syn_reply_id=uuid.uuid4().hex if not no_syn else "")
            for h, d in zip(handlers, datas)
        ]
        for p in payloads:
            self._outstanding[p.request_id] = (p.handler, p.handle_name)
            self.post(p)
        if not no_syn:
            want = {p.syn_reply_id: p.handler for p in payloads}
            deadline = time.monotonic() + syn_timeout
            while want:
                try:
                    r = self.poll(timeout=max(
                        0.01, deadline - time.monotonic()))
                except TimeoutError:
                    raise TimeoutError(
                        "No syn from workers: "
                        f"{sorted(want.values())}") from None
                want.pop(r.request_id, None)
            for p in payloads:
                self.post(Payload(handler=p.handler, handle_name="ack",
                                  request_id=p.syn_reply_id,
                                  ack_reply_id=p.request_id))
        return [p.request_id for p in payloads]

    def poll(self, timeout: Optional[float] = None) -> Payload:
        if self._reply_backlog:
            return self._reply_backlog.popleft()
        if timeout is not None:
            if not self._pull.poll(timeout * 1000):
                raise TimeoutError("No reply within timeout.")
        return self._recv()

    def poll_batch(self, timeout: float = 0.0) -> List[Payload]:
        """All immediately-available replies; `timeout` bounds the wait
        for the FIRST one only."""
        out = list(self._reply_backlog)
        self._reply_backlog.clear()
        if self._pull.poll(0 if out else timeout * 1000):
            out.append(self._recv())
            while self._pull.poll(0):
                out.append(self._recv())
        return out

    def gather_replies(self, request_ids: List[str],
                       timeout: float = 600.0,
                       check_liveness: Optional[callable] = None
                       ) -> List[Payload]:
        """Blocking gather of specific replies. Replies to OTHER
        requests arriving meanwhile are buffered for later
        poll/poll_batch calls, never dropped (the master interleaves
        blocking save/eval gathers with in-flight MFC replies).

        ``check_liveness`` (optional) runs ~every 100 ms of waiting
        and may raise (e.g. ``Watchdog.raise_if_lost``): a dead worker
        then fails the gather within the heartbeat timeout instead of
        after the full ``timeout``. On expiry raises
        :class:`ReplyTimeoutError` naming the silent handlers.

        Reads the SOCKET directly -- going through poll() would
        re-consume the very payloads this method just backlogged and
        spin forever.
        """
        got: Dict[str, Payload] = {}
        # a matching reply may already sit in the backlog
        for p in list(self._reply_backlog):
            if p.request_id in request_ids and p.request_id not in got:
                got[p.request_id] = p
                self._reply_backlog.remove(p)
        deadline = time.monotonic() + timeout
        while len(got) < len(request_ids):
            remaining = deadline - time.monotonic()
            missing = {r: self._outstanding.get(r, ("<unknown>", ""))
                       for r in request_ids if r not in got}
            if remaining <= 0:
                # checked every iteration: steady unrelated traffic
                # must not postpone the timeout indefinitely
                raise ReplyTimeoutError(missing, timeout)
            if check_liveness is not None:
                check_liveness()
            if not self._pull.poll(min(remaining, 0.1) * 1000):
                continue
            p = self._recv()
            if p.request_id in request_ids:
                got[p.request_id] = p
            else:
                self._reply_backlog.append(p)
        return [got[r] for r in request_ids]

    def close(self):
        self._pub.close(0)
        self._pull.close(0)


class NameResolvingReplyServer:
    """Worker side (reference NameResolvingReplyServer:206)."""

    def __init__(self, experiment_name: str, trial_name: str,
                 handler_name: str, stream_name: str = "master"):
        self.handler_name = handler_name
        self._backlog = collections.deque()
        key = names.request_reply_stream(experiment_name, trial_name,
                                         stream_name)
        pub_addr = name_resolve.wait(f"{key}/pub", timeout=120)
        pull_addr = name_resolve.wait(f"{key}/pull", timeout=120)
        self._ctx = zmq.Context.instance()
        self._sub = self._ctx.socket(zmq.SUB)
        self._sub.connect(pub_addr)
        self._sub.setsockopt(zmq.SUBSCRIBE, handler_name.encode() + b"\0")
        self._push = self._ctx.socket(zmq.PUSH)
        self._push.connect(pull_addr)

    def poll(self, timeout: Optional[float] = None) -> Payload:
        """Receive the next request; answers syn-ack and pubsub-barrier
        bookkeeping transparently."""
        while True:
            if self._backlog:
                payload = self._backlog.popleft()
            else:
                if timeout is not None and not self._sub.poll(timeout * 1000):
                    raise TimeoutError("No request within timeout.")
                _, raw = self._sub.recv_multipart()
                payload = pickle.loads(raw)
            if payload.handler != self.handler_name:
                # belt-and-braces against topic prefix collisions
                continue
            if payload.handle_name == PUBSUB_BARRIER_NAME:
                self.reply(Payload(handler=self.handler_name,
                                   handle_name=PUBSUB_BARRIER_NAME,
                                   request_id=payload.request_id))
                continue
            if payload.handle_name == "ack":
                return payload
            if not payload.no_syn:
                # reply syn, then wait for the broadcast ack before
                # handing the request to the worker
                self.reply(Payload(handler=self.handler_name,
                                   handle_name="syn",
                                   request_id=payload.syn_reply_id))
                while True:
                    _, raw2 = self._sub.recv_multipart()
                    ack: Payload = pickle.loads(raw2)
                    if (ack.handle_name == "ack"
                            and ack.request_id == payload.syn_reply_id):
                        break
                    # interleaved broadcasts must not be dropped --
                    # buffer them for subsequent poll() calls
                    self._backlog.append(ack)
            return payload

    def reply(self, payload: Payload):
        # worker->master chaos shim: here the worker name is the
        # SENDER (this handler), mirroring the handler-level
        # `drop_reply` fault one layer down, at the wire
        chaos = fault_injection.default_net_chaos()
        if chaos is not None and chaos.check(
                self.handler_name,
                f"reply.{payload.handle_name}") == "drop":
            logger.warning("Chaos dropped reply %s from %s (%s).",
                           payload.request_id, self.handler_name,
                           payload.handle_name)
            return
        self._push.send(pickle.dumps(payload))

    def respond(self, request: Payload, data: Any = None):
        self.reply(Payload(handler=self.handler_name,
                           handle_name=request.handle_name,
                           request_id=request.request_id, data=data))

    def close(self):
        self._sub.close(0)
        self._push.close(0)
