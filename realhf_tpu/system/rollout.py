"""RolloutController: overlap generation with training.

The loop-closer of ROADMAP item 1 (fully-async RLHF). The serving
subsystem (PR 2/7/8) already provides everything an async trainer
needs -- continuous batching, weight hot-swap with monotonic versions,
per-sequence ``weight_version`` stamps, ``max_staleness`` eviction --
but nothing kept the GenServer fleet saturated while the train mesh
consumed trajectories off-policy. This module does exactly that:

- :class:`RolloutController` pumps prompts into one or more
  :class:`~realhf_tpu.serving.server.RolloutClient` connections
  (round-robin across a fleet or through the PR 7 router), keeps a
  target number of requests in flight, and harvests finished
  trajectories as they complete -- stamped with the ``weight_version``
  they were generated under and, via ``harvest(export_kv=True)`` on
  the server side, the PR 8 spec-decoding stats riding the done event.
- Trajectories whose staleness (trainer version minus generation
  version) exceeds ``max_staleness`` are DROPPED and their prompts
  resubmitted -- the client-side mirror of the server's eviction
  policy, for the case where weights advanced after the sequence
  finished but before training consumed it.
- :func:`trajectories_to_sample` packs harvested trajectories into the
  actor-gen ``SequenceSample`` layout (``packed_input_ids`` /
  ``packed_logprobs`` / ``prompt_mask`` / ``seq_no_eos_mask``) with
  per-sample ``weight_version`` metadata, ready to stream into the
  per-sample :class:`~realhf_tpu.system.buffer.SequenceBuffer` while
  training drains it at its own ``n_seqs``.

Metrics (``serving_rollout_*``, docs/observability.md) and
``rollout:*`` trace spans make the generation/training overlap visible
in the PR 5 Perfetto timeline.
"""

import dataclasses
import time
from typing import Callable, Dict, Hashable, Iterator, List, Optional, Tuple

import numpy as np

from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging
from realhf_tpu.obs import metrics, tracing

logger = logging.getLogger("rollout", "system")


@dataclasses.dataclass
class Trajectory:
    """One finished rollout, as training consumes it."""
    sid: Hashable
    prompt: np.ndarray
    tokens: np.ndarray
    logprobs: np.ndarray
    no_eos: bool
    #: weight version installed when generation STARTED (the behavior
    #: policy label the PPO staleness correction keys on)
    weight_version: int
    #: trainer_version - weight_version at harvest time
    staleness: int
    spec_proposed: int = 0
    spec_accepted: int = 0
    # -- multi-turn / agentic extension (realhf_tpu/agentic/,
    # docs/agentic.md). When ``prompt_mask`` is set, the trajectory is
    # TRAJECTORY-STRUCTURED: ``prompt`` holds only the first
    # observation, ``tokens`` the remaining turns (actions + env/tool
    # observations interleaved), and the fields below carry the turn
    # structure. ``logprobs`` is then the FULL shifted (l-1) array,
    # zeros on non-action slots.
    #: full-length (l) bool mask: True on tokens the policy did NOT
    #: emit (initial prompt + env/tool observations) -- the same
    #: semantics single-turn samples give the key, so the PPO
    #: shifted-loss-mask excludes observation tokens unchanged
    prompt_mask: Optional[np.ndarray] = None
    #: shifted (l-1) per-position rewards: each turn's reward at its
    #: last action token's prediction slot, zeros elsewhere
    dense_rewards: Optional[np.ndarray] = None
    #: scalar episode reward (sum of turn rewards)
    reward: Optional[float] = None
    #: per-turn (start, n_obs, n_action, weight_version) spans over
    #: the flattened sequence, in turn order
    turns: Optional[List[tuple]] = None


class RolloutController:
    """Keeps a GenServer fleet saturated and streams back trajectories.

    ``prompt_source`` yields ``(sample_id, prompt_tokens)``;
    ``current_version`` reports the trainer's weight version (for
    staleness stamping/drops). ``max_inflight`` is the saturation
    target -- set it to a multiple of the train batch so generation
    runs ahead of consumption (e.g. 2x for the ISSUE-10 acceptance
    geometry).
    """

    def __init__(self, clients: List,
                 prompt_source: Iterator[Tuple[Hashable, np.ndarray]],
                 *, max_inflight: int = 8,
                 max_staleness: Optional[int] = None,
                 current_version: Callable[[], int] = lambda: 0,
                 ttl: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not clients:
            raise ValueError("RolloutController needs >= 1 client.")
        self.clients = list(clients)
        self._source = iter(prompt_source)
        self.max_inflight = max(1, int(max_inflight))
        self.max_staleness = max_staleness
        self._current_version = current_version
        self._ttl = ttl
        self._clock = clock
        # rid -> (sid, prompt, client index)
        self._pending: Dict[str, tuple] = {}
        #: prompts bounced back (rejected / stale / dropped) -- they
        #: resubmit ahead of fresh source prompts
        self._requeue: List[Tuple[Hashable, np.ndarray]] = []
        self._rr = 0
        self._exhausted = False
        # stats
        self.submitted = 0
        self.completed = 0
        self.dropped_stale = 0
        self.resubmits = 0
        self.staleness_seen: List[int] = []
        #: wall-clock with zero requests in flight while the source
        #: still had prompts (the rollout-idle fraction numerator)
        self.idle_secs = 0.0
        self._last_pump = self._clock()

    # ------------------------------------------------------------------
    @property
    def inflight(self) -> int:
        return len(self._pending)

    @property
    def exhausted(self) -> bool:
        """True once the prompt source is drained AND nothing is in
        flight or waiting to resubmit."""
        return (self._exhausted and not self._pending
                and not self._requeue)

    def _next_prompt(self):
        if self._requeue:
            return self._requeue.pop(0)
        if self._exhausted:
            return None
        try:
            return next(self._source)
        except StopIteration:
            self._exhausted = True
            return None

    def pump(self) -> int:
        """Submit prompts until ``max_inflight`` are in flight (or the
        source is drained). Returns how many were submitted."""
        now = self._clock()
        if self.inflight == 0 and not self.exhausted:
            self.idle_secs += now - self._last_pump
        self._last_pump = now
        n = 0
        spans_attrs = None
        while self.inflight < self.max_inflight:
            item = self._next_prompt()
            if item is None:
                break
            sid, prompt = item
            ci = self._rr % len(self.clients)
            self._rr += 1
            try:
                rid = self.clients[ci].submit(
                    np.asarray(prompt, np.int32), ttl=self._ttl)
            except (RuntimeError, OSError) as e:
                # transient submission failure (e.g. a sharded router
                # plane mid-re-home with no shard registered yet): the
                # prompt goes back in line rather than being lost, and
                # the pump retries on a later tick
                logger.warning("Rollout pump: submit failed (%s); "
                               "requeueing prompt.", e)
                self._requeue.append((sid, prompt))
                break
            self._pending[rid] = (sid, np.asarray(prompt, np.int32), ci)
            self.submitted += 1
            n += 1
        if n:
            spans_attrs = dict(n=n, inflight=self.inflight)
            with tracing.span("rollout:submit", **spans_attrs):
                pass
            metrics.inc("serving_rollout_submitted_total", amount=n)
        metrics.set_gauge("serving_rollout_inflight", self.inflight)
        return n

    def poll(self, timeout: float = 0.0) -> List[Trajectory]:
        """Harvest every finished trajectory (waiting up to
        ``timeout`` seconds for the first). Stale results are dropped
        and resubmitted; rejected/bounced requests resubmit too."""
        out: List[Trajectory] = []
        cur = self._current_version()
        for ci, client in enumerate(self.clients):
            for res in client.poll_results(timeout=timeout):
                ref = self._pending.pop(res.rid, None)
                if ref is None:
                    continue
                sid, prompt, _ci = ref
                if not res.ok:
                    # any non-``done`` terminal from
                    # serving/protocol.py (rejected, draining,
                    # expired, stale, cancelled): the prompt goes
                    # back in line
                    self._requeue.append((sid, prompt))
                    self.resubmits += 1
                    metrics.inc("serving_rollout_resubmits_total",
                                reason=res.status)
                    continue
                wv = int(res.data.get("weight_version") or 0)
                staleness = max(0, cur - wv)
                if self.max_staleness is not None \
                        and staleness > self.max_staleness:
                    # finished under weights now too old to train on:
                    # drop + regenerate under the fresh version
                    self.dropped_stale += 1
                    self.resubmits += 1
                    self._requeue.append((sid, prompt))
                    metrics.inc(
                        "serving_rollout_dropped_stale_total")
                    continue
                self.completed += 1
                self.staleness_seen.append(staleness)
                metrics.inc("serving_rollout_completed_total")
                metrics.observe("serving_rollout_staleness",
                                staleness)
                out.append(Trajectory(
                    sid=sid, prompt=prompt,
                    tokens=np.asarray(res.data["tokens"], np.int32),
                    logprobs=np.asarray(
                        res.data.get("logprobs", ()), np.float32),
                    no_eos=bool(res.data.get("no_eos", False)),
                    weight_version=wv, staleness=staleness,
                    spec_proposed=int(
                        res.data.get("spec_proposed") or 0),
                    spec_accepted=int(
                        res.data.get("spec_accepted") or 0)))
            timeout = 0.0  # only the first client may block
        if out:
            with tracing.span("rollout:harvest", n=len(out),
                              inflight=self.inflight):
                pass
        return out

    def drain(self, timeout: float = 60.0) -> List[Trajectory]:
        """Stop feeding and collect everything still in flight."""
        deadline = self._clock() + timeout
        out: List[Trajectory] = []
        while self._pending and self._clock() < deadline:
            out.extend(self.poll(timeout=0.05))
        return out

    def stats(self) -> dict:
        stale = self.staleness_seen
        return dict(
            submitted=self.submitted, completed=self.completed,
            dropped_stale=self.dropped_stale,
            resubmits=self.resubmits, inflight=self.inflight,
            idle_secs=round(self.idle_secs, 4),
            staleness_mean=(float(np.mean(stale)) if stale else 0.0),
            staleness_max=(int(max(stale)) if stale else 0),
            staleness_hist={str(k): int(v) for k, v in zip(
                *np.unique(stale, return_counts=True))} if stale
            else {})


# ----------------------------------------------------------------------
def trajectories_to_sample(trajs: List[Trajectory]) -> SequenceSample:
    """Pack harvested trajectories into the actor-gen output layout
    (mirrors ``PPOActorInterface.generate``): per sequence,
    ``packed_input_ids`` = prompt + generated tokens,
    ``packed_logprobs`` (length l-1, zeros over the prompt) carries
    the BEHAVIOR policy's sampling logprobs, ``prompt_mask`` marks the
    prompt span, and ``seq_no_eos_mask`` the truncated sequences.
    ``metadata['weight_version']`` stamps each sample for the
    staleness-aware importance correction in ``interfaces/ppo.py``.

    Multi-turn trajectories (``Trajectory.prompt_mask`` set -- built
    by ``realhf_tpu.agentic.trajectory``) pack through the SAME layout
    so the per-sample buffer and the PPO staleness machinery consume
    them unchanged; the batch additionally carries ``rewards`` (scalar
    episode reward -- no reward-model MFC exists in agentic graphs)
    and ``dense_rewards`` (shifted per-position turn rewards for the
    ``turn_level_credit`` knob), plus per-sample ``n_turns`` /
    ``turn_spans`` metadata. Single- and multi-turn trajectories must
    not mix in one batch (the data keys differ)."""
    if not trajs:
        raise ValueError("no trajectories to pack")
    agentic = trajs[0].prompt_mask is not None
    if any((t.prompt_mask is not None) != agentic for t in trajs):
        raise ValueError(
            "cannot pack single-turn and multi-turn trajectories into "
            "one batch: their data keys differ")
    seqlens, ids, in_ids, logprobs, prompt_mask = [], [], [], [], []
    no_eos, versions, staleness = [], [], []
    rewards, dense, n_turns, turn_spans = [], [], [], []
    for t in trajs:
        g = len(t.tokens)
        l = len(t.prompt) + g
        seqlens.append(l)
        ids.append(t.sid)
        in_ids.append(np.concatenate(
            [np.asarray(t.prompt, np.int32),
             np.asarray(t.tokens, np.int32)]))
        if agentic:
            lp = np.asarray(t.logprobs, np.float32)
            pm = np.asarray(t.prompt_mask, bool)
            dr = np.asarray(t.dense_rewards, np.float32)
            if len(lp) != l - 1 or len(pm) != l or len(dr) != l - 1:
                raise ValueError(
                    f"trajectory {t.sid}: multi-turn arrays must be "
                    f"full-length (l={l}): logprobs {len(lp)} "
                    f"(want {l - 1}), prompt_mask {len(pm)} (want {l}),"
                    f" dense_rewards {len(dr)} (want {l - 1})")
            logprobs.append(lp)
            prompt_mask.append(pm)
            dense.append(dr)
            rewards.append(np.float32(t.reward if t.reward is not None
                                      else dr.sum()))
            n_turns.append(len(t.turns or ()))
            turn_spans.append(list(t.turns or ()))
        else:
            lp = np.zeros(l - 1, np.float32)
            lp[len(t.prompt) - 1:] = np.asarray(t.logprobs,
                                                np.float32)[:g]
            logprobs.append(lp)
            prompt_mask.append(np.concatenate(
                [np.ones(len(t.prompt), bool), np.zeros(g, bool)]))
        no_eos.append(bool(t.no_eos))
        versions.append(int(t.weight_version))
        staleness.append(int(t.staleness))
    data = dict(
        seq_no_eos_mask=np.asarray(no_eos),
        packed_input_ids=np.concatenate(in_ids).astype(np.int32),
        packed_logprobs=np.concatenate(logprobs).astype(np.float32),
        prompt_mask=np.concatenate(prompt_mask),
    )
    metadata = dict(weight_version=versions, staleness=staleness)
    if agentic:
        data["rewards"] = np.asarray(rewards, np.float32)
        data["dense_rewards"] = np.concatenate(dense).astype(np.float32)
        metadata["n_turns"] = n_turns
        metadata["turn_spans"] = turn_spans
    return SequenceSample.from_default(
        ids=ids, seqlens=seqlens, data=data, metadata=metadata)
