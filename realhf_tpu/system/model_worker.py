"""Model worker: executes MFCs on its local device fleet.

TPU-native counterpart of reference ``realhf/system/model_worker.py``
(ModelWorker:85). One worker process per device slice (reference: one
per GPU; on TPU one per host-slice) hosts the model roles assigned to
it, stores MFC inputs/outputs locally (tensors never travel through
the master -- replies carry ``SequenceSample.meta()`` only,
model_worker.py:766-779), fetches missing input keys from peer
workers over the host data plane, and runs the dataset shard when it
owns the source MFC's role.

Request handlers mirror model_poll_step (model_worker.py:505):
fetch_data / generate / inference / train_step / evaluate / save /
clear_data_cache / offload.
"""

import os
import pickle
import queue
import time
from typing import Dict

from realhf_tpu.api import data as data_api
from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.api.dfg import DFG
from realhf_tpu.base import (
    constants,
    logging,
    name_resolve,
    names,
    recover,
    seeding,
)
from realhf_tpu.base.fault_injection import FaultInjected, FaultInjector
from realhf_tpu.obs import flight, metrics, tracing
from realhf_tpu.system import worker_base
from realhf_tpu.system.ckpt_manager import CheckpointManager
from realhf_tpu.system.data_plane import DataClient, DataServer, DataStore
from realhf_tpu.system.model_host import ModelHost
from realhf_tpu.system.request_reply_stream import (
    NameResolvingReplyServer,
    Payload,
)

logger = logging.getLogger("model_worker", "benchmark")


class ModelWorker(worker_base.Worker):
    """Config dict: {spec_path | spec, worker_index}."""

    def _configure(self, config: Dict):
        spec = config.get("spec")
        if spec is None:
            with open(config["spec_path"], "rb") as f:
                spec = pickle.load(f)
        self.spec = spec
        self.worker_index = int(config["worker_index"])

        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        seeding.set_random_seed(spec.seed + self.worker_index + 1)
        seeding.set_shared_seed(spec.seed)

        import realhf_tpu.datasets  # noqa: F401 - register datasets
        import realhf_tpu.interfaces  # noqa: F401 - register interfaces

        from realhf_tpu.api.experiment import FaultToleranceConfig
        self.ft = getattr(spec, "ft", None) or FaultToleranceConfig()
        # deterministic fault injection (REALHF_TPU_FAULTS), used by
        # the fault-tolerance tier-1 tests; None in production.
        # Created BEFORE the checkpoint managers so corrupt_ckpt
        # faults reach their commit hooks.
        self.faults = FaultInjector.from_env()
        self._ckpt_mgrs: Dict[str, CheckpointManager] = {}
        self.recover_mode = config.get("recover_mode", "disabled")
        if self.recover_mode == "resume" and getattr(
                self.ft, "durable_ckpt", False):
            self._redirect_models_to_durable(spec)

        self.dfg = DFG(spec.mfcs)
        # Roles whose primary group includes this worker.
        my_primary_roles = [r for r in spec.models
                            if self.worker_index
                            in spec.workers_of_role(r)]
        # MFCs this worker EXECUTES: its role's group by default, or
        # the MFC allocation's own worker group (per-MFC device-subset
        # placement, reference RPCAllocation device_mesh.py:269).
        my_nodes = [n for n in self.dfg.nodes
                    if self.worker_index
                    in spec.workers_of_node(n.name, n.role)]
        self.my_nodes = {n.name for n in my_nodes}
        self.cross_group_nodes = {
            n.name for n in my_nodes
            if spec.is_cross_group(n.name, n.role)}
        # Roles whose primary lives here but some MFC of theirs
        # executes on a DIFFERENT group: this worker is then the
        # SENDER side of the cross-group parameter sync. Only
        # trainable roles ever need syncing (frozen roles' replicas
        # initialize bit-identically from the shared checkpoint/seed).
        self.sync_send_roles = {
            n.role for n in self.dfg.nodes
            if n.role in my_primary_roles
            and spec.is_cross_group(n.name, n.role)
            and spec.models[n.role].optimizer is not None}
        # Primary engines actually needed here: roles with a local
        # exec node, plus sender roles (a frozen role whose MFCs all
        # moved elsewhere builds NO engine in this process).
        local_node_roles = {n.role for n in my_nodes
                            if n.name not in self.cross_group_nodes}
        my_roles = [r for r in my_primary_roles
                    if r in local_node_roles or r in self.sync_send_roles]
        # Group leadership: the first worker of a group owns the
        # dataset / reply payloads; members execute the same jitted
        # computations (their devices are part of the mesh) and
        # reply lightweight acks.
        self.leader_of_role = {
            r: spec.workers_of_role(r)[0] == self.worker_index
            for r in my_roles
        }
        self.leader_nodes = {
            n.name for n in my_nodes
            if spec.workers_of_node(n.name, n.role)[0]
            == self.worker_index}

        # Multi-host: all model workers join ONE jax.distributed world
        # (reference's single NCCL world, global_comm.py:44) with rank
        # == worker_index, then role meshes span their group's devices.
        self._devices_by_proc = None
        if spec.multihost:
            from realhf_tpu.parallel.multihost import (
                initialize_worker_world,
            )
            ldc = os.environ.get("REALHF_TPU_LOCAL_DEVICE_COUNT")
            initialize_worker_world(
                spec.experiment_name, spec.trial_name,
                spec.n_model_workers, self.worker_index,
                local_device_count=int(ldc) if ldc else None)
            from realhf_tpu.parallel.mesh import default_devices
            by_proc: Dict[int, list] = {}
            for d in sorted(default_devices(),
                            key=lambda d: (d.process_index, d.id)):
                by_proc.setdefault(d.process_index, []).append(d)
            self._devices_by_proc = by_proc

        self.tokenizer = spec.tokenizer or (
            data_api.load_hf_tokenizer(spec.tokenizer_path)
            if spec.tokenizer_path else None)

        # Dataset lives with the LEADER of the worker group hosting the
        # source MFC's role (reference: datasets on src-RPC DP-head
        # model workers, model_worker.py:256-292).
        src = self.dfg.sources[0]
        self.owns_data = src.name in self.leader_nodes
        self.dataloader_iter = None
        self._epoch = 0
        # steps_per_epoch feeds every trainable role's lr schedule, so
        # all workers must agree on it. The data owner loads the
        # dataset and publishes the count; other workers read it (or
        # fall back to loading the dataset themselves when they
        # configure before the owner).
        steps_key = (names.trial_root(spec.experiment_name,
                                      spec.trial_name)
                     + "/steps_per_epoch")
        if self.owns_data:
            dataset = data_api.make_dataset(
                spec.dataset, seed=spec.seed, dp_rank=0, world_size=1,
                tokenizer_or_path=self.tokenizer)
            self.dataloader = data_api.PackedDataLoader(
                dataset, batch_size=src.n_seqs, seed=spec.seed)
            self.steps_per_epoch = len(self.dataloader)
            self.dataloader_iter = iter(self.dataloader)
            name_resolve.add(steps_key, str(self.steps_per_epoch),
                             replace=True, delete_on_exit=False)
        else:
            try:
                self.steps_per_epoch = int(name_resolve.get(steps_key))
            except name_resolve.NameEntryNotFoundError:
                dataset = data_api.make_dataset(
                    spec.dataset, seed=spec.seed, dp_rank=0,
                    world_size=1, tokenizer_or_path=self.tokenizer)
                self.steps_per_epoch = len(data_api.PackedDataLoader(
                    dataset, batch_size=src.n_seqs, seed=spec.seed))

        self.eval_dataloader = None
        if spec.eval_dataset is not None and any(
                n.interface_type == ModelInterfaceType.TRAIN_STEP
                for n in my_nodes):
            eval_ds = data_api.make_dataset(
                spec.eval_dataset, seed=spec.seed, dp_rank=0,
                world_size=1, tokenizer_or_path=self.tokenizer)
            self.eval_dataloader = data_api.PackedDataLoader(
                eval_ds, batch_size=src.n_seqs, shuffle=False)

        total_steps = (self.steps_per_epoch or 1) * spec.total_train_epochs
        devices_fn = self._devices_for_group if spec.multihost else None
        self.host = ModelHost(spec, my_roles, my_nodes, self.tokenizer,
                              total_steps, devices_fn=devices_fn,
                              leader_of_role=self.leader_of_role,
                              cross_group_nodes=self.cross_group_nodes)

        # role -> engine version of the last published sync stream;
        # role -> retained published versions (GC window)
        self._last_published_sync: Dict[str, int] = {}
        self._published_versions: Dict[str, list] = {}
        # data plane: store + threaded server + peer-fetch client
        self.store = DataStore()
        self.data_server = DataServer(spec.experiment_name,
                                      spec.trial_name, self.worker_name,
                                      self.store)
        self.data_server.start()
        self.data_client = DataClient(spec.experiment_name,
                                      spec.trial_name)

        self.stream = NameResolvingReplyServer(
            spec.experiment_name, spec.trial_name, self.worker_name)
        logger.info("ModelWorker %s configured: roles=%s nodes=%s "
                    "owns_data=%s", self.worker_name, my_roles,
                    sorted(self.my_nodes), self.owns_data)
        return dict(roles=my_roles, nodes=sorted(self.my_nodes),
                    owns_data=self.owns_data,
                    steps_per_epoch=self.steps_per_epoch)

    # --- durable checkpoints (system/ckpt_manager.py) -----------------
    def _ckpt_manager(self, role: str) -> CheckpointManager:
        mgr = self._ckpt_mgrs.get(role)
        if mgr is None:
            mgr = CheckpointManager(
                os.path.join(constants.run_save_path(), "durable", role),
                keep=getattr(self.ft, "ckpt_keep", 2),
                injector=self.faults, owner=self.worker_name)
            self._ckpt_mgrs[role] = mgr
        return mgr

    def _redirect_models_to_durable(self, spec):
        """Resume path: point every role with a committed durable
        checkpoint at it (RecoverInfo v3 names the manifest covering
        the restored counters; a checksum failure falls back to the
        previous committed checkpoint, loudly). Multi-process groups
        agree by construction: shared FS + deterministic
        verification."""
        info = recover.load_safe()
        manifests = (getattr(info, "ckpt_manifests", None) or {}
                     if info is not None else {})
        for role, mspec in spec.models.items():
            mgr = self._ckpt_mgrs.get(role) or self._ckpt_manager(role)
            rec = (mgr.resolve_manifest(manifests[role])
                   if role in manifests else mgr.latest_verified())
            path = rec.path if rec is not None else None
            if path is None:
                if mgr.records():
                    # durable checkpoints exist but NONE verifies: a
                    # fresh start beats silently loading corrupt
                    # weights through the legacy symlink (which points
                    # into this same tree)
                    logger.error(
                        "Resume: every durable checkpoint of %s fails "
                        "verification; starting %s from scratch.",
                        role, role)
                    continue
                # durable_ckpt=False vintage: a REAL directory in the
                # plain HF layout is accepted without checksum cover
                legacy = os.path.join(constants.run_save_path(), role)
                if not os.path.islink(legacy) and os.path.exists(
                        os.path.join(legacy, "config.json")):
                    path = legacy
            if path is None:
                continue
            mspec.path = path
            mspec.random_init_config = None
            mspec.restore_optimizer_state = True
            logger.info("Resume: %s restores from %s%s.", role, path,
                        "" if rec is None else
                        f" (committed step {rec.step}, verified)")

    def _durable_save_role(self, role: str, node_name: str,
                           step: int):
        """Leader-side durable save: stage the ordinary role save in
        the manager's temp dir, checksum every produced file into the
        manifest, commit atomically, and refresh the legacy
        ``run_save_path()/role`` symlink for external consumers.
        Returns {path, manifest} or None (save disabled)."""
        mgr = self._ckpt_manager(role)
        t0 = time.monotonic()
        writer = mgr.begin(step, meta=dict(role=role, node=node_name,
                                           worker=self.worker_name))
        try:
            out = self.host.save_role(role, node_name, path=writer.path)
        except BaseException:
            writer.abort()
            raise
        if out is None and not os.listdir(writer.path):
            writer.abort()  # interface save disabled: nothing staged
            return None
        rec = writer.commit()
        mgr.gc()
        self._refresh_latest_link(role, rec.path)
        metrics.observe("ckpt_save_secs", time.monotonic() - t0,
                        role=role)
        return dict(path=rec.path, manifest=rec.manifest_path,
                    step=rec.step)

    @staticmethod
    def _refresh_latest_link(role: str, target: str):
        """Atomic symlink swap: ``run_save_path()/role`` keeps naming
        the newest committed checkpoint (external consumers and the
        legacy resume path read it)."""
        link = os.path.join(constants.run_save_path(), role)
        tmp = f"{link}.tmp-{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(link), exist_ok=True)
            if os.path.isdir(link) and not os.path.islink(link):
                # a real directory from a pre-durable run: leave it --
                # replacing user data with a link is not our call
                return
            os.symlink(target, tmp)
            os.replace(tmp, link)
        except OSError as e:
            logger.warning("Could not refresh latest-checkpoint link "
                           "%s -> %s: %s", link, target, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # --- preemption (elastic degraded-mode training) ------------------
    def _preempt_hook(self, grace: float):
        """Last acts inside the preemption grace window: finish
        draining is handled by the poll loop; here the trainable
        roles' state is emergency-saved through the durable manager so
        a relaunch (or a surviving adopter) restores the exact
        weights+optimizer instead of losing progress."""
        if not getattr(self.ft, "durable_ckpt", True) or \
                getattr(self, "host", None) is None:
            return
        deadline = time.monotonic() + max(0.0, grace) * 0.8
        for role in self.host.roles:
            model = self.host.models.get(role)
            if model is None or model.engine.opt_state is None:
                continue
            if not self.host.leader_of_role.get(role, True):
                continue  # member joins no emergency collectives alone
            if self.spec.multihost and len(
                    self.spec.workers_of_role(role)) > 1:
                # a process-spanning mesh cannot run the collective
                # save with its peers mid-preemption reliably; the
                # periodic durable checkpoint is the recovery point
                continue
            node_name = next(
                (n for n in self.leader_nodes
                 if self.dfg.find(n).role == role
                 and self.dfg.find(n).interface_type
                 == ModelInterfaceType.TRAIN_STEP), None)
            if node_name is None:
                continue
            mgr = self._ckpt_manager(role)
            step = self.host.role_version(role)

            def produce(writer, _role=role, _node=node_name):
                self.host.save_role(_role, _node, path=writer.path)

            rec = mgr.emergency_save(step, produce, deadline=deadline)
            if rec is not None:
                self._refresh_latest_link(role, rec.path)
                logger.warning(
                    "Emergency checkpoint of %s committed at step %d "
                    "(%s).", role, rec.step, rec.manifest_path)

    # ------------------------------------------------------------------
    def _devices_for_group(self, group: list, parallel,
                           device_ids=None) -> list:
        """Mesh devices for a worker group in the joint worker world:
        an equal per-member slice of every group member's local
        devices, ordered group-major so the innermost mesh axes
        (tensor parallel) stay within one process/host (ICI) and outer
        axes (data) cross hosts (DCN) -- the reference's TP-on-NVLink
        placement. ``device_ids`` picks specific local devices per
        member (per-MFC device subsets)."""
        ws = parallel.world_size
        if device_ids is None and ws % len(group) != 0:
            raise ValueError(
                f"layout {parallel} world_size {ws} not divisible by "
                f"its worker group size {len(group)} (group {group}); "
                "every member must own an equal slice of the mesh.")
        per = len(device_ids) if device_ids is not None \
            else ws // len(group)
        if device_ids is not None and per * len(group) != ws:
            raise ValueError(
                f"device_ids {device_ids} x group {group} != "
                f"world_size {ws}.")
        devs = []
        for w in group:
            local = self._devices_by_proc.get(w, [])
            if device_ids is not None:
                if any(i >= len(local) for i in device_ids):
                    raise ValueError(
                        f"worker {w} has {len(local)} devices; "
                        f"device_ids {device_ids} out of range.")
                devs.extend(local[i] for i in device_ids)
                continue
            if len(local) < per:
                raise ValueError(
                    f"worker {w} has {len(local)} devices but the "
                    f"layout needs {per} per member.")
            devs.extend(local[:per])
        return devs

    def _advance_loader(self):
        """One dataloader advance with epoch-wrap + position
        accounting. Shared by the serve path (_handle_fetch_data) and
        the elastic data-owner handoff's position replay, so both walk
        the identical stream."""
        try:
            batch = next(self.dataloader_iter)
        except StopIteration:
            self.dataloader_iter = iter(self.dataloader)
            self._epoch += 1
            batch = next(self.dataloader_iter)
        # Peek whether this batch ends the epoch by position.
        self._step_in_epoch = getattr(self, "_step_in_epoch", -1) + 1
        is_epoch_last = False
        if self._step_in_epoch >= self.steps_per_epoch - 1:
            is_epoch_last = True
            self._step_in_epoch = -1
        return batch, is_epoch_last

    def _handle_fetch_data(self, req: Payload):
        """Load the next dataset batch, keep tensors locally under
        EPOCH-QUALIFIED ids, reply metadata (ids/seqlens/keys) + epoch
        accounting. Qualification makes cross-epoch id reuse safe with
        concurrent batches (a finishing batch's cache clear can no
        longer delete a next-epoch sample) and keeps ids unique inside
        per-sample assemblies spanning the epoch boundary."""
        assert self.owns_data
        batch, is_epoch_last = self._advance_loader()
        # skip ids arrive qualified (the master's consumed list);
        # strip to the raw dataset ids -- skipping only applies to the
        # resumed epoch, which the master clears at its boundary
        batch = data_api.drop_ids(
            batch, set(data_api.raw_ids(req.data.get("skip_ids")
                                        or ())))
        if batch is None:
            self.stream.respond(req, data=dict(
                empty=True, epoch=self._epoch,
                is_epoch_last=is_epoch_last))
            return
        batch = data_api.epoch_qualified(batch, self._epoch)
        self.store.put(batch)
        self.stream.respond(req, data=dict(
            empty=False, meta=batch.meta(), epoch=self._epoch,
            is_epoch_last=is_epoch_last))

    def _assemble_input(self, ids, keys, fetch_plan) -> data_api.SequenceSample:
        """Gather the MFC input from local storage, fetching missing
        keys from their owner workers (the data_transfer pre-hook,
        reference model_worker.py:782-814).

        ``fetch_plan[k]`` is either one owner name (legacy, whole
        batch homed together) or an owner->ids map: a per-sample
        assembly can span dataset batches whose pieces live on
        different workers (elastic reroute mid-window), so the master
        ships an owner-exact plan."""
        # owner -> key -> ids actually missing locally; fetch only the
        # union of missing ids per owner (cached pieces never re-ship)
        missing: Dict[str, Dict[str, list]] = {}
        for k in keys:
            spec = fetch_plan.get(k, self.worker_name)
            by_owner = (spec if isinstance(spec, dict)
                        else {spec: list(ids)})
            for owner, oids in by_owner.items():
                if owner == self.worker_name:
                    continue
                need = [i for i in oids
                        if not self.store.has(i, [k])]
                if need:
                    missing.setdefault(owner, {}).setdefault(
                        k, []).extend(need)
        for owner, by_key in missing.items():
            need_union = sorted({i for v in by_key.values() for i in v},
                                key=lambda x: ids.index(x))
            fetched = self.data_client.fetch(owner, need_union,
                                             list(by_key))
            self.store.put(fetched)
        return self.store.get(ids, list(keys))

    def _handle_mfc(self, req: Payload):
        # fault injection for recovery tests: die ONCE when the poison
        # file exists (removed before raising so the relaunch survives)
        poison = os.environ.get("REALHF_TPU_TEST_POISON")
        if poison and os.path.exists(poison):
            os.remove(poison)
            raise RuntimeError("induced worker failure (test poison)")
        d = req.data
        node_name = d["node"]
        assert node_name in self.my_nodes, (node_name, self.my_nodes)
        node = self.dfg.find(node_name)
        ps = d.get("param_sync")
        if ps and self.host.node_version(node_name) < ps["version"]:
            # Cross-group parameter sync, receiver side: the primary's
            # group was dispatched a param_sync_send alongside this
            # request; fetch the streamed chunk set and install.
            with tracing.span("realloc", mfc=node_name,
                              role=node.role, worker=self.worker_name,
                              weight_version=ps["version"]):
                self._receive_param_sync(node_name, ps)
        keys = [k for k in node.input_keys]
        try:
            with tracing.span("data_fetch", mfc=node_name,
                              worker=self.worker_name,
                              n_ids=len(d["ids"]), n_keys=len(keys)):
                inp = self._assemble_input(d["ids"], keys,
                                           d.get("fetch_plan", {}))
        except Exception as e:  # noqa: BLE001 - a fetch from a
            # just-dead host (SIGKILLed VM: no grace window, tensors
            # gone) must not take THIS worker down with it; reply a
            # structured refusal the master converts into a bounded
            # requeue (the producer recomputes on a survivor first)
            logger.warning(
                "ModelWorker %s: input fetch for %s failed (%r); "
                "replying fetch_failed for requeue.",
                self.worker_name, node_name, e)
            flight.record("fetch_failed", mfc=node_name, error=repr(e))
            metrics.inc("worker_fetch_failed_total", mfc=node_name)
            self.stream.respond(req, data=dict(fetch_failed=repr(e)))
            return
        out = self.host.execute(node_name, inp)
        info = getattr(self.host, "last_exec_info", None)
        if info is not None and node_name in self.cross_group_nodes:
            info = dict(info,
                        param_version=self.host.node_version(node_name))
        elif info is not None and node_name in self.host.adopted_nodes:
            # adopted next to its live primary: fresh every execute
            # via the replica-refresh pre-hook. The adopter does not
            # necessarily HOST the role's primary model (it may be the
            # nominal primary worker of a role whose only node lived
            # on the lost host) -- fall back to the replica's own
            # installed version then.
            info = dict(info, param_version=(
                self.host.role_version(node.role)
                if node.role in self.host.models
                else self.host.node_version(node_name)))
        is_leader = node_name in self.leader_nodes
        if isinstance(out, data_api.SequenceSample):
            # members store the (replicated) outputs too: later MFCs on
            # this worker then hit the local cache instead of refetching
            self.store.put(out)
            if is_leader:
                self.stream.respond(req, data=dict(meta=out.meta(),
                                                   stats=None,
                                                   exec_info=info))
            else:
                self.stream.respond(req, data=dict(member=True,
                                                   exec_info=info))
        elif is_leader:
            self.stream.respond(req, data=dict(meta=None, stats=out,
                                               exec_info=info))
        else:
            self.stream.respond(req, data=dict(member=True,
                                               exec_info=info))

    def _handle_param_sync_send(self, req: Payload):
        """Sender side of the cross-group parameter sync: gather the
        role's primary weights to host (COLLECTIVE over the primary
        group -- the master dispatched this to every member) and
        publish them as a version-qualified CHUNK STREAM on the data
        plane (reference param_realloc sender steps,
        comm/param_realloc.py:279,312: per-shard sends, one sender per
        node -- here per-chunk blobs, one publisher per group).

        The blobs are stamped with the sender's OWN train version at
        gather time (not the master's dispatch-time capture): with
        off-policyness > 0 a later train step may have run before this
        gather, and the label must name the weights actually shipped.
        The previous version's chunk set is retained so a receiver
        group mid-install never has its agreed version overwritten."""
        from realhf_tpu.parallel import param_stream

        role = req.data["role"]
        assert role in self.sync_send_roles, (role, self.sync_send_roles)
        actual = self.host.role_version(role)
        if self._last_published_sync.get(role) == actual:
            # identical weights already streamed: dedupe the collective
            # gather (decision uses only process-local state, so every
            # member of a multi-process sender group agrees).
            self.stream.respond(req, data=dict(published=actual))
            return
        host_params = self.host.gather_role_params(role)
        if self.leader_of_role.get(role, True):
            flat = param_stream.flatten_params(host_params)
            plan = param_stream.plan_chunks(flat)
            prefix = f"__params__/{role}/"
            for i, idxs in enumerate(plan):
                self.store.put_blob(
                    f"{prefix}v{actual}/chunk{i}", actual,
                    param_stream.chunk_payload(flat, idxs))
            self.store.put_blob(f"{prefix}v{actual}/manifest", actual,
                                param_stream.build_manifest(flat, plan))
            self.store.put_blob(f"{prefix}latest", actual, actual)
            # Retention window: a receiver may still be mid-install on
            # a version up to max_head_offpolicyness dispatches behind
            # the newest publish; keep that many generations so its
            # agreed chunk set never vanishes under it.
            window = getattr(self.spec, "max_head_offpolicyness", 0) + 2
            hist = self._published_versions.setdefault(role, [])
            if actual not in hist:
                hist.append(actual)
            del hist[:-window]
            self.store.gc_blobs(prefix + "v", set(hist))
        self._last_published_sync[role] = actual
        self.stream.respond(req, data=dict(published=actual))

    def _receive_param_sync(self, node_name: str, ps: Dict):
        """Receiver side: agree on ONE exact version for the whole
        exec group (the leader picks the sender's latest >= the
        master's floor and publishes "nonce:version" under ONE
        per-node name_resolve key -- reused every dispatch so the
        store stays bounded; members poll until the nonce matches
        their dispatch), then stream the chunks and install
        incrementally."""
        import time as _time

        role, src = ps["role"], ps["src"]
        agree_key = (names.trial_root(constants.experiment_name(),
                                      constants.trial_name())
                     + f"/param_install/{node_name}")
        if node_name in self.leader_nodes:
            version, _ = self.data_client.fetch_blob(
                src, f"__params__/{role}/latest", ps["version"])
            name_resolve.add(agree_key, f"{ps['nonce']}:{version}",
                             replace=True)
        else:
            deadline = _time.monotonic() + 300
            while True:
                try:
                    nonce_s, ver_s = name_resolve.get(agree_key).split(
                        ":", 1)
                    if int(nonce_s) == ps["nonce"]:
                        version = int(ver_s)
                        break
                except name_resolve.NameEntryNotFoundError:
                    pass
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"param_install agreement for {node_name} "
                        f"nonce {ps['nonce']} not published in 300s.")
                _time.sleep(0.05)
        prefix = f"__params__/{role}/v{version}"
        _, manifest = self.data_client.fetch_blob(
            src, f"{prefix}/manifest", version)

        def fetch_chunk(i):
            _, chunk = self.data_client.fetch_blob(
                src, f"{prefix}/chunk{i}", version)
            return chunk

        self.host.install_node_params_streamed(
            node_name, manifest["n_chunks"], fetch_chunk, version,
            eta=ps.get("eta", 1.0))

    def _handle_save(self, req: Payload):
        saved = {}
        step = int(req.data.get("global_step", 0) or 0)
        durable = getattr(self.ft, "durable_ckpt", True)
        for node_name in req.data["nodes"]:
            node = self.dfg.find(node_name)
            writer = self.host.leader_of_role.get(node.role, True)
            if durable and writer and not (
                    self.spec.multihost
                    and len(self.spec.workers_of_role(node.role)) > 1):
                # single-process writer: stage + checksum + atomic
                # commit. (Process-spanning meshes keep the collective
                # legacy path -- every member must walk the identical
                # collective schedule, and only the leader could
                # commit; staged-dir coordination across hosts is
                # future work.)
                saved[node.role] = self._durable_save_role(
                    node.role, node_name, step)
            else:
                saved[node.role] = self.host.save_role(node.role,
                                                       node_name)
        self.stream.respond(req, data=saved)

    # --- elastic adoption (system/elastic.py) -------------------------
    def _handle_adopt_node(self, req: Payload):
        """Take over an MFC from a preempted/lost worker: build a
        replica engine on the degraded layout (weights from the live
        primary when it lives here, else the verified emergency
        checkpoint, else the deterministic init seed) and start
        executing its dispatches."""
        d = req.data
        node_name = d["node"]
        node = self.dfg.find(node_name)
        ckpt = d.get("ckpt")
        if ckpt is None and d.get("try_ckpt", False) \
                and node.role not in self.host.models:
            rec = self._ckpt_manager(node.role).latest_verified()
            ckpt = rec.path if rec is not None else None
        version = self.host.adopt_node(node, d["parallel"],
                                       ckpt_path=ckpt)
        self.my_nodes.add(node_name)
        self.leader_nodes.add(node_name)  # single adopter leads
        if d.get("cross_group", False):
            self.cross_group_nodes.add(node_name)
        else:
            self.cross_group_nodes.discard(node_name)
        self.stream.respond(req, data=dict(adopted=node_name,
                                           version=version))

    def _handle_adopt_data(self, req: Payload):
        """Become the data owner (elastic handoff): the previous owner
        is draining under a preemption notice. Pull every live batch's
        pieces it still homes (its data server answers until the
        graceful exit), then build a dataloader and replay
        ``fetches_done`` advances -- same dataset, same seed, so the
        position replay reproduces the exact sample stream with no
        re-consumption."""
        d = req.data
        src_worker = d["from_worker"]
        timeout = float(d.get("fetch_timeout", 30.0))
        rescued = 0
        try:
            # rescue BEFORE any loader mutation: a failed pull leaves
            # this worker untouched (it stays healthy, the master
            # keeps the old owner and its fatal deadline)
            for group in d.get("rescue") or ():
                fetched = self.data_client.fetch(
                    src_worker, list(group["ids"]), list(group["keys"]),
                    timeout=timeout)
                self.store.put(fetched)
                rescued += len(group["ids"])
        except Exception as e:  # noqa: BLE001 - soft-fail the handoff
            logger.error("Data rescue from draining %s failed: %s",
                         src_worker, e)
            self.stream.respond(req, data=dict(error=repr(e)))
            return
        if not self.owns_data:
            src = self.dfg.sources[0]
            dataset = data_api.make_dataset(
                self.spec.dataset, seed=self.spec.seed, dp_rank=0,
                world_size=1, tokenizer_or_path=self.tokenizer)
            self.dataloader = data_api.PackedDataLoader(
                dataset, batch_size=src.n_seqs, seed=self.spec.seed)
            self.steps_per_epoch = len(self.dataloader)
            self.dataloader_iter = iter(self.dataloader)
            self._epoch = 0
            self._step_in_epoch = -1
            self.owns_data = True
            # an ALREADY-owning worker (re-adoption) keeps its loader:
            # it is positioned correctly; replaying would skip samples
            for _ in range(int(d.get("fetches_done", 0))):
                self._advance_loader()
        logger.warning(
            "ADOPTED data ownership from %s: %d sequences rescued, "
            "loader replayed %d fetches (epoch %d).", src_worker,
            rescued, int(d.get("fetches_done", 0)), self._epoch)
        self.stream.respond(req, data=dict(
            rescued=rescued, epoch=self._epoch))

    def _handle_release_node(self, req: Payload):
        node_name = req.data["node"]
        released = self.host.release_node(node_name)
        if released:
            self.my_nodes.discard(node_name)
            self.leader_nodes.discard(node_name)
            self.cross_group_nodes.discard(node_name)
        self.stream.respond(req, data=dict(released=released))

    def _handle_evaluate(self, req: Payload):
        out = {}
        for node_name in req.data["nodes"]:
            node = self.dfg.find(node_name)
            ev = self.host.evaluate_role(node.role, node_name,
                                         self.eval_dataloader)
            if ev:
                out[node.role] = ev
        self.stream.respond(req, data=out)

    # ------------------------------------------------------------------
    def _drain_requests(self, first: Payload) -> list:
        """Collect every immediately-available request, then move
        param_sync_send requests ahead of queued MFCs (the reference's
        pre-hook priority: handle_all_pre_hooks drains and runs every
        realloc hook before any MFC, model_worker.py:483). Reordering
        is only safe when the sender group is THIS process alone --
        with a multi-process primary group the gather is a collective
        whose relative order against other collectives must match the
        stream order on every member."""
        batch = [first]
        while True:
            try:
                batch.append(self.stream.poll(timeout=0))
            except TimeoutError:
                break
        if len(batch) == 1:
            return batch

        def prio(p: Payload) -> int:
            if p.handle_name == "param_sync_send" and len(
                    self.spec.workers_of_role(p.data["role"])) == 1:
                return 0
            return 1

        return sorted(batch, key=prio)  # stable: FIFO within a class

    def _poll(self) -> worker_base.PollResult:
        try:
            first = self.stream.poll(timeout=0.05)
        except TimeoutError:
            return worker_base.PollResult(0, 0)
        n = 0
        for req in self._drain_requests(first):
            self._handle_request(req)
            n += 1
        return worker_base.PollResult(n, n)

    def _apply_fault(self, req: Payload) -> bool:
        """Execute any injected fault for this request. Returns True
        when the reply must be suppressed (drop_reply)."""
        if self.faults is None:
            return False
        fault = self.faults.on_event(self.worker_name, req.handle_name)
        if fault is None:
            return False
        if fault.kind == "die":
            # emulate a silent machine/process loss: no error reply,
            # no ERROR status, heartbeat just stops -- only the
            # watchdog can notice. The flight recorder still dumps:
            # a real kernel panic leaves no trail, but an injected one
            # should prove the postmortem pipeline end to end.
            logger.error("Fault injection: hard-exiting %s now.",
                         self.worker_name)
            flight.record("fault", fault_kind="die",
                          fault_id=fault.fault_id)
            flight.dump(reason=f"injected die ({fault.fault_id})")
            os._exit(17)
        if fault.kind == "crash":
            flight.record("fault", fault_kind="crash",
                          fault_id=fault.fault_id,
                          handle=req.handle_name)
            raise FaultInjected(
                f"injected crash in {self.worker_name} handling "
                f"{req.handle_name} ({fault.fault_id})")
        if fault.kind == "delay_reply":
            logger.warning("Fault injection: delaying %s reply by "
                           "%.1fs.", req.handle_name, fault.seconds)
            time.sleep(fault.seconds)
            return False
        if fault.kind == "preempt":
            # SIGTERM-equivalent notice: announce, keep executing this
            # request (in-flight work drains within the grace window),
            # exit PREEMPTED when the window closes (worker_base)
            self.notice_preemption(
                grace=fault.seconds or None,
                reason=f"injected fault {fault.fault_id}")
            return False
        return fault.kind == "drop_reply"

    def _handle_request(self, req: Payload):
        handle = req.handle_name
        node = (req.data or {}).get("node") \
            if isinstance(req.data, dict) else None
        flight.record("request", handle=handle, node=node,
                      request_id=req.request_id)
        metrics.inc("worker_requests_total", handle=handle)
        # the master's dispatch span context rides in the payload;
        # everything this request does (realloc, data fetch, compute)
        # nests under this span in the merged timeline
        ctx = tracing.extract(getattr(req, "trace", None))
        try:
            with tracing.span(
                    f"mfc:{node}" if node else f"rpc:{handle}",
                    parent=ctx, handle=handle,
                    worker=self.worker_name):
                self._handle_request_inner(req, handle)
            flight.record("reply", handle=handle, node=node,
                          request_id=req.request_id)
        except Exception as e:  # noqa: BLE001 - report, then re-raise
            logger.error("ModelWorker %s failed handling %s: %s",
                         self.worker_name, handle, e, exc_info=True)
            flight.record("error", handle=handle, node=node,
                          error=repr(e))
            self.stream.reply(Payload(
                handler=self.worker_name, handle_name="error",
                request_id=req.request_id, data=repr(e)))
            raise

    def _handle_request_inner(self, req: Payload, handle: str):
        if self._apply_fault(req):
            # drop_reply: execute nothing and never respond --
            # the master sees pure silence on this request id
            logger.warning("Fault injection: dropping reply for "
                           "%s (%s).", handle, req.request_id)
            return
        if handle == "fetch_data":
            self._handle_fetch_data(req)
        elif handle in ("generate", "inference", "train_step"):
            self._handle_mfc(req)
        elif handle == "param_sync_send":
            self._handle_param_sync_send(req)
        elif handle == "save":
            self._handle_save(req)
        elif handle == "evaluate":
            self._handle_evaluate(req)
        elif handle == "adopt_node":
            self._handle_adopt_node(req)
        elif handle == "adopt_data":
            self._handle_adopt_data(req)
        elif handle == "release_node":
            self._handle_release_node(req)
        elif handle == "clear_data_cache":
            self.store.clear(req.data["ids"])
            self.stream.respond(req, data="ok")
        elif handle == "profiler":
            # master-broadcast jax.profiler toggle (worker_base owns
            # the actual start/stop; same code path as the direct
            # worker command)
            self.stream.respond(
                req, data=self._handle_profiler(**(req.data or {})))
        elif handle == "ping":
            self.stream.respond(req, data="pong")
        else:
            raise ValueError(f"Unknown request {handle}")

    def _exit_hook(self):
        if getattr(self, "data_server", None) is not None:
            self.data_server.stop()
