"""Sequence buffer driving the master's dataflow dispatch.

TPU-native counterpart of reference ``realhf/system/buffer.py``
(AsyncIOSequenceBuffer:117): holds metadata-only SequenceSamples
(tensors stay on the model workers) at PER-SAMPLE granularity. Each
sample tracks its own per-key readiness mask and per-MFC
dispatch/consumption state (the reference's numpy indicator arrays);
each MFC declares its own ``n_seqs`` and the buffer assembles that
MFC's next batch from whichever ready samples exist -- possibly
spanning dataset batches (and, with epoch-qualified ids, epochs) --
instead of waiting for a full dataset batch to complete every
upstream key. This is the lockstep->pipeline transition: generation
can stream samples in at one granularity while training drains them
at another, and per-MFC consumption watermarks feed the master's
off-policyness guard.

Dataset batches remain a first-class grouping for the data-plane
lifecycle (epoch accounting, ``clear_data_cache`` when every sample of
a batch retires, crash-recovery snapshots); the legacy per-batch API
(``ready_mfcs`` / ``amend_batch`` / ...) is kept as a thin layer over
the per-sample state for callers that still think in aligned batches.
"""

import dataclasses
from typing import Dict, Hashable, Iterable, List, Optional, Set, Tuple

from realhf_tpu.api.data import SequenceSample


@dataclasses.dataclass
class SampleState:
    """Per-sample readiness/consumption record (reference buffer.py
    per-sample indicator rows)."""
    sid: Hashable
    seqno: int                 # global arrival order
    batch_id: int              # dataset batch it arrived in
    epoch: int
    is_epoch_last: bool
    meta: SequenceSample       # bs=1 view; keys merge as MFCs complete
    key_owner: Dict[str, str]  # data key -> worker holding the tensors
    #: MFCs that CLAIMED this sample (reserved into an assembly or
    #: legacy-dispatched); completed is a subset once they finish
    dispatched: Set[str] = dataclasses.field(default_factory=set)
    completed: Set[str] = dataclasses.field(default_factory=set)

    def ready_for(self, mfc: str, input_keys: Tuple) -> bool:
        return (mfc not in self.dispatched and mfc not in self.completed
                and all(k in self.meta.keys for k in input_keys))


@dataclasses.dataclass
class Assembly:
    """One dispatch unit of one MFC: its ``n_seqs`` (or a flushed
    tail) drawn FIFO from the ready pool, possibly spanning dataset
    batches."""
    aid: int
    mfc: str
    sids: List[Hashable]
    #: dataset batch of the FIRST sample (step-span / exec-log anchor)
    primary_bid: int
    #: cumulative samples claimed by this MFC up to and including this
    #: assembly -- the consumption watermark the off-policyness guard
    #: compares against the role's train watermark
    end_mark: int
    dispatched: bool = False

    @property
    def ids(self) -> List[Hashable]:
        return list(self.sids)


class BufferEntry:
    """Per-dataset-batch view over the live samples (legacy surface +
    data-plane lifecycle: epoch accounting, rescue plans, cache
    clears)."""

    def __init__(self, batch_id: int, samples: List[SampleState],
                 epoch: int, is_epoch_last: bool):
        self.batch_id = batch_id
        self.samples = samples
        self.epoch = epoch
        self.is_epoch_last = is_epoch_last

    @property
    def ids(self) -> List[Hashable]:
        return [s.sid for s in self.samples]

    @property
    def key_owner(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for s in self.samples:
            out.update(s.key_owner)
        return out

    @property
    def completed(self) -> Set[str]:
        """MFCs completed on EVERY sample of the batch."""
        if not self.samples:
            return set()
        out = set(self.samples[0].completed)
        for s in self.samples[1:]:
            out &= s.completed
        return out

    @property
    def dispatched(self) -> Set[str]:
        """MFCs claimed on every sample of the batch."""
        if not self.samples:
            return set()
        out = set(self.samples[0].dispatched)
        for s in self.samples[1:]:
            out &= s.dispatched
        return out

    @property
    def meta(self) -> SequenceSample:
        """Batch metadata gathered over the keys common to every
        sample (under the legacy aligned API all samples progress
        together, so this is the full key set)."""
        common = set(self.samples[0].meta.keys)
        for s in self.samples[1:]:
            common &= s.meta.keys
        return SequenceSample.gather(
            [s.meta.select(sorted(common)) for s in self.samples])


class SequenceBuffer:
    """Per-sample key-readiness accounting (reference buffer.py:117).

    ``n_seqs_of`` / ``input_keys_of`` / ``producers_of`` enable the
    assembly API the master's dispatch loop uses; buffers constructed
    without them still serve the legacy per-batch API.
    """

    def __init__(self, mfc_names: List[str], capacity: int = 4,
                 n_seqs_of: Optional[Dict[str, int]] = None,
                 input_keys_of: Optional[Dict[str, Tuple]] = None,
                 producers_of: Optional[Dict[str, Tuple]] = None):
        self._mfcs = list(mfc_names)
        self.capacity = capacity           # dataset batches in flight
        self._samples: Dict[Hashable, SampleState] = {}
        self._order: List[Hashable] = []   # arrival order (FIFO)
        self._batches: Dict[int, List[Hashable]] = {}
        self._batch_info: Dict[int, Tuple[int, bool]] = {}
        self._next_id = 0
        self._next_seqno = 0
        self._next_aid = 0
        self._assemblies: Dict[int, Assembly] = {}
        self._n_seqs_of = dict(n_seqs_of or {})
        self._input_keys_of = {m: tuple(v) for m, v in
                               (input_keys_of or {}).items()}
        self._producers_of = {m: tuple(v) for m, v in
                              (producers_of or {}).items()}
        # consumption watermark base: samples COMPLETED by each MFC
        # that have since retired out of the live window
        self._retired_consumed = {m: 0 for m in self._mfcs}
        # claim watermark: samples ever claimed per MFC (monotone;
        # feeds Assembly.end_mark)
        self._claimed = {m: 0 for m in self._mfcs}

    def __len__(self):
        return len(self._batches)

    @property
    def has_space(self) -> bool:
        return len(self._batches) < self.capacity

    @property
    def n_samples(self) -> int:
        return len(self._samples)

    # -- intake ---------------------------------------------------------
    def put_batch(self, meta: SequenceSample, owner: str, epoch: int,
                  is_epoch_last: bool) -> int:
        bid = self._next_id
        self._next_id += 1
        sids = []
        for piece in meta.unpack():
            sid = piece.ids[0]
            self._samples[sid] = SampleState(
                sid=sid, seqno=self._next_seqno, batch_id=bid,
                epoch=epoch, is_epoch_last=is_epoch_last, meta=piece,
                key_owner={k: owner for k in piece.keys})
            self._next_seqno += 1
            self._order.append(sid)
            sids.append(sid)
        self._batches[bid] = sids
        self._batch_info[bid] = (epoch, is_epoch_last)
        return bid

    # -- assembly API (the master's dispatch surface) -------------------
    def ready_count(self, mfc: str) -> int:
        """Unclaimed samples whose input keys for ``mfc`` are all
        present (the ``buffer_ready_samples`` observability surface)."""
        keys = self._input_keys_of.get(mfc, ())
        return sum(1 for sid in self._order
                   if self._samples[sid].ready_for(mfc, keys))

    def _ready_sids(self, mfc: str) -> List[Hashable]:
        keys = self._input_keys_of.get(mfc, ())
        return [sid for sid in self._order
                if self._samples[sid].ready_for(mfc, keys)]

    def _upstream_drained(self, mfc: str) -> bool:
        """True when no producer of ``mfc``'s inputs can still emit
        samples from the live window (every producer completed every
        live sample) -- the gate for flushing a partial tail."""
        for p in self._producers_of.get(mfc, ()):
            for s in self._samples.values():
                if p not in s.completed:
                    return False
        return True

    def ready_assemblies(self, flush: Iterable[str] = ()
                         ) -> List[Assembly]:
        """Undispatched assemblies, FIFO: previously released ones
        first, then new assemblies formed from ready samples. An MFC in
        ``flush`` (the master sets it once fetching is done) may form a
        PARTIAL tail assembly when its upstream is fully drained --
        per-MFC ``n_seqs`` need not divide the dataset."""
        flush = set(flush)
        out = [a for a in sorted(self._assemblies.values(),
                                 key=lambda a: a.aid)
               if not a.dispatched]
        for m in self._mfcs:
            n = self._n_seqs_of.get(m)
            if n is None or n <= 0:
                continue
            while True:
                ready = self._ready_sids(m)
                if len(ready) >= n:
                    take = ready[:n]
                elif ready and m in flush and self._upstream_drained(m):
                    take = ready
                else:
                    break
                self._claimed[m] += len(take)
                asm = Assembly(
                    aid=self._next_aid, mfc=m, sids=list(take),
                    primary_bid=self._samples[take[0]].batch_id,
                    end_mark=self._claimed[m])
                self._next_aid += 1
                for sid in take:
                    self._samples[sid].dispatched.add(m)
                self._assemblies[asm.aid] = asm
                out.append(asm)
                if len(take) < n:
                    break
        return out

    def assembly(self, aid: int) -> Optional[Assembly]:
        return self._assemblies.get(aid)

    def assembly_ready(self, aid: int) -> bool:
        """Every sample of the assembly still holds every input key
        (an upstream invalidation revokes readiness until the producer
        recomputes)."""
        asm = self._assemblies.get(aid)
        if asm is None:
            return False
        keys = self._input_keys_of.get(asm.mfc, ())
        return all(k in self._samples[sid].meta.keys
                   for sid in asm.sids for k in keys
                   if sid in self._samples)

    def assembly_plan(self, aid: int) -> Dict[str, Dict[str, list]]:
        """Per-key fetch plan: key -> {owner -> [sample ids]}. Samples
        of one assembly may be homed on different workers (elastic
        reroute mid-window), so the plan is owner-exact rather than
        one owner per key."""
        asm = self._assemblies[aid]
        plan: Dict[str, Dict[str, list]] = {}
        for sid in asm.sids:
            s = self._samples.get(sid)
            if s is None:
                continue
            for k in self._input_keys_of.get(asm.mfc, ()):
                o = s.key_owner.get(k)
                if o is not None:
                    plan.setdefault(k, {}).setdefault(o, []).append(sid)
        return plan

    def gather_assembly(self, aid: int,
                        keys: Optional[Iterable[str]] = None
                        ) -> SequenceSample:
        """The assembly's input batch gathered from the per-sample
        metas (data rides along when the samples carry it -- the
        inline async runner stores full samples; the distributed
        master stores metadata only and workers fetch tensors over
        the data plane instead)."""
        asm = self._assemblies[aid]
        pieces = [self._samples[sid].meta for sid in asm.sids]
        if keys is not None:
            pieces = [p.select(sorted(set(keys))) for p in pieces]
        return SequenceSample.gather(pieces)

    def plan_owners(self, aid: int) -> Set[str]:
        return {o for owners in self.assembly_plan(aid).values()
                for o in owners}

    def mark_assembly_dispatched(self, aid: int):
        self._assemblies[aid].dispatched = True

    def release_assembly(self, aid: int):
        """Requeue an in-flight assembly (worker lost / fetch failed
        before replying): it is re-offered by ready_assemblies with
        the same samples once dispatchable again."""
        asm = self._assemblies.get(aid)
        if asm is not None:
            asm.dispatched = False

    def complete_assembly(self, aid: int,
                          out_meta: Optional[SequenceSample],
                          owner: str) -> Optional[Assembly]:
        """Record an assembly's completion: per-sample consumption
        watermarks advance, produced keys merge into each sample's
        meta with their owner."""
        asm = self._assemblies.pop(aid, None)
        if asm is None:
            return None
        pieces = {}
        if out_meta is not None:
            for piece in out_meta.unpack():
                pieces[piece.ids[0]] = piece
        for sid in asm.sids:
            s = self._samples.get(sid)
            if s is None:
                continue
            s.completed.add(asm.mfc)
            piece = pieces.get(sid)
            if piece is not None:
                s.meta.update_(piece)
                for k in piece.keys:
                    s.key_owner[k] = owner
        return asm

    # -- per-MFC consumption watermarks ---------------------------------
    def consumed(self, mfc: str) -> int:
        """Samples COMPLETED by ``mfc`` since buffer creation
        (monotone except for host-loss invalidation rollback)."""
        return self._retired_consumed.get(mfc, 0) + sum(
            1 for s in self._samples.values() if mfc in s.completed)

    def claimed(self, mfc: str) -> int:
        """Samples ever claimed by ``mfc`` (completed + in flight +
        reserved)."""
        return self._claimed.get(mfc, 0)

    # -- retirement (data-plane lifecycle) ------------------------------
    def pop_retired(self) -> List[BufferEntry]:
        """Remove and return dataset batches every sample of which has
        been completed by every MFC. Oldest first -- step/epoch
        accounting and cache clears key off these."""
        done = []
        all_mfcs = set(self._mfcs)
        for bid in sorted(self._batches):
            sids = self._batches[bid]
            if all(self._samples[sid].completed >= all_mfcs
                   for sid in sids):
                done.append(bid)
        out = []
        for bid in done:
            sids = self._batches.pop(bid)
            epoch, last = self._batch_info.pop(bid)
            samples = [self._samples.pop(sid) for sid in sids]
            for s in samples:
                for m in s.completed:
                    if m in self._retired_consumed:
                        self._retired_consumed[m] += 1
            keep = set(self._samples)
            self._order = [sid for sid in self._order if sid in keep]
            out.append(BufferEntry(bid, samples, epoch, last))
        return out

    # legacy name
    def pop_finished(self) -> List[BufferEntry]:
        return self.pop_retired()

    # -- fault paths ----------------------------------------------------
    def invalidate_outputs(self, batch_id: int, mfc_name: str, keys):
        """Un-complete an MFC whose output tensors died with their
        owning worker (host loss / SIGKILL): the keys leave the
        affected samples' meta and ownership maps, so consumers stop
        being ready until the producer recomputes. The samples return
        to the unclaimed pool (their completed producer assembly is
        long gone) and re-assemble for recompute -- recomputation, not
        re-consumption: the sample ids were drawn exactly once."""
        sids = self._batches.get(batch_id)
        if sids is None:
            return
        for sid in sids:
            s = self._samples[sid]
            s.completed.discard(mfc_name)
            # unclaim unless a LIVE assembly of this MFC still holds
            # the sample (in-flight recompute already underway)
            held = any(sid in a.sids for a in self._assemblies.values()
                       if a.mfc == mfc_name)
            if not held:
                s.dispatched.discard(mfc_name)
            for k in keys:
                s.key_owner.pop(k, None)
                # SequenceSample invariant: keys == seqlens == shapes
                # == dtypes (== data when present); drop from all views
                s.meta.keys.discard(k)
                s.meta.seqlens.pop(k, None)
                s.meta.trailing_shapes.pop(k, None)
                s.meta.dtypes.pop(k, None)
                if s.meta.data is not None:
                    s.meta.data.pop(k, None)

    def invalidate_worker_outputs(self, workers: Iterable[str],
                                  key_producer: Dict[str, str]
                                  ) -> List[Tuple[int, str, List[str]]]:
        """Sample-granular sweep after a grace-less worker death: every
        key homed on a dead worker is invalidated and its producer
        un-completed on the affected samples. Returns
        ``[(batch_id, mfc, keys)]`` records for attribution."""
        ws = set(workers)
        hits: Dict[Tuple[int, str], Set[str]] = {}
        for s in self._samples.values():
            for k, o in list(s.key_owner.items()):
                if o in ws and k in key_producer:
                    hits.setdefault(
                        (s.batch_id, key_producer[k]), set()).add(k)
        out = []
        for (bid, mfc), keys in sorted(
                hits.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            self.invalidate_outputs(bid, mfc, sorted(keys))
            out.append((bid, mfc, sorted(keys)))
        return out

    def rehome_owner(self, old: str, new: str):
        """Data-owner handoff: every key homed on ``old`` re-homes to
        ``new`` (the successor pulled the pieces already)."""
        for s in self._samples.values():
            for k, o in list(s.key_owner.items()):
                if o == old:
                    s.key_owner[k] = new

    def rescue_plan(self, worker: str) -> List[Dict]:
        """Per-batch (ids, keys) groups still homed on ``worker`` --
        what a data-owner successor must pull before the grace window
        closes. Samples of one batch are grouped by their owned-key
        set (mid-assembly batches can be non-uniform)."""
        out = []
        for bid in sorted(self._batches):
            groups: Dict[Tuple, List[Hashable]] = {}
            for sid in self._batches[bid]:
                s = self._samples[sid]
                keys = tuple(sorted(k for k, o in s.key_owner.items()
                                    if o == worker))
                if keys:
                    groups.setdefault(keys, []).append(sid)
            for keys in sorted(groups):
                out.append(dict(ids=list(groups[keys]),
                                keys=list(keys)))
        return out

    # -- legacy per-batch API (aligned callers + old tests) -------------
    def amend_batch(self, batch_id: int,
                    out_meta: Optional[SequenceSample], owner: str,
                    mfc_name: str):
        """Record an MFC's completion over a whole dataset batch."""
        pieces = {}
        if out_meta is not None:
            for piece in out_meta.unpack():
                pieces[piece.ids[0]] = piece
        for sid in self._batches[batch_id]:
            s = self._samples[sid]
            s.completed.add(mfc_name)
            piece = pieces.get(sid)
            if piece is not None:
                s.meta.update_(piece)
                for k in piece.keys:
                    s.key_owner[k] = owner

    def ready_mfcs(self, input_keys_of: Dict[str, tuple]
                   ) -> List[tuple]:
        """(batch_id, mfc_name) pairs whose inputs are present on
        every sample and which are neither claimed nor completed
        anywhere in the batch. Oldest batch first."""
        out = []
        for bid in sorted(self._batches):
            samples = [self._samples[sid] for sid in self._batches[bid]]
            for m in self._mfcs:
                if all(s.ready_for(m, input_keys_of.get(m, ()))
                       for s in samples):
                    out.append((bid, m))
        return out

    def mark_dispatched(self, batch_id: int, mfc_name: str):
        for sid in self._batches[batch_id]:
            self._samples[sid].dispatched.add(mfc_name)

    def mark_undispatched(self, batch_id: int, mfc_name: str):
        e = self._batches.get(batch_id)
        if e is None:
            return
        for sid in e:
            s = self._samples[sid]
            if mfc_name not in s.completed:
                s.dispatched.discard(mfc_name)

    def get(self, batch_id: int) -> BufferEntry:
        epoch, last = self._batch_info[batch_id]
        return BufferEntry(
            batch_id, [self._samples[sid]
                       for sid in self._batches[batch_id]],
            epoch, last)

    def batch_ids(self) -> List[int]:
        return sorted(self._batches)

    @property
    def next_batch_id(self) -> int:
        """The id the next put_batch will assign (the watermark a
        resumed master restores)."""
        return self._next_id

    # -- crash-recovery snapshot ----------------------------------------
    #: snapshot schema: 1 = per-batch entries (pre-ISSUE-10);
    #: 2 = per-sample records. RecoverInfo v4 carries schema-2 dumps.
    STATE_VERSION = 2

    def state_dict(self) -> Dict:
        """Picklable in-flight snapshot for RecoverInfo. Claim state
        is intentionally NOT saved: after a crash every uncompleted
        MFC must re-assemble and re-dispatch, and the data-plane
        tensors behind these samples died with the workers anyway --
        the snapshot records identity/accounting (ids, per-sample
        completion, epoch position, batch-id watermark), not
        payloads."""
        batches = []
        for bid in sorted(self._batches):
            epoch, last = self._batch_info[bid]
            batches.append(dict(
                batch_id=bid, epoch=epoch, is_epoch_last=last,
                samples=[dict(sid=s.sid, meta=s.meta,
                              key_owner=dict(s.key_owner),
                              completed=sorted(s.completed))
                         for s in (self._samples[sid]
                                   for sid in self._batches[bid])]))
        return {
            "version": self.STATE_VERSION,
            "next_id": self._next_id,
            "batches": batches,
        }

    def load_state_dict(self, state: Dict):
        """Restore a snapshot. Uncompleted MFCs come back unclaimed
        (they re-assemble and re-run); the batch-id counter resumes
        past the watermark so ids stay monotonic across restarts.
        Schema-1 (per-batch ``entries``) dumps are upgraded in place:
        batch-level completion becomes uniform per-sample completion."""
        self._samples = {}
        self._order = []
        self._batches = {}
        self._batch_info = {}
        self._assemblies = {}
        self._next_seqno = 0
        if "entries" in state and "batches" not in state:  # schema 1
            for d in state.get("entries", ()):
                bid = d["batch_id"]
                sids = []
                for piece in d["meta"].unpack():
                    sid = piece.ids[0]
                    self._samples[sid] = SampleState(
                        sid=sid, seqno=self._next_seqno, batch_id=bid,
                        epoch=d["epoch"],
                        is_epoch_last=d["is_epoch_last"], meta=piece,
                        key_owner={k: o for k, o in d["key_owner"]
                                   .items() if k in piece.keys},
                        dispatched=set(d["completed"]),
                        completed=set(d["completed"]))
                    self._next_seqno += 1
                    self._order.append(sid)
                    sids.append(sid)
                self._batches[bid] = sids
                self._batch_info[bid] = (d["epoch"], d["is_epoch_last"])
        else:
            for b in state.get("batches", ()):
                bid = b["batch_id"]
                sids = []
                for sd in b["samples"]:
                    sid = sd["sid"]
                    self._samples[sid] = SampleState(
                        sid=sid, seqno=self._next_seqno, batch_id=bid,
                        epoch=b["epoch"],
                        is_epoch_last=b["is_epoch_last"],
                        meta=sd["meta"],
                        key_owner=dict(sd["key_owner"]),
                        dispatched=set(sd["completed"]),
                        completed=set(sd["completed"]))
                    self._next_seqno += 1
                    self._order.append(sid)
                    sids.append(sid)
                self._batches[bid] = sids
                self._batch_info[bid] = (b["epoch"], b["is_epoch_last"])
        self._next_id = int(state.get("next_id", 0))
