"""Sequence buffer driving the master's dataflow dispatch.

TPU-native counterpart of reference ``realhf/system/buffer.py``
(AsyncIOSequenceBuffer:117): holds metadata-only SequenceSamples
(tensors stay on the model workers), tracks which data keys are ready
for every sample, and hands each MFC its batch once all of the MFC's
input keys exist. Granularity here is one dataset batch (all MFCs of
our experiment graphs share ``n_seqs``); the reference's per-sample
indicator arrays collapse to per-batch key accounting, and the buffer
may hold several batches at once so MFCs of consecutive steps overlap
on disjoint meshes (the decoupled-allocation concurrency that is the
reference's core throughput claim).
"""

import dataclasses
from typing import Dict, List, Optional, Set

from realhf_tpu.api.data import SequenceSample


@dataclasses.dataclass
class BufferEntry:
    batch_id: int
    meta: SequenceSample                  # metadata only (ids/seqlens/keys)
    key_owner: Dict[str, str]             # data key -> worker name holding it
    dispatched: Set[str] = dataclasses.field(default_factory=set)
    completed: Set[str] = dataclasses.field(default_factory=set)
    epoch: int = 0
    is_epoch_last: bool = False

    @property
    def ids(self):
        return self.meta.ids


class SequenceBuffer:
    """Per-batch key-readiness accounting (reference buffer.py:117)."""

    def __init__(self, mfc_names: List[str], capacity: int = 4):
        self._mfcs = list(mfc_names)
        self.capacity = capacity
        self._entries: Dict[int, BufferEntry] = {}
        self._next_id = 0

    def __len__(self):
        return len(self._entries)

    @property
    def has_space(self) -> bool:
        return len(self._entries) < self.capacity

    def put_batch(self, meta: SequenceSample, owner: str, epoch: int,
                  is_epoch_last: bool) -> int:
        bid = self._next_id
        self._next_id += 1
        self._entries[bid] = BufferEntry(
            batch_id=bid, meta=meta,
            key_owner={k: owner for k in meta.keys},
            epoch=epoch, is_epoch_last=is_epoch_last)
        return bid

    def amend_batch(self, batch_id: int, out_meta: Optional[SequenceSample],
                    owner: str, mfc_name: str):
        """Record an MFC's completion (+ its output keys' location)."""
        e = self._entries[batch_id]
        e.completed.add(mfc_name)
        if out_meta is not None:
            e.meta.update_(out_meta)
            for k in out_meta.keys:
                e.key_owner[k] = owner

    def ready_mfcs(self, input_keys_of: Dict[str, tuple]
                   ) -> List[tuple]:
        """(batch_id, mfc_name) pairs whose inputs are all present and
        which are neither dispatched nor completed. Oldest batch first
        (FIFO keeps step ordering for trainable models)."""
        out = []
        for bid in sorted(self._entries):
            e = self._entries[bid]
            for m in self._mfcs:
                if m in e.dispatched or m in e.completed:
                    continue
                if all(k in e.meta.keys for k in input_keys_of[m]):
                    out.append((bid, m))
        return out

    def mark_dispatched(self, batch_id: int, mfc_name: str):
        self._entries[batch_id].dispatched.add(mfc_name)

    def mark_undispatched(self, batch_id: int, mfc_name: str):
        """Requeue an in-flight MFC (its worker was lost before
        replying): ready_mfcs offers it again once its group is
        eligible. No-op for completed MFCs."""
        e = self._entries.get(batch_id)
        if e is not None and mfc_name not in e.completed:
            e.dispatched.discard(mfc_name)

    def invalidate_outputs(self, batch_id: int, mfc_name: str, keys):
        """Un-complete an MFC whose output tensors died with their
        owning worker (host loss / SIGKILL -- no grace window to hand
        them off): the keys leave the batch meta and ownership map, so
        consumers stop being ready until the producer recomputes, and
        ready_mfcs offers the producer again. Recomputation, not
        re-consumption: the batch's sample ids were drawn exactly
        once."""
        e = self._entries.get(batch_id)
        if e is None:
            return
        e.completed.discard(mfc_name)
        e.dispatched.discard(mfc_name)
        for k in keys:
            e.key_owner.pop(k, None)
            # SequenceSample invariant: keys == seqlens == shapes ==
            # dtypes (== data when present); remove from all views
            e.meta.keys.discard(k)
            e.meta.seqlens.pop(k, None)
            e.meta.trailing_shapes.pop(k, None)
            e.meta.dtypes.pop(k, None)
            if e.meta.data is not None:
                e.meta.data.pop(k, None)

    def get(self, batch_id: int) -> BufferEntry:
        return self._entries[batch_id]

    def batch_ids(self) -> List[int]:
        return sorted(self._entries)

    @property
    def next_batch_id(self) -> int:
        """The id the next put_batch will assign (the watermark a
        resumed master restores)."""
        return self._next_id

    # -- crash-recovery snapshot ----------------------------------------
    def state_dict(self) -> Dict:
        """Picklable in-flight snapshot for RecoverInfo. Dispatch
        state is intentionally NOT saved: after a crash every
        uncompleted MFC must re-dispatch, and the data-plane tensors
        behind these entries died with the workers anyway -- the
        snapshot records identity/accounting (ids, completion, epoch
        position, batch-id watermark), not payloads."""
        return {
            "next_id": self._next_id,
            "entries": [
                dict(batch_id=e.batch_id, meta=e.meta,
                     key_owner=dict(e.key_owner),
                     completed=sorted(e.completed), epoch=e.epoch,
                     is_epoch_last=e.is_epoch_last)
                for bid, e in sorted(self._entries.items())
            ],
        }

    def load_state_dict(self, state: Dict):
        """Restore a snapshot. Uncompleted MFCs come back
        undispatched (they re-run); the batch-id counter resumes past
        the watermark so ids stay monotonic across restarts."""
        self._entries = {}
        for d in state.get("entries", ()):
            self._entries[d["batch_id"]] = BufferEntry(
                batch_id=d["batch_id"], meta=d["meta"],
                key_owner=dict(d["key_owner"]),
                dispatched=set(d["completed"]),
                completed=set(d["completed"]),
                epoch=d["epoch"], is_epoch_last=d["is_epoch_last"])
        self._next_id = int(state.get("next_id", 0))

    def pop_finished(self) -> List[BufferEntry]:
        """Remove and return entries every MFC has completed."""
        done = [e for e in self._entries.values()
                if e.completed >= set(self._mfcs)]
        for e in done:
            del self._entries[e.batch_id]
        return done
