"""Job schedulers: launch and supervise worker processes.

Parity with reference ``realhf/scheduler/client.py`` (SchedulerClient
ABC :44-111) + ``scheduler/local/client.py`` (subprocess spawner). The
SLURM backend (reference ``scheduler/slurm/``) is a planned addition
for GPU-style clusters; TPU pods typically launch one process per host
via their own orchestrator (GKE/xmanager), for which this local client
doubles as the per-host bootstrapper.
"""

import dataclasses
import enum
import os
import shlex
import signal
import subprocess
import time
from typing import Dict, List, Optional

from realhf_tpu.base import logging
from realhf_tpu.base.retry import RetryPolicy, retry_call

logger = logging.getLogger("scheduler")


class JobState(str, enum.Enum):
    NOT_FOUND = "NOT_FOUND"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"
    # watchdog verdict: the process may still exist but its heartbeat
    # expired (hung or on a dead host) -- treated like FAILED by the
    # launcher's auto-recover loop
    LOST = "LOST"


@dataclasses.dataclass
class JobInfo:
    name: str
    state: JobState
    pid: Optional[int] = None
    returncode: Optional[int] = None


class JobException(Exception):

    def __init__(self, name: str, state: JobState):
        super().__init__(f"Job {name} ended in state {state}.")
        self.name = name
        self.state = state


class SchedulerClient:

    def submit(self, name: str, cmd: List[str],
               env: Optional[Dict[str, str]] = None):
        raise NotImplementedError()

    def submit_array(self, name: str, cmd_template: List[str], count: int,
                     env: Optional[Dict[str, str]] = None):
        for i in range(count):
            cmd = [c.format(index=i) for c in cmd_template]
            self.submit(f"{name}/{i}", cmd, env)

    def stop_all(self, grace: float = 10.0):
        raise NotImplementedError()

    def find(self, name: str) -> JobInfo:
        raise NotImplementedError()

    def wait(self, timeout: Optional[float] = None,
             check_status: bool = True,
             remove_failed: bool = False) -> None:
        raise NotImplementedError()


class LocalSchedulerClient(SchedulerClient):
    """Subprocess scheduler (reference local/client.py:66). On a TPU
    host, each worker process sees the full local chip fleet; device
    isolation happens through per-model meshes, not env masking (the
    reference instead isolates via CUDA_VISIBLE_DEVICES,
    gpu_utils.py:64)."""

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}
        self._specs: Dict[str, tuple] = {}  # name -> (cmd, env)

    def submit(self, name, cmd, env=None):
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        logger.info("Launching job %s: %s", name, " ".join(cmd))
        self._specs[name] = (list(cmd), dict(env or {}))
        self._procs[name] = subprocess.Popen(
            cmd, env=full_env, start_new_session=True)

    def resubmit(self, name) -> JobInfo:
        """Relaunch a dead job under the same name (single-worker
        recovery primitive: an external supervisor can restart just
        the lost worker while the rest of the fleet keeps running).
        Refuses while the old process is still alive."""
        if name not in self._specs:
            raise KeyError(f"Job {name} was never submitted.")
        p = self._procs.get(name)
        if p is not None and p.poll() is None:
            raise RuntimeError(f"Job {name} is still running "
                               f"(pid {p.pid}); not resubmitting.")
        cmd, env = self._specs[name]
        self.submit(name, cmd, env)
        return self.find(name)

    def find(self, name) -> JobInfo:
        p = self._procs.get(name)
        if p is None:
            return JobInfo(name, JobState.NOT_FOUND)
        rc = p.poll()
        if rc is None:
            return JobInfo(name, JobState.RUNNING, pid=p.pid)
        state = JobState.COMPLETED if rc == 0 else JobState.FAILED
        return JobInfo(name, state, pid=p.pid, returncode=rc)

    def stop(self, name: str, grace: float = 10.0):
        """Stop ONE job (autoscale scale-down reaping): SIGTERM its
        process group, escalate to SIGKILL after ``grace`` seconds.
        The job stays findable (COMPLETED/FAILED) until forgotten."""
        p = self._procs.get(name)
        if p is None or p.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            p.wait(timeout=max(0.1, grace))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass

    def stop_all(self, grace: float = 10.0):
        """SIGTERM every job, escalate to SIGKILL after ``grace``
        seconds. Serving deployments pass a longer grace so a
        GenServerWorker can drain its in-flight sequences
        (ServingSpec.drain_timeout_secs) before the hard kill."""
        for name, p in self._procs.items():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + grace
        try:
            for name, p in self._procs.items():
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass  # exited during the grace period
        finally:
            self._procs.clear()

    def wait(self, timeout=None, check_status=True, remove_failed=False):
        """Block until all jobs finish; raise JobException on the first
        failure (triggers the launcher's recover path, reference
        apps/main.py:195-230)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            states = {n: self.find(n) for n in list(self._procs)}
            if check_status:
                for n, info in states.items():
                    if info.state == JobState.FAILED:
                        if remove_failed:
                            del self._procs[n]
                        raise JobException(n, info.state)
            if all(i.state in (JobState.COMPLETED, JobState.FAILED,
                               JobState.NOT_FOUND)
                   for i in states.values()):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("Scheduler wait timed out.")
            time.sleep(0.2)


class SlurmSchedulerClient(SchedulerClient):
    """SLURM backend (reference ``scheduler/slurm/client.py:25`` +
    ``slurm/utils.py:167`` SlurmLaunchInfo): each job becomes one
    sbatch script; states are polled through ``squeue``/``sacct``.

    TPU pods are usually launched by GKE/xmanager instead, but
    GPU-cluster parity demands sbatch support; script generation is
    unit-tested without a slurm installation by injecting ``runner``.
    """

    #: SLURM state -> JobState (reference slurm/client.py STATUS_MAP)
    STATE_MAP = {
        "PENDING": JobState.PENDING, "CONFIGURING": JobState.PENDING,
        "RUNNING": JobState.RUNNING, "COMPLETING": JobState.RUNNING,
        "COMPLETED": JobState.COMPLETED,
        "FAILED": JobState.FAILED, "OUT_OF_MEMORY": JobState.FAILED,
        "NODE_FAIL": JobState.FAILED, "TIMEOUT": JobState.FAILED,
        "CANCELLED": JobState.CANCELLED, "PREEMPTED": JobState.CANCELLED,
    }

    def __init__(self, experiment_name: str = "exp",
                 trial_name: str = "trial",
                 partition: str = "", account: str = "",
                 cpus_per_task: int = 8, mem_gb: int = 32,
                 container_image: str = "",
                 script_dir: Optional[str] = None, runner=None,
                 submit_retry: Optional[RetryPolicy] = None):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.partition = partition
        self.account = account
        self.cpus_per_task = cpus_per_task
        self.mem_gb = mem_gb
        self.container_image = container_image
        self.script_dir = script_dir or os.path.join(
            os.path.expanduser("~"), ".cache", "realhf_tpu", "slurm")
        # injectable for tests: (argv) -> stdout string
        self._run = runner or (lambda argv: subprocess.check_output(
            argv, text=True))
        # sbatch hits transient slurmctld hiccups under load; retry
        # with backoff instead of failing the whole launch
        self._submit_retry = submit_retry or RetryPolicy(
            max_attempts=3, base_delay=1.0, max_delay=15.0)
        self._slurm_ids: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def render_sbatch_script(self, name: str, cmd: List[str],
                             env: Optional[Dict[str, str]] = None,
                             n_tasks: int = 1) -> str:
        """One sbatch script per job (reference builds an srun
        multiprog file per worker group, slurm/utils.py:357-473)."""
        job = f"{self.experiment_name}_{self.trial_name}_{name}" \
            .replace("/", "-")
        lines = [
            "#!/bin/bash",
            f"#SBATCH --job-name={job}",
            f"#SBATCH --ntasks={n_tasks}",
            f"#SBATCH --cpus-per-task={self.cpus_per_task}",
            f"#SBATCH --mem={self.mem_gb}G",
            "#SBATCH --output=%x_%j.out",
        ]
        if self.partition:
            lines.append(f"#SBATCH --partition={self.partition}")
        if self.account:
            lines.append(f"#SBATCH --account={self.account}")
        if self.container_image:
            lines.append(f"#SBATCH --container-image="
                         f"{self.container_image}")
        for k, v in sorted((env or {}).items()):
            lines.append(f"export {k}={shlex.quote(str(v))}")
        quoted = " ".join(shlex.quote(c) for c in cmd)
        lines.append(f"srun --ntasks={n_tasks} --kill-on-bad-exit=1 "
                     f"{quoted}")
        return "\n".join(lines) + "\n"

    def submit(self, name, cmd, env=None):
        os.makedirs(self.script_dir, exist_ok=True)
        script = self.render_sbatch_script(name, cmd, env)
        path = os.path.join(self.script_dir,
                            name.replace("/", "-") + ".sbatch")
        with open(path, "w") as f:
            f.write(script)
        out = retry_call(
            lambda: self._run(["sbatch", "--parsable", path]),
            self._submit_retry,
            retry_on=(subprocess.SubprocessError, OSError),
            what=f"sbatch {name}")
        self._slurm_ids[name] = out.strip().split(";")[0]
        logger.info("Submitted slurm job %s (id %s).", name,
                    self._slurm_ids[name])

    def find(self, name) -> JobInfo:
        sid = self._slurm_ids.get(name)
        if sid is None:
            return JobInfo(name, JobState.NOT_FOUND)
        # squeue errors on jobs past MinJobAge; sacct may be absent --
        # degrade to NOT_FOUND rather than crash the monitor loop
        try:
            out = self._run(["squeue", "-j", sid, "-h", "-o",
                             "%T"]).strip()
        except Exception:  # noqa: BLE001
            out = ""
        if not out:
            try:
                out = self._run(["sacct", "-j", sid, "-n", "-X", "-o",
                                 "State"]).strip()
            except Exception:  # noqa: BLE001
                out = ""
        state = self.STATE_MAP.get(out.split()[0].rstrip("+")
                                   if out else "",
                                   JobState.NOT_FOUND)
        return JobInfo(name, state)

    def stop_all(self, grace: float = 10.0):
        for name, sid in self._slurm_ids.items():
            try:
                self._run(["scancel", sid])
            except Exception as e:  # noqa: BLE001 - best effort
                logger.warning("scancel %s (%s): %s", sid, name, e)
        self._slurm_ids.clear()

    def wait(self, timeout=None, check_status=True, remove_failed=False):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            states = {n: self.find(n) for n in list(self._slurm_ids)}
            if check_status:
                for n, info in states.items():
                    if info.state == JobState.FAILED:
                        if remove_failed:
                            del self._slurm_ids[n]
                        raise JobException(n, info.state)
            if all(i.state in (JobState.COMPLETED, JobState.FAILED,
                               JobState.CANCELLED, JobState.NOT_FOUND)
                   for i in states.values()):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("Scheduler wait timed out.")
            time.sleep(2.0)


def make_scheduler(mode: str = "local", **kwargs) -> SchedulerClient:
    if mode == "local":
        return LocalSchedulerClient()
    if mode == "slurm":
        return SlurmSchedulerClient(**kwargs)
    if mode == "multihost_local":
        # emulated N-host pod on one box (system/pod.py): per-host env
        # namespaces + process groups, kill_host() for failure drills
        from realhf_tpu.system.pod import MultiHostLocalScheduler
        return MultiHostLocalScheduler(**kwargs)
    raise NotImplementedError(f"Scheduler mode {mode}")
