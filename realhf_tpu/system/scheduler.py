"""Job schedulers: launch and supervise worker processes.

Parity with reference ``realhf/scheduler/client.py`` (SchedulerClient
ABC :44-111) + ``scheduler/local/client.py`` (subprocess spawner). The
SLURM backend (reference ``scheduler/slurm/``) is a planned addition
for GPU-style clusters; TPU pods typically launch one process per host
via their own orchestrator (GKE/xmanager), for which this local client
doubles as the per-host bootstrapper.
"""

import dataclasses
import enum
import os
import signal
import subprocess
import time
from typing import Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("scheduler")


class JobState(str, enum.Enum):
    NOT_FOUND = "NOT_FOUND"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"


@dataclasses.dataclass
class JobInfo:
    name: str
    state: JobState
    pid: Optional[int] = None
    returncode: Optional[int] = None


class JobException(Exception):

    def __init__(self, name: str, state: JobState):
        super().__init__(f"Job {name} ended in state {state}.")
        self.name = name
        self.state = state


class SchedulerClient:

    def submit(self, name: str, cmd: List[str],
               env: Optional[Dict[str, str]] = None):
        raise NotImplementedError()

    def submit_array(self, name: str, cmd_template: List[str], count: int,
                     env: Optional[Dict[str, str]] = None):
        for i in range(count):
            cmd = [c.format(index=i) for c in cmd_template]
            self.submit(f"{name}/{i}", cmd, env)

    def stop_all(self):
        raise NotImplementedError()

    def find(self, name: str) -> JobInfo:
        raise NotImplementedError()

    def wait(self, timeout: Optional[float] = None,
             check_status: bool = True,
             remove_failed: bool = False) -> None:
        raise NotImplementedError()


class LocalSchedulerClient(SchedulerClient):
    """Subprocess scheduler (reference local/client.py:66). On a TPU
    host, each worker process sees the full local chip fleet; device
    isolation happens through per-model meshes, not env masking (the
    reference instead isolates via CUDA_VISIBLE_DEVICES,
    gpu_utils.py:64)."""

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}

    def submit(self, name, cmd, env=None):
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        logger.info("Launching job %s: %s", name, " ".join(cmd))
        self._procs[name] = subprocess.Popen(
            cmd, env=full_env, start_new_session=True)

    def find(self, name) -> JobInfo:
        p = self._procs.get(name)
        if p is None:
            return JobInfo(name, JobState.NOT_FOUND)
        rc = p.poll()
        if rc is None:
            return JobInfo(name, JobState.RUNNING, pid=p.pid)
        state = JobState.COMPLETED if rc == 0 else JobState.FAILED
        return JobInfo(name, state, pid=p.pid, returncode=rc)

    def stop_all(self):
        for name, p in self._procs.items():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 10
        try:
            for name, p in self._procs.items():
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass  # exited during the grace period
        finally:
            self._procs.clear()

    def wait(self, timeout=None, check_status=True, remove_failed=False):
        """Block until all jobs finish; raise JobException on the first
        failure (triggers the launcher's recover path, reference
        apps/main.py:195-230)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            states = {n: self.find(n) for n in list(self._procs)}
            if check_status:
                for n, info in states.items():
                    if info.state == JobState.FAILED:
                        if remove_failed:
                            del self._procs[n]
                        raise JobException(n, info.state)
            if all(i.state in (JobState.COMPLETED, JobState.FAILED,
                               JobState.NOT_FOUND)
                   for i in states.values()):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("Scheduler wait timed out.")
            time.sleep(0.2)


def make_scheduler(mode: str = "local") -> SchedulerClient:
    if mode == "local":
        return LocalSchedulerClient()
    raise NotImplementedError(f"Scheduler mode {mode}")
