"""Pod-scale controller path: manifests, host emulation, bring-up.

TPU pods are launched one process per host by an external orchestrator
(GKE / xmanager), not by a long-lived Ray controller (reference
``controller.py:398``); the TPU-idiomatic controller is therefore a
*manifest generator* plus a thin supervisor:

* :func:`build_pod_manifest` produces the deterministic per-host
  launch manifest (JSON; one :class:`HostSpec` per host with its
  worker set, env namespace -- ``REALHF_TPU_HOST_ID`` above all --
  and Prometheus scrape port). ``python -m realhf_tpu.apps.main
  pod-manifest`` / ``scripts/gen_pod_manifest.py`` expose it.
* :class:`MultiHostLocalScheduler` emulates N hosts on one box: each
  submitted worker is namespaced into its host's env and process
  group, and :meth:`MultiHostLocalScheduler.kill_host` SIGKILLs every
  process of one emulated host at once -- the exact failure shape of
  a TPU VM preemption -- so the whole controller path is CI-testable
  without a pod.
* :class:`PodController` supervises bring-up over ANY
  ``SchedulerClient``: submission with retry/backoff, a bring-up
  deadline with host-attributed errors, and the per-host obs
  artifacts (Prometheus ``file_sd`` scrape targets) at teardown.

Host identity threads through the runtime from here: the scheduler
injects ``REALHF_TPU_HOST_ID``, ``WorkerServer`` republishes it under
``names.worker_host``, and the watchdog/master aggregate losses per
host (``HOST_LOST``; see ``system/watchdog.py``).
"""

import dataclasses
import json
import os
import signal
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from realhf_tpu.base import logging, name_resolve, names
from realhf_tpu.base.cluster import HOST_ID_ENV
from realhf_tpu.base.retry import RetryPolicy, retry_call
from realhf_tpu.system.scheduler import (
    JobState,
    LocalSchedulerClient,
    SchedulerClient,
)

logger = logging.getLogger("pod")

MANIFEST_VERSION = 1
DEFAULT_SCRAPE_BASE_PORT = 9100
SCRAPE_TARGETS_NAME = "scrape_targets.json"


def host_name(index: int) -> str:
    return f"host-{index:04d}"


def default_host_assignment(workers: Sequence[str], n_hosts: int
                            ) -> Dict[str, str]:
    """Block-contiguous worker->host map, the pod-slice shape (workers
    of one host are consecutive, like jax process indices on a slice).
    ``master_worker``/``router`` processes are controller-adjacent and
    pinned to host 0; every other worker type is split independently
    into contiguous blocks. Deterministic in the worker list."""
    if n_hosts <= 0:
        raise ValueError(f"n_hosts must be positive, got {n_hosts}")
    by_type: Dict[str, List[str]] = {}
    out: Dict[str, str] = {}
    for w in workers:
        wtype = w.split("/", 1)[0]
        if wtype in ("master_worker", "router"):
            out[w] = host_name(0)
        else:
            by_type.setdefault(wtype, []).append(w)

    def _index(w: str) -> int:
        tail = w.rsplit("/", 1)[-1]
        return int(tail) if tail.isdigit() else 0

    for wtype in sorted(by_type):
        ws = sorted(by_type[wtype], key=_index)
        n = len(ws)
        for i, w in enumerate(ws):
            out[w] = host_name(min(i * n_hosts // n, n_hosts - 1))
    return out


@dataclasses.dataclass
class HostSpec:
    """One pod host: its worker set and per-host env namespace."""
    host_id: str
    index: int
    workers: List[str]
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    address: str = "127.0.0.1"
    scrape_port: int = DEFAULT_SCRAPE_BASE_PORT

    def to_dict(self) -> Dict:
        return dict(host_id=self.host_id, index=self.index,
                    workers=list(self.workers),
                    env={k: self.env[k] for k in sorted(self.env)},
                    address=self.address, scrape_port=self.scrape_port)


@dataclasses.dataclass
class PodManifest:
    """The deterministic launch plan: who runs where, with what env.

    ``to_json`` is byte-stable for identical inputs (sorted keys, no
    timestamps) so manifests can be diffed and committed; round-trips
    through :meth:`from_json` and the
    :class:`MultiHostLocalScheduler`."""
    experiment_name: str
    trial_name: str
    hosts: List[HostSpec]
    n_chips_per_host: Optional[int] = None
    version: int = MANIFEST_VERSION

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    @property
    def workers(self) -> List[str]:
        return [w for h in self.hosts for w in h.workers]

    def host_of(self, worker: str) -> Optional[str]:
        for h in self.hosts:
            if worker in h.workers:
                return h.host_id
        return None

    def host(self, host_id: str) -> Optional[HostSpec]:
        for h in self.hosts:
            if h.host_id == host_id:
                return h
        return None

    def host_env(self, host_id: str) -> Dict[str, str]:
        h = self.host(host_id)
        return dict(h.env) if h is not None else {}

    def to_dict(self) -> Dict:
        d = dict(version=self.version,
                 experiment_name=self.experiment_name,
                 trial_name=self.trial_name,
                 n_hosts=self.n_hosts,
                 hosts=[h.to_dict() for h in self.hosts])
        if self.n_chips_per_host is not None:
            d["n_chips_per_host"] = self.n_chips_per_host
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=True) \
            + "\n"

    @classmethod
    def from_dict(cls, d: Dict) -> "PodManifest":
        hosts = [HostSpec(host_id=h["host_id"], index=h["index"],
                          workers=list(h["workers"]),
                          env=dict(h.get("env") or {}),
                          address=h.get("address", "127.0.0.1"),
                          scrape_port=h.get("scrape_port",
                                            DEFAULT_SCRAPE_BASE_PORT))
                 for h in d["hosts"]]
        return cls(experiment_name=d["experiment_name"],
                   trial_name=d["trial_name"], hosts=hosts,
                   n_chips_per_host=d.get("n_chips_per_host"),
                   version=d.get("version", MANIFEST_VERSION))

    @classmethod
    def from_json(cls, text: str) -> "PodManifest":
        return cls.from_dict(json.loads(text))


def build_pod_manifest(experiment_name: str, trial_name: str, *,
                       n_hosts: int, n_model_workers: int = 0,
                       workers: Optional[Sequence[str]] = None,
                       include_master: bool = True,
                       assignment: Optional[Dict[str, str]] = None,
                       n_chips_per_host: Optional[int] = None,
                       base_scrape_port: int = DEFAULT_SCRAPE_BASE_PORT,
                       extra_env: Optional[Dict[str, str]] = None
                       ) -> PodManifest:
    """The pod launch plan for a training fleet (or an explicit
    ``workers`` list). Assignment defaults to
    :func:`default_host_assignment`; ``assignment`` overrides it per
    worker (hosts named ``host-0000`` ... ``host-{n-1:04d}``). Every
    host's env carries ``REALHF_TPU_HOST_ID`` (and, when
    ``n_chips_per_host`` is given, ``REALHF_TPU_LOCAL_DEVICE_COUNT``
    so the elastic planner sizes degraded meshes to the host)."""
    if workers is None:
        workers = [f"model_worker/{i}" for i in range(n_model_workers)]
        if include_master:
            workers = workers + ["master_worker/0"]
    assign = default_host_assignment(workers, n_hosts)
    if assignment:
        unknown = sorted(set(assignment) - set(workers))
        if unknown:
            raise ValueError(
                f"assignment names unknown workers: {unknown}")
        assign.update(assignment)
    hosts: List[HostSpec] = []
    for i in range(n_hosts):
        hid = host_name(i)
        env = {HOST_ID_ENV: hid}
        if n_chips_per_host is not None:
            env["REALHF_TPU_LOCAL_DEVICE_COUNT"] = str(n_chips_per_host)
        env.update(extra_env or {})
        hosts.append(HostSpec(
            host_id=hid, index=i,
            workers=sorted((w for w, h in assign.items() if h == hid),
                           key=lambda w: (w.split("/", 1)[0],
                                          int(w.rsplit("/", 1)[-1])
                                          if w.rsplit("/", 1)[-1].isdigit()
                                          else 0)),
            env=env, scrape_port=base_scrape_port + i))
    return PodManifest(experiment_name=experiment_name,
                       trial_name=trial_name, hosts=hosts,
                       n_chips_per_host=n_chips_per_host)


def scrape_targets(hosts: Sequence[HostSpec],
                   labels: Optional[Dict[str, str]] = None) -> List[Dict]:
    """Prometheus ``file_sd_configs`` entries, one per host -- the
    MANIFEST view (planned ports). Prefer
    :func:`resolve_scrape_targets` for a running trial: workers
    publish the ports they actually bound."""
    out = []
    for h in sorted(hosts, key=lambda h: h.host_id):
        lab = dict(host=h.host_id)
        lab.update(labels or {})
        out.append(dict(
            targets=[f"{h.address}:{h.scrape_port}"],
            labels={k: lab[k] for k in sorted(lab)}))
    return out


def resolve_scrape_targets(experiment_name: str, trial_name: str,
                           labels: Optional[Dict[str, str]] = None
                           ) -> List[Dict]:
    """LIVE per-worker Prometheus ``file_sd_configs`` entries resolved
    from the telemetry registry: every worker's ``TelemetryServer``
    (obs/http.py) publishes the ``host:port`` it actually bound under
    ``names.telemetry``, so -- unlike the manifest's planned per-host
    ports -- a GET against each target here reaches a process that
    answers. Each entry carries a ``worker`` label (and ``host`` when
    the worker published its host domain). Never raises; a worker
    that vanished between listing and reading is skipped."""
    root = names.telemetry_root(experiment_name, trial_name)
    try:
        keys = name_resolve.find_subtree(root) or []
    except Exception:  # noqa: BLE001 - discovery is best effort
        return []
    out: List[Dict] = []
    for key in sorted(keys):
        worker = key[len(root):] if key.startswith(root) \
            else key.rsplit("/telemetry/", 1)[-1]
        try:
            address = str(name_resolve.get(key))
        except Exception:  # noqa: BLE001 - raced a departing worker
            continue
        lab = dict(worker=worker)
        try:
            lab["host"] = str(name_resolve.get(names.worker_host(
                experiment_name, trial_name, worker)))
        except Exception:  # noqa: BLE001 - single-host runs publish
            # no host domain
            pass
        lab.update(labels or {})
        out.append(dict(targets=[address],
                        labels={k: lab[k] for k in sorted(lab)}))
    return out


def write_target_entries(entries: Sequence[Dict], path: str) -> str:
    """Atomically write ``file_sd_configs`` entries to ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(list(entries), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def write_scrape_targets(hosts: Sequence[HostSpec], path: str,
                         labels: Optional[Dict[str, str]] = None) -> str:
    """Write the per-host scrape-target file (Prometheus file-based
    service discovery) so the obs stack deploys alongside the pod."""
    return write_target_entries(scrape_targets(hosts, labels), path)


# ----------------------------------------------------------------------
class MultiHostLocalScheduler(LocalSchedulerClient):
    """Emulate an N-host pod with subprocesses on one box.

    Every submitted job is assigned a host (manifest > explicit
    ``assign`` map > index-modulo fallback), launched in its own
    process group with the host's env namespace merged in
    (``REALHF_TPU_HOST_ID`` above all), and tracked per host so
    :meth:`kill_host` can take the whole emulated VM down in one shot
    -- the failure granularity TPU pods actually exhibit.
    ``resubmit`` (the launcher's elastic-rejoin primitive) keeps the
    worker on its original host."""

    def __init__(self, n_hosts: int = 2,
                 manifest: Optional[PodManifest] = None,
                 assign: Optional[Dict[str, str]] = None):
        super().__init__()
        if manifest is not None:
            n_hosts = manifest.n_hosts
        if n_hosts <= 0:
            raise ValueError(f"n_hosts must be positive, got {n_hosts}")
        self.n_hosts = n_hosts
        self.manifest = manifest
        self._assign = dict(assign or {})
        self._host_jobs: Dict[str, set] = {}

    # -- host mapping ---------------------------------------------------
    def host_of(self, name: str) -> str:
        if name in self._assign:
            return self._assign[name]
        if self.manifest is not None:
            h = self.manifest.host_of(name)
            if h is not None:
                self._assign[name] = h
                return h
        # count-free fallback: controller-adjacent workers on host 0,
        # the rest round-robin by index (a manifest gives the
        # pod-idiomatic contiguous blocks instead)
        wtype, _, tail = name.partition("/")
        if wtype in ("master_worker", "router") or not tail.isdigit():
            h = host_name(0)
        else:
            h = host_name(int(tail) % self.n_hosts)
        self._assign[name] = h
        return h

    def hosts(self) -> List[str]:
        known = set(self._host_jobs) | set(self._assign.values())
        if self.manifest is not None:
            known |= {h.host_id for h in self.manifest.hosts}
        else:
            known |= {host_name(i) for i in range(self.n_hosts)}
        return sorted(known)

    def workers_on(self, host: str) -> List[str]:
        return sorted(self._host_jobs.get(host, ()))

    # -- scheduling -----------------------------------------------------
    def submit(self, name, cmd, env=None):
        host = self.host_of(name)
        merged = dict(env or {})
        if self.manifest is not None:
            merged.update(self.manifest.host_env(host))
        merged[HOST_ID_ENV] = host
        self._host_jobs.setdefault(host, set()).add(name)
        super().submit(name, cmd, merged)

    def kill_host(self, host: str,
                  sig: int = signal.SIGKILL) -> List[str]:
        """Take one emulated host down: signal every live process
        group belonging to it at once (default SIGKILL -- a VM
        preemption gives no grace). Returns the jobs signalled."""
        killed = []
        for name in sorted(self._host_jobs.get(host, ())):
            p = self._procs.get(name)
            if p is None or p.poll() is not None:
                continue
            try:
                os.killpg(os.getpgid(p.pid), sig)
                killed.append(name)
            except ProcessLookupError:
                pass
        logger.warning("Emulated host %s killed (signal %d): %s.",
                       host, sig, killed or "no live jobs")
        return killed

    def resubmit_host(self, host: str) -> List[str]:
        """Relaunch every dead job of one host (host back from
        preemption); live jobs are left alone."""
        out = []
        for name in sorted(self._host_jobs.get(host, ())):
            p = self._procs.get(name)
            if p is not None and p.poll() is None:
                continue
            self.resubmit(name)
            out.append(name)
        return out


# ----------------------------------------------------------------------
class PodBringupError(TimeoutError):
    """Bring-up deadline expired (or a worker died before
    registering); the message groups the missing workers by host.
    A ``TimeoutError`` so ``main_start``'s auto-recover loop treats a
    transient boot failure as relaunchable."""

    def __init__(self, missing_by_host: Dict[str, List[str]],
                 deadline: float):
        self.missing_by_host = {h: sorted(ws)
                                for h, ws in missing_by_host.items()}
        parts = [f"{h}: {sorted(ws)}"
                 for h, ws in sorted(missing_by_host.items())]
        super().__init__(
            f"Pod bring-up deadline ({deadline:.0f}s) expired; workers "
            f"never registered -- {'; '.join(parts)}")


class PodController:
    """Thin pod supervisor over any ``SchedulerClient``.

    Wraps submission with retry/backoff (transient orchestrator /
    fork hiccups must not fail a 64-host launch), offers the ``hosts``
    view (from the scheduler when it is host-aware, else a single
    synthetic host), enforces a bring-up deadline with host-attributed
    errors, and writes the per-host obs artifacts at teardown."""

    def __init__(self, sched: SchedulerClient,
                 manifest: Optional[PodManifest] = None,
                 submit_retry: Optional[RetryPolicy] = None):
        self.sched = sched
        self.manifest = manifest if manifest is not None \
            else getattr(sched, "manifest", None)
        self._retry = submit_retry or RetryPolicy(
            max_attempts=3, base_delay=0.5, max_delay=10.0)
        self._submitted: List[str] = []

    # -- hosts view -----------------------------------------------------
    @property
    def multi_host(self) -> bool:
        return hasattr(self.sched, "host_of")

    def host_of(self, name: str) -> str:
        if self.multi_host:
            return self.sched.host_of(name)
        if self.manifest is not None:
            h = self.manifest.host_of(name)
            if h is not None:
                return h
        return host_name(0)

    def hosts(self) -> List[str]:
        if self.multi_host:
            return self.sched.hosts()
        if self.manifest is not None:
            return sorted(h.host_id for h in self.manifest.hosts)
        return [host_name(0)]

    def workers_on(self, host: str) -> List[str]:
        if hasattr(self.sched, "workers_on"):
            return self.sched.workers_on(host)
        return sorted(w for w in self._submitted
                      if self.host_of(w) == host)

    # -- bring-up -------------------------------------------------------
    def submit(self, name: str, cmd: List[str],
               env: Optional[Dict[str, str]] = None):
        """Submit one worker, retrying transient scheduler failures
        with backoff (sbatch slurmctld hiccups, EAGAIN forks)."""
        retry_call(lambda: self.sched.submit(name, cmd, env),
                   self._retry,
                   retry_on=(OSError, subprocess.SubprocessError),
                   what=f"submit {name}")
        self._submitted.append(name)

    def stop(self, name: str, grace: float = 10.0):
        """Stop ONE worker (the autoscale scale-down reaper,
        ``system/autoscale.py``): delegates to the scheduler's
        single-job stop when it has one (``LocalSchedulerClient.stop``
        SIGTERMs the process group and escalates to SIGKILL after
        ``grace``). Best effort -- never raises."""
        stop = getattr(self.sched, "stop", None)
        if stop is None:
            logger.warning("Scheduler %s has no single-job stop; "
                           "cannot reap %s.", type(self.sched).__name__,
                           name)
            return
        try:
            stop(name, grace=grace)
        except Exception as e:  # noqa: BLE001 - reaping is best effort
            logger.warning("Stop of %s failed: %s", name, e)

    def wait_ready(self, experiment_name: str, trial_name: str,
                   workers: Optional[Sequence[str]] = None,
                   deadline: float = 120.0, poll_interval: float = 0.5,
                   clock: Callable[[], float] = time.monotonic):
        """Block until every worker registered its command endpoint
        (``names.worker_key``) -- the first observable sign of a
        booted process -- or raise :class:`PodBringupError` naming the
        still-missing workers grouped by host. A worker whose process
        already FAILED fails fast instead of burning the deadline."""
        pending = set(workers if workers is not None
                      else self._submitted)
        t_end = clock() + deadline
        while pending:
            for w in sorted(pending):
                try:
                    name_resolve.get(names.worker_key(
                        experiment_name, trial_name, w))
                    pending.discard(w)
                except name_resolve.NameEntryNotFoundError:
                    pass
            if not pending:
                break
            dead = [w for w in pending
                    if self.sched.find(w).state == JobState.FAILED]
            if dead or clock() > t_end:
                missing: Dict[str, List[str]] = {}
                for w in (dead or pending):
                    missing.setdefault(self.host_of(w), []).append(w)
                raise PodBringupError(missing, deadline)
            time.sleep(poll_interval)
        total = len(workers) if workers is not None \
            else len(self._submitted)
        logger.info("Pod bring-up complete: %d workers registered "
                    "across %d host(s).", total, len(self.hosts()))

    # -- teardown obs ---------------------------------------------------
    def host_specs(self) -> List[HostSpec]:
        if self.manifest is not None:
            return list(self.manifest.hosts)
        return [HostSpec(host_id=h, index=i,
                         workers=self.workers_on(h),
                         scrape_port=DEFAULT_SCRAPE_BASE_PORT + i)
                for i, h in enumerate(self.hosts())]

    def write_scrape_targets(self, path: Optional[str] = None,
                             labels: Optional[Dict[str, str]] = None,
                             experiment_name: Optional[str] = None,
                             trial_name: Optional[str] = None
                             ) -> Optional[str]:
        """Prometheus scrape-target file under this run's obs dir
        (default). Targets come from the LIVE telemetry registry
        (:func:`resolve_scrape_targets` -- per-worker ports real HTTP
        servers bound, with ``worker``/``host`` labels) whenever any
        worker has published one; only when the registry is empty
        (pre-bring-up, or a teardown after every worker exited) does
        it fall back to the manifest's planned per-host ports. Never
        raises -- teardown must not mask the trial's outcome."""
        try:
            if path is None:
                from realhf_tpu.base import constants
                path = os.path.join(constants.run_log_path(), "obs",
                                    SCRAPE_TARGETS_NAME)
            entries: List[Dict] = []
            try:
                from realhf_tpu.base import constants
                exp = experiment_name or constants.experiment_name()
                trial = trial_name or constants.trial_name()
                entries = resolve_scrape_targets(exp, trial,
                                                 labels=labels)
            except Exception:  # noqa: BLE001 - run constants unset
                entries = []
            if entries:
                return write_target_entries(entries, path)
            return write_scrape_targets(self.host_specs(), path,
                                        labels=labels)
        except Exception as e:  # noqa: BLE001 - teardown best effort
            logger.warning("Scrape-target write failed: %s", e)
            return None


def name_resolve_host_lookup(experiment_name: str, trial_name: str
                             ) -> Callable[[str], Optional[str]]:
    """A ``host_of`` callable for the watchdog/master built on the
    host ids workers self-publish (``names.worker_host``). Positive
    results are cached; unknown workers re-read (they may simply not
    have booted yet)."""
    cache: Dict[str, str] = {}

    def host_of(worker: str) -> Optional[str]:
        h = cache.get(worker)
        if h is not None:
            return h
        try:
            h = str(name_resolve.get(names.worker_host(
                experiment_name, trial_name, worker)))
        except name_resolve.NameEntryNotFoundError:
            return None
        cache[worker] = h
        return h

    return host_of
