"""Elastic degraded-mode planning: survive preemptions, re-expand.

The paper's core mechanism -- parameter reallocation between per-MFC
device meshes -- is exactly the machinery needed to *survive* capacity
loss. Before this module, a LOST worker could only requeue MFCs
(hoping the worker returned) or fail the trial for a cold relaunch.
Now the master consults an :class:`ElasticPlanner` when the watchdog
declares workers LOST or a preemption notice arrives
(``names.worker_preempt``), and:

1. **Degrade.** Each affected MFC is re-planned onto the surviving
   worker set: a new single-worker home (preferring the role's
   primary group -- the weights are already there) and a degraded
   parallelism layout sized to the adopter's devices
   (:func:`degrade_parallelism`, optionally ranked by the search
   engine's analytic cost model). The master dispatches
   ``adopt_node`` to the adopter -- which builds a replica engine and
   reshards weights onto the degraded layout via
   ``parallel/realloc.py`` / ``param_stream.py`` -- and reroutes
   dispatch. Training continues at reduced throughput.
2. **Re-expand.** When the preempted/lost worker rejoins (relaunched
   by the launcher, heartbeat fresh + status RUNNING), the master
   dispatches ``release_node`` to the temporary adopter, restores the
   original routing, and forgives the worker's exclusion-backoff
   history (``ExclusionBook.forgive``). The rejoined worker's replica
   self-heals to the latest weights through the ordinary cross-group
   param-sync stream (version floor attached to the next dispatch) --
   reverse reallocation is the existing machinery, not a special
   case.

What is deliberately NOT migrated: MFCs executing on their role's
PRIMARY group (train steps above all). Moving a trainable primary
means moving optimizer state and the data-parallel training world --
that is relaunch-level recovery territory, served by the durable
checkpoint subsystem (``system/ckpt_manager.py``): the preempted
worker's emergency save lands a committed manifest the relaunch
restores from. The planner returns None for such nodes and the
master's existing requeue/fatal path takes over.

The planner is pure bookkeeping over the ExperimentSpec -- no
sockets, no engines -- so every decision is unit-testable.
"""

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Set

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.base import logging
from realhf_tpu.parallel.mesh import ParallelismConfig

logger = logging.getLogger("elastic")


def degrade_parallelism(par: ParallelismConfig, n_devices: int,
                        workload=None, cost_model=None
                        ) -> Optional[ParallelismConfig]:
    """The degraded layout for a mesh that must now fit ``n_devices``.

    Preference order mirrors what is cheapest to shrink: data
    parallelism first (pure throughput, no weight-layout change along
    other axes), then context, then pipeline, then tensor parallelism
    last (a TP change re-pads the vocab and reshards every matrix).
    The layout is preserved outright when it already fits -- a CPU
    fleet or a fat surviving host keeps full-fidelity numerics, which
    also keeps degraded-mode training bitwise-comparable to the
    original plan.

    With a ``workload`` (``search.engine.MFCWorkload``) the surviving
    candidates enumerated by the search engine on ``n_devices`` are
    ranked by its analytic cost model instead, picking the fastest
    layout that fits HBM. Returns None when nothing fits (zero
    devices).
    """
    if n_devices <= 0:
        return None
    if par.world_size <= n_devices:
        return par
    if workload is not None:
        from realhf_tpu.search.engine import (
            TPUCostModel,
            enumerate_candidates,
        )
        cands = enumerate_candidates(workload, n_devices,
                                     cost_model or TPUCostModel())
        if cands:
            best = min(cands, key=lambda c: c.time)
            chosen = dataclasses.replace(
                best.parallel, gen_tp_size=par.gen_tp_size)
            logger.info("Degraded %s -> %s by cost model (%d devices).",
                        par, chosen, n_devices)
            return chosen
    dp, tp = par.data_parallel_size, par.tensor_parallel_size
    pp, cp = par.pipeline_parallel_size, par.context_parallel_size
    while dp * tp * pp * cp > n_devices:
        if dp > 1:
            dp = max(1, dp // 2)
        elif cp > 1:
            cp = max(1, cp // 2)
        elif pp > 1:
            pp = max(1, pp // 2)
        elif tp > 1:
            tp = max(1, tp // 2)
        else:
            return None
    return ParallelismConfig(
        data_parallel_size=dp, tensor_parallel_size=tp,
        pipeline_parallel_size=pp, context_parallel_size=cp,
        sequence_parallel=par.sequence_parallel and tp > 1,
        gen_tp_size=par.gen_tp_size if par.gen_tp_size
        and par.gen_tp_size <= n_devices else 0)


@dataclasses.dataclass(frozen=True)
class NodePlan:
    """Where one MFC runs while degraded."""
    node: str
    workers: List[int]              # new exec group (single adopter)
    parallel: ParallelismConfig     # degraded layout
    cross_group: bool               # != the role's primary group
    reason: str = ""


@dataclasses.dataclass
class DegradedNode:
    """Bookkeeping for one migrated MFC, kept until re-expansion."""
    node: str
    original_workers: List[str]     # worker names, leader first
    original_cross_group: bool
    adopted_workers: List[str]
    plan: NodePlan
    since: float


class ElasticPlanner:
    """Degrade/re-expand planning over an ExperimentSpec.

    ``devices_per_worker``: local device count of one model worker
    (the adopter sizes its degraded mesh to this). ``max_adopted``:
    cap on concurrently adopted nodes per worker -- every adoption is
    a full extra weight replica in HBM.
    """

    def __init__(self, spec, dfg, devices_per_worker: Optional[int] = None,
                 max_adopted_per_worker: int = 2):
        self.spec = spec
        self.dfg = dfg
        if devices_per_worker is None:
            ldc = os.environ.get("REALHF_TPU_LOCAL_DEVICE_COUNT")
            if ldc:
                devices_per_worker = int(ldc)
            else:
                from realhf_tpu.parallel.mesh import default_devices
                devices_per_worker = len(default_devices())
        self.devices_per_worker = int(devices_per_worker)
        self.max_adopted_per_worker = max_adopted_per_worker
        #: node -> DegradedNode, the live degradations
        self.degraded: Dict[str, DegradedNode] = {}

    # ------------------------------------------------------------------
    def _adopted_on(self, widx: int) -> int:
        return sum(1 for d in self.degraded.values()
                   if d.adopted_workers == [f"model_worker/{widx}"])

    def plan_degraded(self, node_name: str, lost: Set[int],
                      alive: Sequence[int],
                      workload=None) -> Optional[NodePlan]:
        """Re-plan one MFC off the ``lost`` workers onto a survivor.

        Returns None when the node cannot be migrated (its role's
        primary group is hit, it is a train step, or no survivor has
        capacity) -- the caller falls back to requeue/fatal handling.
        """
        node = self.dfg.find(node_name)
        role = node.role
        primary = self.spec.workers_of_role(role)
        exec_group = self.spec.workers_of_node(node_name, role)
        if not (set(exec_group) & lost):
            return None  # unaffected
        if node.interface_type == ModelInterfaceType.TRAIN_STEP:
            logger.warning(
                "Elastic: train MFC %s hit by loss of workers %s; "
                "train steps never migrate (optimizer state moves via "
                "the durable checkpoint on relaunch).", node_name,
                sorted(lost))
            return None
        if set(primary) & lost:
            logger.warning(
                "Elastic: role %s's PRIMARY group %s hit by loss of "
                "workers %s; %s not migratable (weights source is "
                "gone -- relaunch restores from the emergency "
                "checkpoint).", role, primary, sorted(lost), node_name)
            return None
        survivors = [w for w in alive if w not in lost]
        if not survivors:
            return None
        # Adopter preference: the role's primary-group leader first
        # (weights are live in-process: adoption is a local reshard,
        # no cross-group stream), then the least-loaded survivor.
        ordered = ([w for w in primary if w in survivors]
                   + sorted((w for w in survivors if w not in primary),
                            key=lambda w: (self._adopted_on(w), w)))
        for widx in ordered:
            if self._adopted_on(widx) >= self.max_adopted_per_worker:
                continue
            par = degrade_parallelism(
                self._node_parallel(node_name, role),
                self.devices_per_worker, workload=workload)
            if par is None:
                continue
            cross = widx not in primary
            return NodePlan(
                node=node_name, workers=[widx], parallel=par,
                cross_group=cross,
                reason=f"workers {sorted(lost)} lost/preempted")
        logger.error(
            "Elastic: no surviving worker can adopt %s (survivors %s "
            "all at max_adopted_per_worker=%d or too small).",
            node_name, survivors, self.max_adopted_per_worker)
        return None

    def _node_parallel(self, node_name: str, role: str
                       ) -> ParallelismConfig:
        alloc = self.spec.alloc_of(node_name)
        if alloc is not None:
            return alloc.parallel
        return self.spec.models[role].parallel

    # ------------------------------------------------------------------
    def record_degraded(self, plan: NodePlan,
                        original_workers: List[str],
                        original_cross_group: bool,
                        clock=time.monotonic) -> DegradedNode:
        rec = DegradedNode(
            node=plan.node, original_workers=list(original_workers),
            original_cross_group=original_cross_group,
            adopted_workers=[f"model_worker/{w}" for w in plan.workers],
            plan=plan, since=clock())
        self.degraded[plan.node] = rec
        return rec

    def restorable_nodes(self, rejoined: Set[str]) -> List[DegradedNode]:
        """Degraded nodes whose ENTIRE original worker group is back
        among ``rejoined`` (worker names) -- ready for re-expansion."""
        return [d for d in self.degraded.values()
                if set(d.original_workers) <= rejoined]

    def mark_restored(self, node_name: str) -> Optional[DegradedNode]:
        return self.degraded.pop(node_name, None)

    def degraded_workers(self) -> Set[str]:
        """Original homes of currently degraded nodes (the workers
        whose rejoin we are waiting for)."""
        return {w for d in self.degraded.values()
                for w in d.original_workers}


@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One observation of the serving fleet's live load, as consumed
    by :class:`AutoscalePolicy.observe`. All fields are plain numbers
    so policy tests are pure data on a fake clock."""
    #: requests not yet started anywhere: at minimum the router's
    #: pending (unassigned) count -- what ``run_serve`` wires, with
    #: requests queued INSIDE a replica folded into ``inflight`` --
    #: while in-process harnesses that can read replica queues
    #: cheaply (scripts/bench_serving.py) aggregate those in too, so
    #: tune ``up_queue_per_replica`` against the signal actually fed
    queue_depth: int = 0
    #: requests dispatched to a replica fleet-wide (decoding, or
    #: queued inside it when the feeder cannot see replica queues)
    inflight: int = 0
    #: NEW admission rejections since the previous observation
    #: (backpressure / no_healthy_replica -- a shed request is the
    #: strongest possible scale-up signal)
    rejections: int = 0
    #: recent end-to-end response latency (e.g. the router's EWMA)
    latency_secs: float = 0.0
    #: live, non-retiring replicas the decision applies to
    n_replicas: int = 1


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """The policy's verdict for one observation. ``action`` is
    ``"up"``/``"down"`` only when the controller should act NOW;
    a triggered-but-vetoed decision comes back as ``"hold"`` with
    ``suppressed`` naming the veto (cooldown / flap / floor / ceiling
    / last_healthy)."""
    action: str                     # "up" | "down" | "hold"
    target: int                     # desired replica count
    reason: str = ""
    suppressed: Optional[str] = None

    @property
    def acted(self) -> bool:
        return self.action in ("up", "down")


class AutoscalePolicy:
    """Closed-loop scale decisions from live serving signals
    (docs/serving.md "Autoscaling"): the promotion of the log-only
    :class:`GrowAdvisor` into the decision engine the
    ``AutoscaleController`` (``system/autoscale.py``) acts on.

    **Scale-up** triggers when any pressure signal holds for
    ``consecutive_up`` observations: queue depth above
    ``up_queue_per_replica`` per live replica, any admission
    rejections (``up_rejections`` per observation), or response
    latency above ``up_latency_secs``. **Scale-down** triggers when
    the fleet has been idle for ``consecutive_down`` observations:
    zero queued requests AND the in-flight load would fit on one
    replica fewer (``down_idle_per_replica`` in-flight requests per
    remaining replica).

    A triggered decision still has to clear the vetoes, each recorded
    as ``serving_autoscale_suppressed_total{reason=...}``:

    - **floor/ceiling**: the replica count never leaves
      ``[min_replicas, max_replicas]``, and the last replica is never
      retired while traffic is queued or in flight (``last_healthy``)
      even when ``min_replicas == 0``.
    - **cooldown**: after an action, the SAME direction re-arms only
      after ``cooldown_secs``.
    - **flap**: every action excludes the OPPOSITE direction through
      an :class:`~realhf_tpu.system.watchdog.ExclusionBook` window
      (``flap_base_secs``, doubling per repeat, capped) -- the
      up/down/up oscillation a bursty workload invites gets
      exponentially longer dead time, exactly the cooldown discipline
      flapping workers get. A ``flap_forgive_secs`` stretch with no
      actions clears the escalation history.

    Emitted decisions and suppressions are recorded as flight events
    plus ``serving_autoscale_{up,down,suppressed}_total`` metrics.
    The clock is injectable; all hysteresis tests run on a fake clock
    in milliseconds."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 up_queue_per_replica: int = 8,
                 up_rejections: int = 1,
                 up_latency_secs: Optional[float] = None,
                 consecutive_up: int = 3,
                 down_idle_per_replica: float = 1.0,
                 consecutive_down: int = 10,
                 cooldown_secs: float = 60.0,
                 flap_base_secs: Optional[float] = None,
                 flap_max_secs: float = 600.0,
                 flap_forgive_secs: Optional[float] = None,
                 clock=time.monotonic):
        if min_replicas < 0:
            raise ValueError(
                f"min_replicas must be >= 0, got {min_replicas}")
        if max_replicas < max(1, min_replicas):
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"max(1, min_replicas={min_replicas})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_per_replica = int(up_queue_per_replica)
        self.up_rejections = int(up_rejections)
        self.up_latency_secs = up_latency_secs
        self.consecutive_up = max(1, int(consecutive_up))
        self.down_idle_per_replica = float(down_idle_per_replica)
        self.consecutive_down = int(consecutive_down)
        self.cooldown_secs = float(cooldown_secs)
        flap_base = cooldown_secs if flap_base_secs is None \
            else flap_base_secs
        self.flap_forgive_secs = 10.0 * float(cooldown_secs) \
            if flap_forgive_secs is None else float(flap_forgive_secs)
        self._clock = clock
        # the flap guard IS an ExclusionBook: each action "excludes"
        # the opposite direction, with the book's exponential-backoff
        # discipline escalating repeated reversals (jitter pinned to 0
        # -- scale decisions must be deterministic in the clock)
        from realhf_tpu.system.watchdog import ExclusionBook
        self._book = ExclusionBook(base=flap_base, factor=2.0,
                                   max_delay=flap_max_secs,
                                   jitter=0.0, clock=clock)
        self._streak_up = 0
        self._streak_down = 0
        self._last_action: Optional[tuple] = None  # (direction, t)
        #: (direction, reason) of the current suppression episode --
        #: the flight event fires once per episode, the counter every
        #: suppressed observation
        self._suppress_episode: Optional[tuple] = None
        self.decisions = dict(up=0, down=0, suppressed=0)

    # -- triggers ------------------------------------------------------
    def _up_pressure(self, s: AutoscaleSignals) -> Optional[str]:
        n = max(1, s.n_replicas)
        if self.up_queue_per_replica > 0 \
                and s.queue_depth > self.up_queue_per_replica * n:
            return (f"queue_depth {s.queue_depth} > "
                    f"{self.up_queue_per_replica}/replica x {n}")
        if self.up_rejections > 0 and s.rejections >= self.up_rejections:
            return f"rejections {s.rejections}"
        if self.up_latency_secs is not None \
                and s.latency_secs > self.up_latency_secs:
            return (f"latency {s.latency_secs:.3f}s > "
                    f"{self.up_latency_secs:.3f}s")
        return None

    def _down_idle(self, s: AutoscaleSignals) -> Optional[str]:
        if self.consecutive_down <= 0:
            return None  # scale-down disabled
        if s.queue_depth > 0:
            return None
        capacity_after = self.down_idle_per_replica \
            * max(0, s.n_replicas - 1)
        if s.inflight <= capacity_after:
            return (f"idle: {s.inflight} in flight fits "
                    f"{s.n_replicas - 1} replica(s)")
        return None

    # -- decision ------------------------------------------------------
    def observe(self, signals: AutoscaleSignals, **ctx) -> ScaleDecision:
        """Feed one observation; returns the decision for it. The
        controller acts on ``action in ("up", "down")``; everything
        else is bookkeeping."""
        up_why = self._up_pressure(signals)
        down_why = None if up_why else self._down_idle(signals)
        self._streak_up = self._streak_up + 1 if up_why else 0
        self._streak_down = self._streak_down + 1 if down_why else 0
        if up_why and self._streak_up >= self.consecutive_up:
            return self._decide("up", signals, up_why, ctx)
        if down_why and self.consecutive_down > 0 \
                and self._streak_down >= self.consecutive_down:
            return self._decide("down", signals, down_why, ctx)
        self._suppress_episode = None
        return ScaleDecision("hold", signals.n_replicas,
                             reason="no_trigger")

    def _decide(self, direction: str, s: AutoscaleSignals, why: str,
                ctx: Dict) -> ScaleDecision:
        now = self._clock()
        if direction == "up":
            if s.n_replicas >= self.max_replicas:
                return self._suppress(direction, s, "ceiling", ctx)
        else:
            if s.n_replicas <= self.min_replicas:
                return self._suppress(direction, s, "floor", ctx)
            if s.n_replicas <= 1 and (s.inflight > 0
                                      or s.queue_depth > 0):
                # even with floor 0: never take the last healthy
                # replica while traffic is in flight
                return self._suppress(direction, s, "last_healthy", ctx)
        la = self._last_action
        if la is not None and now - la[1] >= self.flap_forgive_secs:
            # a long stable stretch forgives the flap escalation
            self._book.forgive("up")
            self._book.forgive("down")
        if la is not None and la[0] == direction \
                and now - la[1] < self.cooldown_secs:
            return self._suppress(direction, s, "cooldown", ctx)
        if self._book.is_excluded(direction):
            return self._suppress(direction, s, "flap", ctx)
        target = s.n_replicas + (1 if direction == "up" else -1)
        self._last_action = (direction, now)
        self._book.exclude("down" if direction == "up" else "up")
        self._streak_up = self._streak_down = 0
        self._suppress_episode = None
        self.decisions[direction] += 1
        self._emit(direction, target, s, why, ctx)
        return ScaleDecision(direction, target, reason=why)

    # -- recording (subclass hooks: GrowAdvisor keeps legacy names) ----
    def _emit(self, direction: str, target: int, s: AutoscaleSignals,
              why: str, ctx: Dict):
        from realhf_tpu.obs import flight, metrics
        metrics.inc(f"serving_autoscale_{direction}_total", **ctx)
        flight.record("autoscale_decision", action=direction,
                      target=target, reason=why,
                      queue_depth=s.queue_depth, inflight=s.inflight,
                      rejections=s.rejections,
                      n_replicas=s.n_replicas, **ctx)
        logger.warning(
            "Autoscale %s: %d -> %d replicas (%s).", direction.upper(),
            s.n_replicas, target, why)

    def _suppress(self, direction: str, s: AutoscaleSignals,
                  reason: str, ctx: Dict) -> ScaleDecision:
        self.decisions["suppressed"] += 1
        self._suppress_emit(direction, s, reason, ctx)
        return ScaleDecision("hold", s.n_replicas,
                             reason=f"{direction} suppressed: {reason}",
                             suppressed=reason)

    def _suppress_emit(self, direction: str, s: AutoscaleSignals,
                       reason: str, ctx: Dict):
        from realhf_tpu.obs import flight, metrics
        metrics.inc("serving_autoscale_suppressed_total",
                    direction=direction, reason=reason, **ctx)
        episode = (direction, reason)
        if self._suppress_episode != episode:
            # one flight event per suppression EPISODE; the counter
            # above still counts every suppressed observation
            self._suppress_episode = episode
            flight.record("autoscale_suppressed", action=direction,
                          reason=reason, queue_depth=s.queue_depth,
                          n_replicas=s.n_replicas, **ctx)
            logger.info("Autoscale %s suppressed (%s): queue=%d "
                        "inflight=%d replicas=%d.", direction, reason,
                        s.queue_depth, s.inflight, s.n_replicas)


class GrowAdvisor(AutoscalePolicy):
    """Log-only autoscaling advisory (the PR-9 slice, now a thin
    :class:`AutoscalePolicy` in advisory clothing): sustained queue
    depth above ``threshold`` emits ONE grow suggestion --
    ``elastic_grow_suggested_total`` counter, an
    ``elastic_grow_suggestion`` flight event, a warning log -- then
    stays quiet for ``cooldown_secs``. No fleet change happens here;
    the closed loop lives in ``system/autoscale.py``.
    ``threshold <= 0`` disables the advisor entirely."""

    def __init__(self, threshold: int, consecutive: int = 3,
                 cooldown_secs: float = 60.0,
                 clock=time.monotonic):
        super().__init__(
            min_replicas=1, max_replicas=1_000_000,
            up_queue_per_replica=int(threshold),
            up_rejections=0, up_latency_secs=None,
            consecutive_up=consecutive, consecutive_down=0,
            cooldown_secs=cooldown_secs, clock=clock)
        self.threshold = int(threshold)
        self.consecutive = max(1, int(consecutive))
        self.suggestions = 0

    @property
    def _streak(self) -> int:
        return self._streak_up

    def observe(self, queue_depth: int, **ctx) -> bool:
        """Feed one queue-depth observation; True when a grow
        suggestion was emitted for it."""
        if self.threshold <= 0:
            return False
        decision = super().observe(
            AutoscaleSignals(queue_depth=int(queue_depth),
                             n_replicas=1), **ctx)
        return decision.action == "up"

    def _emit(self, direction, target, s, why, ctx):
        self.suggestions += 1
        from realhf_tpu.obs import flight, metrics
        metrics.inc("elastic_grow_suggested_total", **ctx)
        flight.record("elastic_grow_suggestion",
                      queue_depth=s.queue_depth,
                      threshold=self.threshold, **ctx)
        logger.warning(
            "ElasticPlanner GROW suggested: queue depth %d > %d for "
            "%d consecutive observations (%s). Advisory only -- no "
            "mesh change.", s.queue_depth, self.threshold,
            self.consecutive, ctx or "no context")

    def _suppress_emit(self, direction, s, reason, ctx):
        pass  # advisory stays silent while suppressed (PR-9 contract)
