"""Elastic degraded-mode planning: survive preemptions, re-expand.

The paper's core mechanism -- parameter reallocation between per-MFC
device meshes -- is exactly the machinery needed to *survive* capacity
loss. Before this module, a LOST worker could only requeue MFCs
(hoping the worker returned) or fail the trial for a cold relaunch.
Now the master consults an :class:`ElasticPlanner` when the watchdog
declares workers LOST or a preemption notice arrives
(``names.worker_preempt``), and:

1. **Degrade.** Each affected MFC is re-planned onto the surviving
   worker set: a new single-worker home (preferring the role's
   primary group -- the weights are already there) and a degraded
   parallelism layout sized to the adopter's devices
   (:func:`degrade_parallelism`, optionally ranked by the search
   engine's analytic cost model). The master dispatches
   ``adopt_node`` to the adopter -- which builds a replica engine and
   reshards weights onto the degraded layout via
   ``parallel/realloc.py`` / ``param_stream.py`` -- and reroutes
   dispatch. Training continues at reduced throughput.
2. **Re-expand.** When the preempted/lost worker rejoins (relaunched
   by the launcher, heartbeat fresh + status RUNNING), the master
   dispatches ``release_node`` to the temporary adopter, restores the
   original routing, and forgives the worker's exclusion-backoff
   history (``ExclusionBook.forgive``). The rejoined worker's replica
   self-heals to the latest weights through the ordinary cross-group
   param-sync stream (version floor attached to the next dispatch) --
   reverse reallocation is the existing machinery, not a special
   case.

What is deliberately NOT migrated: MFCs executing on their role's
PRIMARY group (train steps above all). Moving a trainable primary
means moving optimizer state and the data-parallel training world --
that is relaunch-level recovery territory, served by the durable
checkpoint subsystem (``system/ckpt_manager.py``): the preempted
worker's emergency save lands a committed manifest the relaunch
restores from. The planner returns None for such nodes and the
master's existing requeue/fatal path takes over.

The planner is pure bookkeeping over the ExperimentSpec -- no
sockets, no engines -- so every decision is unit-testable.
"""

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Set

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.base import logging
from realhf_tpu.parallel.mesh import ParallelismConfig

logger = logging.getLogger("elastic")


def degrade_parallelism(par: ParallelismConfig, n_devices: int,
                        workload=None, cost_model=None
                        ) -> Optional[ParallelismConfig]:
    """The degraded layout for a mesh that must now fit ``n_devices``.

    Preference order mirrors what is cheapest to shrink: data
    parallelism first (pure throughput, no weight-layout change along
    other axes), then context, then pipeline, then tensor parallelism
    last (a TP change re-pads the vocab and reshards every matrix).
    The layout is preserved outright when it already fits -- a CPU
    fleet or a fat surviving host keeps full-fidelity numerics, which
    also keeps degraded-mode training bitwise-comparable to the
    original plan.

    With a ``workload`` (``search.engine.MFCWorkload``) the surviving
    candidates enumerated by the search engine on ``n_devices`` are
    ranked by its analytic cost model instead, picking the fastest
    layout that fits HBM. Returns None when nothing fits (zero
    devices).
    """
    if n_devices <= 0:
        return None
    if par.world_size <= n_devices:
        return par
    if workload is not None:
        from realhf_tpu.search.engine import (
            TPUCostModel,
            enumerate_candidates,
        )
        cands = enumerate_candidates(workload, n_devices,
                                     cost_model or TPUCostModel())
        if cands:
            best = min(cands, key=lambda c: c.time)
            chosen = dataclasses.replace(
                best.parallel, gen_tp_size=par.gen_tp_size)
            logger.info("Degraded %s -> %s by cost model (%d devices).",
                        par, chosen, n_devices)
            return chosen
    dp, tp = par.data_parallel_size, par.tensor_parallel_size
    pp, cp = par.pipeline_parallel_size, par.context_parallel_size
    while dp * tp * pp * cp > n_devices:
        if dp > 1:
            dp = max(1, dp // 2)
        elif cp > 1:
            cp = max(1, cp // 2)
        elif pp > 1:
            pp = max(1, pp // 2)
        elif tp > 1:
            tp = max(1, tp // 2)
        else:
            return None
    return ParallelismConfig(
        data_parallel_size=dp, tensor_parallel_size=tp,
        pipeline_parallel_size=pp, context_parallel_size=cp,
        sequence_parallel=par.sequence_parallel and tp > 1,
        gen_tp_size=par.gen_tp_size if par.gen_tp_size
        and par.gen_tp_size <= n_devices else 0)


@dataclasses.dataclass(frozen=True)
class NodePlan:
    """Where one MFC runs while degraded."""
    node: str
    workers: List[int]              # new exec group (single adopter)
    parallel: ParallelismConfig     # degraded layout
    cross_group: bool               # != the role's primary group
    reason: str = ""


@dataclasses.dataclass
class DegradedNode:
    """Bookkeeping for one migrated MFC, kept until re-expansion."""
    node: str
    original_workers: List[str]     # worker names, leader first
    original_cross_group: bool
    adopted_workers: List[str]
    plan: NodePlan
    since: float


class ElasticPlanner:
    """Degrade/re-expand planning over an ExperimentSpec.

    ``devices_per_worker``: local device count of one model worker
    (the adopter sizes its degraded mesh to this). ``max_adopted``:
    cap on concurrently adopted nodes per worker -- every adoption is
    a full extra weight replica in HBM.
    """

    def __init__(self, spec, dfg, devices_per_worker: Optional[int] = None,
                 max_adopted_per_worker: int = 2):
        self.spec = spec
        self.dfg = dfg
        if devices_per_worker is None:
            ldc = os.environ.get("REALHF_TPU_LOCAL_DEVICE_COUNT")
            if ldc:
                devices_per_worker = int(ldc)
            else:
                from realhf_tpu.parallel.mesh import default_devices
                devices_per_worker = len(default_devices())
        self.devices_per_worker = int(devices_per_worker)
        self.max_adopted_per_worker = max_adopted_per_worker
        #: node -> DegradedNode, the live degradations
        self.degraded: Dict[str, DegradedNode] = {}

    # ------------------------------------------------------------------
    def _adopted_on(self, widx: int) -> int:
        return sum(1 for d in self.degraded.values()
                   if d.adopted_workers == [f"model_worker/{widx}"])

    def plan_degraded(self, node_name: str, lost: Set[int],
                      alive: Sequence[int],
                      workload=None) -> Optional[NodePlan]:
        """Re-plan one MFC off the ``lost`` workers onto a survivor.

        Returns None when the node cannot be migrated (its role's
        primary group is hit, it is a train step, or no survivor has
        capacity) -- the caller falls back to requeue/fatal handling.
        """
        node = self.dfg.find(node_name)
        role = node.role
        primary = self.spec.workers_of_role(role)
        exec_group = self.spec.workers_of_node(node_name, role)
        if not (set(exec_group) & lost):
            return None  # unaffected
        if node.interface_type == ModelInterfaceType.TRAIN_STEP:
            logger.warning(
                "Elastic: train MFC %s hit by loss of workers %s; "
                "train steps never migrate (optimizer state moves via "
                "the durable checkpoint on relaunch).", node_name,
                sorted(lost))
            return None
        if set(primary) & lost:
            logger.warning(
                "Elastic: role %s's PRIMARY group %s hit by loss of "
                "workers %s; %s not migratable (weights source is "
                "gone -- relaunch restores from the emergency "
                "checkpoint).", role, primary, sorted(lost), node_name)
            return None
        survivors = [w for w in alive if w not in lost]
        if not survivors:
            return None
        # Adopter preference: the role's primary-group leader first
        # (weights are live in-process: adoption is a local reshard,
        # no cross-group stream), then the least-loaded survivor.
        ordered = ([w for w in primary if w in survivors]
                   + sorted((w for w in survivors if w not in primary),
                            key=lambda w: (self._adopted_on(w), w)))
        for widx in ordered:
            if self._adopted_on(widx) >= self.max_adopted_per_worker:
                continue
            par = degrade_parallelism(
                self._node_parallel(node_name, role),
                self.devices_per_worker, workload=workload)
            if par is None:
                continue
            cross = widx not in primary
            return NodePlan(
                node=node_name, workers=[widx], parallel=par,
                cross_group=cross,
                reason=f"workers {sorted(lost)} lost/preempted")
        logger.error(
            "Elastic: no surviving worker can adopt %s (survivors %s "
            "all at max_adopted_per_worker=%d or too small).",
            node_name, survivors, self.max_adopted_per_worker)
        return None

    def _node_parallel(self, node_name: str, role: str
                       ) -> ParallelismConfig:
        alloc = self.spec.alloc_of(node_name)
        if alloc is not None:
            return alloc.parallel
        return self.spec.models[role].parallel

    # ------------------------------------------------------------------
    def record_degraded(self, plan: NodePlan,
                        original_workers: List[str],
                        original_cross_group: bool,
                        clock=time.monotonic) -> DegradedNode:
        rec = DegradedNode(
            node=plan.node, original_workers=list(original_workers),
            original_cross_group=original_cross_group,
            adopted_workers=[f"model_worker/{w}" for w in plan.workers],
            plan=plan, since=clock())
        self.degraded[plan.node] = rec
        return rec

    def restorable_nodes(self, rejoined: Set[str]) -> List[DegradedNode]:
        """Degraded nodes whose ENTIRE original worker group is back
        among ``rejoined`` (worker names) -- ready for re-expansion."""
        return [d for d in self.degraded.values()
                if set(d.original_workers) <= rejoined]

    def mark_restored(self, node_name: str) -> Optional[DegradedNode]:
        return self.degraded.pop(node_name, None)

    def degraded_workers(self) -> Set[str]:
        """Original homes of currently degraded nodes (the workers
        whose rejoin we are waiting for)."""
        return {w for d in self.degraded.values()
                for w in d.original_workers}


class GrowAdvisor:
    """Log-only autoscaling advisory: the first end-to-end wire from
    the serving metrics to the elastic planner (ROADMAP item 2's
    smallest useful slice).

    ``observe(queue_depth)`` is called wherever the queue-depth gauge
    is set (``serving/server.py`` serve loop). A depth above
    ``threshold`` for ``consecutive`` observations emits ONE grow
    suggestion -- ``elastic_grow_suggested_total`` counter, an
    ``elastic_grow_suggestion`` flight event, a warning log -- and
    then stays quiet for ``cooldown_secs``. No mesh or fleet change
    happens; an operator (or a future autoscaler) acts on the signal.
    ``threshold <= 0`` disables the advisor entirely."""

    def __init__(self, threshold: int, consecutive: int = 3,
                 cooldown_secs: float = 60.0,
                 clock=time.monotonic):
        self.threshold = int(threshold)
        self.consecutive = max(1, int(consecutive))
        self.cooldown_secs = cooldown_secs
        self._clock = clock
        self._streak = 0
        self._last_suggested: Optional[float] = None
        self.suggestions = 0

    def observe(self, queue_depth: int, **ctx) -> bool:
        """Feed one queue-depth observation; True when a grow
        suggestion was emitted for it."""
        if self.threshold <= 0:
            return False
        if queue_depth <= self.threshold:
            self._streak = 0
            return False
        self._streak += 1
        if self._streak < self.consecutive:
            return False
        now = self._clock()
        if self._last_suggested is not None \
                and now - self._last_suggested < self.cooldown_secs:
            return False
        self._last_suggested = now
        self._streak = 0
        self.suggestions += 1
        from realhf_tpu.obs import flight, metrics
        metrics.inc("elastic_grow_suggested_total", **ctx)
        flight.record("elastic_grow_suggestion",
                      queue_depth=queue_depth,
                      threshold=self.threshold, **ctx)
        logger.warning(
            "ElasticPlanner GROW suggested: queue depth %d > %d for "
            "%d consecutive observations (%s). Advisory only -- no "
            "mesh change.", queue_depth, self.threshold,
            self.consecutive, ctx or "no context")
        return True
