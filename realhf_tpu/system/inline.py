"""Single-process experiment runner: the minimum end-to-end slice.

Executes an ExperimentSpec's dataflow graph in one process with all
models sharing the local device fleet in "symmetric allocation" (every
MFC on the same mesh), which is the reference's
``allocation_mode=d$Np$Pm$M`` global-hybrid mode
(``experiments/common/common.py:319``). The distributed
master/model-worker runtime adds disjoint sub-meshes and parameter
reallocation on top of the exact same interface calls.

Responsibilities mirrored from the reference master worker
(``system/master_worker.py``): dataset loading and epoch accounting,
topological MFC execution with key remapping, amending results into
the step's data buffer, save/eval frequency control, per-step
throughput logging (tokens + TFLOP/s), and benchmark early exit.
"""

import os
import time
from typing import Dict

import numpy as np

from realhf_tpu import obs
from realhf_tpu.api import data as data_api
from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.api.dfg import DFG
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.base import constants, logging, recover, seeding, timeutil
from realhf_tpu.obs import metrics, tracing
from realhf_tpu.system.model_host import ModelHost

logger = logging.getLogger("InlineRunner", "benchmark")


class InlineRunner:

    def __init__(self, spec: ExperimentSpec, recover_mode: str = "disabled"):
        self.spec = spec
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        # REALHF_TPU_TRACE=1 gives the single-process runner the same
        # span timeline the distributed runtime emits (one process)
        obs.configure_from_env("inline", experiment=spec.experiment_name,
                               trial=spec.trial_name)
        # live telemetry endpoints, same surface as any worker
        # (obs/http.py; REALHF_TPU_TELEMETRY=0 opts out)
        from realhf_tpu.base import name_resolve, names
        from realhf_tpu.obs import http as obs_http
        self.telemetry = obs_http.start_from_env(
            "inline", health=self._telemetry_health)
        if self.telemetry is not None:
            try:
                name_resolve.add(
                    names.telemetry(spec.experiment_name,
                                    spec.trial_name, "inline"),
                    self.telemetry.address, replace=True)
            except Exception:  # noqa: BLE001 - discovery is advisory
                pass
        seeding.set_random_seed(spec.seed)

        # Recovery (reference recover_mode resume, base/recover.py +
        # master_worker.__recover_save:1541): restore step counters and
        # the set of data ids consumed in the interrupted epoch, and
        # redirect trainable models to their latest checkpoints.
        self.recover_mode = recover_mode
        self._recover_info = None
        if recover_mode == "resume":
            # load_safe: a corrupt/truncated/future-schema file means
            # a fresh start, not a crash loop
            self._recover_info = recover.load_safe()
        if self._recover_info is not None:
            logger.info("Resuming from recover info (schema v%d): %s",
                        self._recover_info.version,
                        self._recover_info.recover_start)
            for role, mspec in spec.models.items():
                ckpt = os.path.join(constants.run_save_path(), role)
                if os.path.exists(os.path.join(ckpt, "config.json")):
                    mspec.path = ckpt
                    mspec.random_init_config = None
                    mspec.restore_optimizer_state = True
                    logger.info("Recovered %s from %s", role, ckpt)

        import realhf_tpu.datasets  # noqa: F401 - register datasets
        import realhf_tpu.interfaces  # noqa: F401 - register interfaces

        self.dfg = DFG(spec.mfcs)
        self.tokenizer = spec.tokenizer or (
            data_api.load_hf_tokenizer(spec.tokenizer_path)
            if spec.tokenizer_path else None)

        src = self.dfg.sources[0]
        self.dataset = data_api.make_dataset(
            spec.dataset, seed=spec.seed, dp_rank=0, world_size=1,
            tokenizer_or_path=self.tokenizer)
        self.dataloader = data_api.PackedDataLoader(
            self.dataset, batch_size=src.n_seqs, seed=spec.seed)
        self.eval_dataloader = None
        if spec.eval_dataset is not None:
            eval_ds = data_api.make_dataset(
                spec.eval_dataset, seed=spec.seed, dp_rank=0, world_size=1,
                tokenizer_or_path=self.tokenizer)
            self.eval_dataloader = data_api.PackedDataLoader(
                eval_ds, batch_size=src.n_seqs, shuffle=False)

        steps_per_epoch = len(self.dataloader)
        total_steps = steps_per_epoch * spec.total_train_epochs
        self.host = ModelHost(spec, list(spec.models), self.dfg.nodes,
                              self.tokenizer, total_steps)

        ctl = spec.ctl
        self.save_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.save_freq_epochs, freq_step=ctl.save_freq_steps,
            freq_sec=ctl.save_freq_secs)
        self.eval_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.eval_freq_epochs, freq_step=ctl.eval_freq_steps,
            freq_sec=None)
        self.global_step = 0
        self._start_epoch = 0
        self._start_epoch_step = 0
        self._ids_to_skip = set()
        if self._recover_info is not None:
            self.global_step = self._recover_info.last_step_info.global_step
            self._start_epoch = self._recover_info.recover_start.epoch
            self._ids_to_skip = set(self._recover_info.hash_vals_to_ignore)
            # dataloader epoch state (schema v2): resume epoch-step
            # accounting mid-epoch so save/eval frequency control and
            # logs line up with the interrupted run (consumed-id
            # skipping already prevents data re-consumption)
            dl = self._recover_info.dataloader_state or {}
            self._start_epoch_step = int(dl.get("epoch_step", 0))

    def _telemetry_health(self):
        return dict(worker="inline", state="RUNNING",
                    global_step=self.global_step)

    # -- compat accessors (tests + callers use these) -------------------
    @property
    def models(self):
        return self.host.models

    @property
    def replicas(self):
        return self.host.replicas

    @property
    def replica_mgr(self):
        return self.host.replica_mgr

    @property
    def interfaces(self):
        return self.host.interfaces

    # ------------------------------------------------------------------
    def run_step(self, batch: data_api.SequenceSample) -> Dict[str, Dict]:
        """Execute the full DFG once over one batch; returns per-MFC
        stats (mirrors one master-worker _poll iteration)."""
        stats: Dict[str, Dict] = {}
        data = batch
        # Execute level by level; independent MFCs within a level run
        # concurrently (host.execute_level), mirroring the distributed
        # master's concurrent dispatch. Outputs merge in level order.
        for level in self.dfg.topological_levels():
            named = [(node.name,
                      data.select([k for k in node.input_keys
                                   if k in data.keys]))
                     for node in level]
            outs = self.host.execute_level(named)
            for node, out in zip(level, outs):
                if isinstance(out, data_api.SequenceSample):
                    data.update_(out)
                elif isinstance(out, dict):
                    stats[node.name] = out
                    if node.log_return_value:
                        logger.info("MFC %s stats: %s", node.name, out)
        return stats

    def _maybe_save(self, epochs: int = 0, steps: int = 0, force=False):
        if not force and not self.save_ctl.check(epochs=epochs, steps=steps):
            return
        for node in self.dfg.nodes:
            if node.interface_type != ModelInterfaceType.TRAIN_STEP:
                continue
            # host.save_role streams the weights AND the optimizer
            # state -- the resume path above restores Adam moments
            # only if they were written here (it used to call the
            # interface save directly, which silently dropped them).
            self.host.save_role(node.role, node.name)
        # Recover info is only valid paired with the checkpoint it
        # describes (reference couples them in __recover_save), so it
        # is dumped here, never on unsaved steps.
        if self.recover_mode != "disabled":
            recover.dump(recover.RecoverInfo(
                recover_start=recover.StepInfo(
                    epoch=self._cur_epoch,
                    epoch_step=self._cur_epoch_step + 1,
                    global_step=self.global_step),
                last_step_info=recover.StepInfo(
                    epoch=self._cur_epoch,
                    epoch_step=self._cur_epoch_step,
                    global_step=self.global_step),
                hash_vals_to_ignore=list(self._consumed_ids),
                dataloader_state=dict(
                    epoch=self._cur_epoch,
                    epoch_step=self._cur_epoch_step)))

    def _maybe_eval(self, epochs: int = 0, steps: int = 0):
        if self.eval_dataloader is None:
            return
        if not self.eval_ctl.check(epochs=epochs, steps=steps):
            return
        for node in self.dfg.nodes:
            if node.interface_type != ModelInterfaceType.TRAIN_STEP:
                continue
            ev = self.interfaces[node.name].evaluate(
                self.models[node.role], self.eval_dataloader)
            if ev:
                logger.info("Eval %s: %s", node.role, ev)

    def run(self) -> Dict[str, Dict]:
        """Train for the configured epochs; returns the last step stats."""
        spec = self.spec
        last_stats = {}
        done = False
        self._consumed_ids = list(self._ids_to_skip)
        self._cur_epoch = self._start_epoch
        self._cur_epoch_step = self._start_epoch_step
        for epoch in range(self._start_epoch, spec.total_train_epochs):
            self._cur_epoch = epoch
            for step, batch in enumerate(self.dataloader):
                self._cur_epoch_step = step
                if self._ids_to_skip:
                    # first epoch after recovery: drop already-consumed
                    # data (reference master_worker.py:762-768)
                    batch = data_api.drop_ids(batch, self._ids_to_skip)
                    if batch is None:
                        continue
                t0 = time.monotonic()
                with tracing.span("step", epoch=epoch, epoch_step=step,
                                  global_step=self.global_step + 1):
                    last_stats = self.run_step(batch)
                dt = time.monotonic() - t0
                self.global_step += 1
                metrics.inc("master_steps_total")
                metrics.observe("master_step_secs", dt)
                token_key = next(
                    (k for k in ("packed_input_ids", "packed_prompts")
                     if k in batch.keys),
                    max(batch.keys, key=batch.total_len))
                n_tokens = batch.total_len(token_key)
                logger.info(
                    "epoch %d step %d (global %d): %.2fs, #tokens %d, %s",
                    epoch, step, self.global_step, dt, n_tokens,
                    {k: {kk: round(vv, 4) for kk, vv in v.items()
                         if isinstance(vv, float)}
                     for k, v in last_stats.items()})
                self._consumed_ids.extend(batch.ids)
                self._maybe_save(steps=1)
                self._maybe_eval(steps=1)
                if (spec.ctl.benchmark_steps is not None
                        and self.global_step >= spec.ctl.benchmark_steps):
                    done = True
                    break
            if done:
                break
            self._ids_to_skip = set()
            self._consumed_ids = []
            self._maybe_save(epochs=1)
            self._maybe_eval(epochs=1)
        self._maybe_save(force=True)
        if tracing.enabled():
            tracing.flush()
            merged = tracing.merge_traces()
            if merged:
                logger.info("Chrome trace written: %s (open in "
                            "Perfetto / chrome://tracing).", merged)
                from realhf_tpu.obs import analyze
                summary = analyze.summarize_path(merged)
                if summary:
                    logger.info("%s (full report: python "
                                "scripts/analyze_trace.py %s)",
                                summary, merged)
        # final metrics snapshot: the poll-loop interval flush never
        # runs here, so a short run would exit with buffered/last
        # gauge values unpersisted
        metrics.flush_final()
        return last_stats
