"""Single-process experiment runner: the minimum end-to-end slice.

Executes an ExperimentSpec's dataflow graph in one process with all
models sharing the local device fleet in "symmetric allocation" (every
MFC on the same mesh), which is the reference's
``allocation_mode=d$Np$Pm$M`` global-hybrid mode
(``experiments/common/common.py:319``). The distributed
master/model-worker runtime adds disjoint sub-meshes and parameter
reallocation on top of the exact same interface calls.

Responsibilities mirrored from the reference master worker
(``system/master_worker.py``): dataset loading and epoch accounting,
topological MFC execution with key remapping, amending results into
the step's data buffer, save/eval frequency control, per-step
throughput logging (tokens + TFLOP/s), and benchmark early exit.
"""

import os
import time
from typing import Dict, Optional

import numpy as np

from realhf_tpu.api import data as data_api
from realhf_tpu.api import model as model_api
from realhf_tpu.api.config import ModelInterfaceType, ModelName
from realhf_tpu.api.dfg import DFG
from realhf_tpu.api.experiment import ExperimentSpec
from realhf_tpu.base import constants, logging, recover, seeding, timeutil
from realhf_tpu.engine.engine import Engine
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf import load_hf_checkpoint
from realhf_tpu.parallel.mesh import MeshContext, make_mesh

logger = logging.getLogger("InlineRunner", "benchmark")


def _build_model(role: str, spec, tokenizer, total_steps: int,
                 devices=None, params_override=None,
                 cfg_override=None) -> model_api.Model:
    from realhf_tpu.parallel.mesh import default_devices

    if params_override is not None:
        # Replica path: reuse the primary's live weights (device_put in
        # Engine.__init__ reshards them) instead of re-reading the
        # checkpoint.
        cfg, params = cfg_override, params_override
    elif spec.path:
        cfg, params = load_hf_checkpoint(
            spec.path, spec.hf_family,
            is_critic=spec.is_critic or spec.init_critic_from_actor)
    else:
        cfg = TransformerConfig(**spec.random_init_config,
                                is_critic=spec.is_critic)
        params = None
    if params_override is None:
        cfg.gradient_checkpointing = spec.gradient_checkpointing
        cfg.compute_dtype = "bfloat16" if spec.bf16 else "float32"
    if params is None:
        params = T.init_params(
            cfg, seeding.derive_key("model_init", role))

    if devices is None:
        devices = default_devices()[:spec.parallel.world_size]
    mesh = make_mesh(spec.parallel, devices=devices)
    ctx = MeshContext(ModelName(role, 0), mesh, spec.parallel)
    engine = Engine(cfg, ctx, params, optimizer=spec.optimizer,
                    total_train_steps=total_steps)
    return model_api.Model(ModelName(role, 0), engine, tokenizer,
                           hf_family=spec.hf_family)


class InlineRunner:

    def __init__(self, spec: ExperimentSpec, recover_mode: str = "disabled"):
        self.spec = spec
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        seeding.set_random_seed(spec.seed)

        # Recovery (reference recover_mode resume, base/recover.py +
        # master_worker.__recover_save:1541): restore step counters and
        # the set of data ids consumed in the interrupted epoch, and
        # redirect trainable models to their latest checkpoints.
        self.recover_mode = recover_mode
        self._recover_info = None
        if recover_mode == "resume" and recover.exists():
            self._recover_info = recover.load()
            logger.info("Resuming from recover info: %s",
                        self._recover_info.recover_start)
            for role, mspec in spec.models.items():
                ckpt = os.path.join(constants.run_save_path(), role)
                if os.path.exists(os.path.join(ckpt, "config.json")):
                    mspec.path = ckpt
                    mspec.random_init_config = None
                    logger.info("Recovered %s from %s", role, ckpt)

        import realhf_tpu.datasets  # noqa: F401 - register datasets
        import realhf_tpu.interfaces  # noqa: F401 - register interfaces

        self.dfg = DFG(spec.mfcs)
        self.tokenizer = spec.tokenizer or (
            data_api.load_hf_tokenizer(spec.tokenizer_path)
            if spec.tokenizer_path else None)

        src = self.dfg.sources[0]
        self.dataset = data_api.make_dataset(
            spec.dataset, seed=spec.seed, dp_rank=0, world_size=1,
            tokenizer_or_path=self.tokenizer)
        self.dataloader = data_api.PackedDataLoader(
            self.dataset, batch_size=src.n_seqs, seed=spec.seed)
        self.eval_dataloader = None
        if spec.eval_dataset is not None:
            eval_ds = data_api.make_dataset(
                spec.eval_dataset, seed=spec.seed, dp_rank=0, world_size=1,
                tokenizer_or_path=self.tokenizer)
            self.eval_dataloader = data_api.PackedDataLoader(
                eval_ds, batch_size=src.n_seqs, shuffle=False)

        steps_per_epoch = len(self.dataloader)
        total_steps = steps_per_epoch * spec.total_train_epochs
        self.models: Dict[str, model_api.Model] = {}
        for role, mspec in spec.models.items():
            self.models[role] = _build_model(
                role, mspec, self.tokenizer, total_steps)

        # Replica engines for MFCs allocated on a different layout than
        # their role's primary (reference resolve_replica_ids,
        # experiments/common/utils.py:126). Replicas never own an
        # optimizer; weights flow from the primary via reallocation.
        from realhf_tpu.parallel.realloc import ReplicaManager
        import dataclasses as _dc
        self.replicas: Dict[str, model_api.Model] = {}
        self.replica_mgr = ReplicaManager()
        for node in self.dfg.nodes:
            alloc = spec.allocations.get(node.name)
            if alloc is None:
                continue
            role = node.role
            primary = self.models[role]
            if alloc.same_layout(primary.engine.ctx.parallel):
                continue
            if node.interface_type == ModelInterfaceType.TRAIN_STEP:
                raise ValueError(
                    f"MFC {node.name}: train MFCs must run on the "
                    "role's primary layout (replicas have no optimizer).")
            mspec = _dc.replace(spec.models[role], parallel=alloc,
                                optimizer=None)
            self.replicas[node.name] = _build_model(
                f"{role}-{node.name}", mspec, self.tokenizer, total_steps,
                params_override=primary.engine.params,
                cfg_override=primary.config)
            logger.info("Created replica for %s: %s (primary %s)",
                        node.name, alloc, primary.engine.ctx.parallel)

        self.interfaces = {}
        for node in self.dfg.nodes:
            self.interfaces[node.name] = model_api.make_interface(
                node.interface_impl)

        ctl = spec.ctl
        self.save_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.save_freq_epochs, freq_step=ctl.save_freq_steps,
            freq_sec=ctl.save_freq_secs)
        self.eval_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.eval_freq_epochs, freq_step=ctl.eval_freq_steps,
            freq_sec=None)
        self.global_step = 0
        self._start_epoch = 0
        self._ids_to_skip = set()
        if self._recover_info is not None:
            self.global_step = self._recover_info.last_step_info.global_step
            self._start_epoch = self._recover_info.recover_start.epoch
            self._ids_to_skip = set(self._recover_info.hash_vals_to_ignore)

    # ------------------------------------------------------------------
    def run_step(self, batch: data_api.SequenceSample) -> Dict[str, Dict]:
        """Execute the full DFG once over one batch; returns per-MFC
        stats (mirrors one master-worker _poll iteration)."""
        stats: Dict[str, Dict] = {}
        data = batch
        for node in self.dfg.topological_order():
            primary = self.models[node.role]
            model = self.replicas.get(node.name, primary)
            if model is not primary:
                # param-realloc pre-hook: refresh the replica's weights
                # from the trainable primary if it has stepped since.
                self.replica_mgr.ensure_fresh(node.role, primary, model)
            itf = self.interfaces[node.name]
            inp = data.select([k for k in node.input_keys if k in data.keys])
            if node.input_key_remap:
                inp.remap_keys_(node.input_key_remap)
            if node.interface_type == ModelInterfaceType.GENERATE:
                out = itf.generate(model, inp, n_mbs=node.n_mbs)
            elif node.interface_type == ModelInterfaceType.INFERENCE:
                out = itf.inference(model, inp, n_mbs=node.n_mbs)
            elif node.interface_type == ModelInterfaceType.TRAIN_STEP:
                out = itf.train_step(model, inp, n_mbs=node.n_mbs)
            else:
                raise NotImplementedError(node.interface_type)
            if isinstance(out, data_api.SequenceSample):
                if node.output_key_remap:
                    out.remap_keys_(node.output_key_remap)
                data.update_(out)
            elif isinstance(out, dict):
                stats[node.name] = out
                if node.log_return_value:
                    logger.info("MFC %s stats: %s", node.name, out)
        return stats

    def _maybe_save(self, epochs: int = 0, steps: int = 0, force=False):
        if not force and not self.save_ctl.check(epochs=epochs, steps=steps):
            return
        for node in self.dfg.nodes:
            if node.interface_type != ModelInterfaceType.TRAIN_STEP:
                continue
            model = self.models[node.role]
            path = f"{constants.run_save_path()}/{node.role}"
            self.interfaces[node.name].save(model, path)
            logger.info("Saved %s to %s", node.role, path)
        # Recover info is only valid paired with the checkpoint it
        # describes (reference couples them in __recover_save), so it
        # is dumped here, never on unsaved steps.
        if self.recover_mode != "disabled":
            recover.dump(recover.RecoverInfo(
                recover_start=recover.StepInfo(
                    epoch=self._cur_epoch,
                    epoch_step=self._cur_epoch_step + 1,
                    global_step=self.global_step),
                last_step_info=recover.StepInfo(
                    epoch=self._cur_epoch,
                    epoch_step=self._cur_epoch_step,
                    global_step=self.global_step),
                hash_vals_to_ignore=list(self._consumed_ids)))

    def _maybe_eval(self, epochs: int = 0, steps: int = 0):
        if self.eval_dataloader is None:
            return
        if not self.eval_ctl.check(epochs=epochs, steps=steps):
            return
        for node in self.dfg.nodes:
            if node.interface_type != ModelInterfaceType.TRAIN_STEP:
                continue
            ev = self.interfaces[node.name].evaluate(
                self.models[node.role], self.eval_dataloader)
            if ev:
                logger.info("Eval %s: %s", node.role, ev)

    def run(self) -> Dict[str, Dict]:
        """Train for the configured epochs; returns the last step stats."""
        spec = self.spec
        last_stats = {}
        done = False
        self._consumed_ids = list(self._ids_to_skip)
        self._cur_epoch = self._start_epoch
        self._cur_epoch_step = 0
        for epoch in range(self._start_epoch, spec.total_train_epochs):
            self._cur_epoch = epoch
            for step, batch in enumerate(self.dataloader):
                self._cur_epoch_step = step
                if self._ids_to_skip:
                    # first epoch after recovery: drop already-consumed
                    # data (reference master_worker.py:762-768)
                    keep = [i for i, x in enumerate(batch.ids)
                            if x not in self._ids_to_skip]
                    if not keep:
                        continue
                    if len(keep) < batch.bs:
                        parts = batch.unpack()
                        from realhf_tpu.api.data import SequenceSample
                        batch = SequenceSample.gather(
                            [parts[i] for i in keep])
                t0 = time.monotonic()
                last_stats = self.run_step(batch)
                dt = time.monotonic() - t0
                self.global_step += 1
                token_key = next(
                    (k for k in ("packed_input_ids", "packed_prompts")
                     if k in batch.keys),
                    max(batch.keys, key=batch.total_len))
                n_tokens = batch.total_len(token_key)
                logger.info(
                    "epoch %d step %d (global %d): %.2fs, #tokens %d, %s",
                    epoch, step, self.global_step, dt, n_tokens,
                    {k: {kk: round(vv, 4) for kk, vv in v.items()
                         if isinstance(vv, float)}
                     for k, v in last_stats.items()})
                self._consumed_ids.extend(batch.ids)
                self._maybe_save(steps=1)
                self._maybe_eval(steps=1)
                if (spec.ctl.benchmark_steps is not None
                        and self.global_step >= spec.ctl.benchmark_steps):
                    done = True
                    break
            if done:
                break
            self._ids_to_skip = set()
            self._consumed_ids = []
            self._maybe_save(epochs=1)
            self._maybe_eval(epochs=1)
        self._maybe_save(force=True)
        return last_stats
