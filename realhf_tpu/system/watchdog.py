"""Controller-side liveness watchdog over worker heartbeats.

Every ``WorkerServer`` publishes a wall-clock heartbeat under
``names.worker_heartbeat`` (``worker_base.py``); the master and the
launcher run a :class:`Watchdog` over the fleet and mark a worker
LOST when its beat goes stale (NFS/memory backends) or its entry
expires (TTL backends). This replaces silent multi-minute
``gather_replies`` hangs with prompt, attributed failure detection:
the raised :class:`WorkerLostError` names the dead worker and the
in-flight MFC.

Also here: :class:`ExclusionBook`, the ``excluded_workers``
bookkeeping for requeue-on-loss -- a flapping worker is kept out of
dispatch for an exponentially growing backoff window (with jitter)
instead of being re-picked the instant its heartbeat returns.

Heartbeats are wall-clock timestamps because watcher and workers live
in different processes (and on pods, different hosts); keep host
clocks NTP-disciplined or widen ``timeout`` accordingly.
"""

import random
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from realhf_tpu.base import logging, name_resolve, names
from realhf_tpu.obs import flight, metrics
from realhf_tpu.system.worker_base import WorkerServerStatus

logger = logging.getLogger("watchdog")

#: Liveness verdicts (heartbeat-level; richer than the control
#: panel's command-status view).
ALIVE = "ALIVE"
PENDING = "PENDING"   # never beat yet, still within the startup grace
LOST = "LOST"
DONE = "DONE"         # terminal status published (COMPLETED/ERROR)


class WorkerLostError(RuntimeError):
    """A worker's heartbeat expired with work attributed to it. The
    message names the worker(s) and the in-flight MFC(s) -- the
    prompt, attributed replacement for a bare TimeoutError after a
    600 s hang."""

    def __init__(self, workers, inflight: Optional[Sequence[str]] = None,
                 detail: str = ""):
        self.workers = sorted({workers} if isinstance(workers, str)
                              else set(workers))
        self.inflight = sorted(set(inflight or ()))
        msg = f"Worker(s) {self.workers} LOST (heartbeat expired)"
        if self.inflight:
            msg += f" with in-flight work: {self.inflight}"
        if detail:
            msg += f". {detail}"
        super().__init__(msg)


class Watchdog:
    """Tracks a fixed worker set's heartbeats through name_resolve.

    ``timeout``: a beat older than this is stale -> LOST.
    ``grace``: a worker that has NEVER beaten gets this long from
    watchdog construction before counting as LOST (process spawn +
    heavy imports happen before the first beat).
    ``poll_interval``: ``poll()`` rate-limits actual store reads to
    this cadence so calling it from a hot master loop is free.
    ``clock``: injectable wall-clock for deterministic tests.
    ``on_lost``: optional callback invoked (with the worker name) on
    the ALIVE->LOST edge -- e.g. ``FleetRouter.notify_lost``, so a
    co-located serving router fails work over immediately instead of
    waiting for the replica's fleet lease to expire.
    """

    def __init__(self, experiment_name: str, trial_name: str,
                 workers: Iterable[str], timeout: float = 20.0,
                 grace: float = 120.0, poll_interval: float = 1.0,
                 clock: Callable[[], float] = time.time,
                 on_lost: Optional[Callable[[str], None]] = None):
        self._exp, self._trial = experiment_name, trial_name
        self.workers = sorted(set(workers))
        self.timeout = timeout
        self.grace = grace
        self.poll_interval = poll_interval
        self._clock = clock
        self._on_lost = on_lost
        self._start = clock()
        self._ever_beat: Dict[str, float] = {}   # worker -> last fresh ts
        self._lost_since: Dict[str, float] = {}
        self._last_poll = 0.0

    # ------------------------------------------------------------------
    def _status_of(self, worker: str) -> Optional[WorkerServerStatus]:
        try:
            return WorkerServerStatus(name_resolve.get(
                names.worker_status(self._exp, self._trial, worker)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    def _verdict(self, worker: str, now: float) -> str:
        try:
            ts = float(name_resolve.get(names.worker_heartbeat(
                self._exp, self._trial, worker)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            ts = None
        if ts is not None:
            # any published beat -- fresh or stale -- proves the
            # worker existed; staleness then means loss, never PENDING
            self._ever_beat.setdefault(worker, ts)
            if now - ts <= self.timeout:
                self._ever_beat[worker] = ts
                return ALIVE
        # silent: either a terminal exit (accounted for), startup lag,
        # or a genuine loss. A PREEMPTED worker that stopped beating
        # exited its grace window gracefully -- accounted for, never
        # LOST (the elastic planner already migrated its work).
        status = self._status_of(worker)
        if status in (WorkerServerStatus.COMPLETED,
                      WorkerServerStatus.ERROR,
                      WorkerServerStatus.PREEMPTED):
            return DONE
        if worker not in self._ever_beat and now - self._start <= max(
                self.grace, self.timeout):
            return PENDING
        return LOST

    def check(self) -> Dict[str, str]:
        """Full liveness snapshot {worker: ALIVE|PENDING|LOST|DONE},
        updating loss bookkeeping."""
        now = self._clock()
        out = {}
        for w in self.workers:
            v = self._verdict(w, now)
            out[w] = v
            if v == LOST:
                if w not in self._lost_since:
                    self._lost_since[w] = now
                    metrics.inc("watchdog_lost_total", worker=w)
                    flight.record("worker_lost", worker=w)
                    logger.error(
                        "Worker %s LOST: no heartbeat for > %.1fs "
                        "(last beat %s).", w, self.timeout,
                        "%.1fs ago" % (now - self._ever_beat[w])
                        if w in self._ever_beat else "never seen")
                    if self._on_lost is not None:
                        try:
                            self._on_lost(w)
                        except Exception as e:  # noqa: BLE001 - the
                            # hook must not break liveness accounting
                            logger.error("on_lost hook failed for "
                                         "%s: %r", w, e)
            elif w in self._lost_since:
                del self._lost_since[w]
                metrics.inc("watchdog_flap_recovered_total", worker=w)
                logger.warning("Worker %s heartbeat returned (flap).", w)
        counts = {v: 0 for v in (ALIVE, PENDING, LOST, DONE)}
        for v in out.values():
            counts[v] += 1
        for verdict, n in counts.items():
            metrics.set_gauge("watchdog_workers", n,
                              state=verdict.lower())
        return out

    def poll(self) -> List[str]:
        """Rate-limited edge-triggered check: workers that became LOST
        since the previous poll. Cheap to call every master loop."""
        now = self._clock()
        if now - self._last_poll < self.poll_interval:
            return []
        self._last_poll = now
        before = set(self._lost_since)
        self.check()
        return sorted(set(self._lost_since) - before)

    def is_alive(self, worker: str) -> bool:
        return self._verdict(worker, self._clock()) in (ALIVE, PENDING)

    def has_fresh_beat(self, worker: str) -> bool:
        """True when the worker's heartbeat is within ``timeout`` of
        now -- the rejoin signal for elastic re-expansion (a DONE /
        PREEMPTED verdict can coexist with a fresh beat while a
        relaunched incarnation spins up)."""
        try:
            ts = float(name_resolve.get(names.worker_heartbeat(
                self._exp, self._trial, worker)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return False
        return self._clock() - ts <= self.timeout

    def preempt_notice(self, worker: str):
        """The worker's active preemption notice as ``(ts, grace)``
        wall-clock seconds, or None. Published by
        ``WorkerServer.publish_preempt_notice`` on SIGTERM/SIGUSR1 or
        an injected ``preempt`` fault; cleared by the worker's next
        incarnation at startup."""
        try:
            raw = name_resolve.get(names.worker_preempt(
                self._exp, self._trial, worker))
            ts_s, grace_s = str(raw).split(":", 1)
            return float(ts_s), float(grace_s)
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    def preempt_notices(self) -> Dict[str, tuple]:
        """All active preemption notices {worker: (ts, grace)}."""
        out = {}
        for w in self.workers:
            n = self.preempt_notice(w)
            if n is not None:
                out[w] = n
        return out

    def lost_workers(self) -> List[str]:
        return sorted(self._lost_since)

    def lost_longer_than(self, secs: float) -> List[str]:
        """Workers continuously LOST for more than ``secs`` (as
        observed by check/poll calls) -- the fatal-deadline input."""
        now = self._clock()
        return sorted(w for w, t in self._lost_since.items()
                      if now - t > secs)

    def raise_if_lost(self, workers: Optional[Iterable[str]] = None,
                      inflight: Optional[Sequence[str]] = None):
        """Convenience liveness gate for blocking waits (the
        ``check_liveness`` hook of ``gather_replies``): refresh the
        snapshot and raise WorkerLostError if any of ``workers``
        (default: all) is lost."""
        self.check()
        sel = set(workers) if workers is not None else set(self.workers)
        lost = sel & set(self._lost_since)
        if lost:
            raise WorkerLostError(lost, inflight=inflight)


class ExclusionBook:
    """``excluded_workers`` bookkeeping: each loss excludes the worker
    from dispatch for ``base * factor**(losses-1)`` seconds (capped,
    jittered), so a flapping worker is not re-picked the moment its
    heartbeat reappears."""

    def __init__(self, base: float = 5.0, factor: float = 2.0,
                 max_delay: float = 120.0, jitter: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.base, self.factor = base, factor
        self.max_delay, self.jitter = max_delay, jitter
        self._clock = clock
        self._rng = rng or random
        self._losses: Dict[str, int] = {}
        self._until: Dict[str, float] = {}

    def exclude(self, worker: str) -> float:
        """Record one loss; returns the exclusion window length."""
        n = self._losses.get(worker, 0) + 1
        self._losses[worker] = n
        d = min(self.base * self.factor ** (n - 1), self.max_delay)
        d += self._rng.uniform(0.0, self.jitter * d)
        self._until[worker] = self._clock() + d
        logger.warning("Worker %s excluded from dispatch for %.1fs "
                       "(loss #%d).", worker, d, n)
        return d

    def is_excluded(self, worker: str) -> bool:
        until = self._until.get(worker)
        if until is None:
            return False
        if self._clock() >= until:
            del self._until[worker]  # window over; loss count persists
            return False
        return True

    def excluded(self) -> List[str]:
        return sorted(w for w in list(self._until) if self.is_excluded(w))

    def loss_count(self, worker: str) -> int:
        return self._losses.get(worker, 0)

    def forgive(self, worker: str):
        """Clear history (e.g. after a long stretch of good health)."""
        self._losses.pop(worker, None)
        self._until.pop(worker, None)
