"""Controller-side liveness watchdog over worker heartbeats.

Every ``WorkerServer`` publishes a wall-clock heartbeat under
``names.worker_heartbeat`` (``worker_base.py``); the master and the
launcher run a :class:`Watchdog` over the fleet and mark a worker
LOST when its beat goes stale (NFS/memory backends) or its entry
expires (TTL backends). This replaces silent multi-minute
``gather_replies`` hangs with prompt, attributed failure detection:
the raised :class:`WorkerLostError` names the dead worker and the
in-flight MFC.

Also here: :class:`ExclusionBook`, the ``excluded_workers``
bookkeeping for requeue-on-loss -- a flapping worker is kept out of
dispatch for an exponentially growing backoff window (with jitter)
instead of being re-picked the instant its heartbeat returns.

Heartbeats are wall-clock timestamps because watcher and workers live
in different processes (and on pods, different hosts); keep host
clocks NTP-disciplined or widen ``timeout`` accordingly.
"""

import random
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from realhf_tpu.base import logging, name_resolve, names
from realhf_tpu.obs import flight, metrics
from realhf_tpu.system.worker_base import WorkerServerStatus

logger = logging.getLogger("watchdog")

#: Liveness verdicts (heartbeat-level; richer than the control
#: panel's command-status view).
ALIVE = "ALIVE"
PENDING = "PENDING"   # never beat yet, still within the startup grace
LOST = "LOST"
DONE = "DONE"         # terminal status published (COMPLETED/ERROR)


class WorkerLostError(RuntimeError):
    """A worker's heartbeat expired with work attributed to it. The
    message names the worker(s) and the in-flight MFC(s) -- the
    prompt, attributed replacement for a bare TimeoutError after a
    600 s hang."""

    def __init__(self, workers, inflight: Optional[Sequence[str]] = None,
                 detail: str = ""):
        self.workers = sorted({workers} if isinstance(workers, str)
                              else set(workers))
        self.inflight = sorted(set(inflight or ()))
        msg = f"Worker(s) {self.workers} LOST (heartbeat expired)"
        if self.inflight:
            msg += f" with in-flight work: {self.inflight}"
        if detail:
            msg += f". {detail}"
        super().__init__(msg)


class Watchdog:
    """Tracks a fixed worker set's heartbeats through name_resolve.

    ``timeout``: a beat older than this is stale -> LOST.
    ``grace``: a worker that has NEVER beaten gets this long from
    watchdog construction before counting as LOST (process spawn +
    heavy imports happen before the first beat).
    ``poll_interval``: ``poll()`` rate-limits actual store reads to
    this cadence so calling it from a hot master loop is free.
    ``clock``: injectable wall-clock for deterministic tests.
    ``on_lost``: optional callback invoked (with the worker name) on
    the ALIVE->LOST edge -- e.g. ``FleetRouter.notify_lost``, so a
    co-located serving router fails work over immediately instead of
    waiting for the replica's fleet lease to expire.

    **Host failure domains** (``system/pod.py``): with ``host_of`` (a
    ``worker -> host id | None`` callable, e.g.
    ``pod.name_resolve_host_lookup``), losses aggregate per host. TPU
    pods fail at VM granularity -- one preemption takes out every
    worker on the host simultaneously -- so when ALL workers of a host
    go stale within ``host_window`` seconds of each other the loss is
    attributed as ONE ``HOST_LOST`` (one flight event, one counter,
    one log line, the ``on_host_lost`` callback) instead of N
    independent worker losses. ``lost_workers``/``poll`` still report
    every worker immediately (the master must requeue their work
    without delay); only the *attribution* is aggregated, and an
    individual worker's event is deferred at most ``host_window``
    seconds (default: ``timeout``) while its host's fate resolves.
    """

    def __init__(self, experiment_name: str, trial_name: str,
                 workers: Iterable[str], timeout: float = 20.0,
                 grace: float = 120.0, poll_interval: float = 1.0,
                 clock: Callable[[], float] = time.time,
                 on_lost: Optional[Callable[[str], None]] = None,
                 host_of: Optional[
                     Callable[[str], Optional[str]]] = None,
                 host_window: Optional[float] = None,
                 on_host_lost: Optional[
                     Callable[[str, List[str]], None]] = None):
        self._exp, self._trial = experiment_name, trial_name
        self.workers = sorted(set(workers))
        self.timeout = timeout
        self.grace = grace
        self.poll_interval = poll_interval
        self._clock = clock
        self._on_lost = on_lost
        self._host_of = host_of
        self.host_window = timeout if host_window is None \
            else host_window
        self._on_host_lost = on_host_lost
        self._start = clock()
        self._ever_beat: Dict[str, float] = {}   # worker -> last fresh ts
        self._lost_since: Dict[str, float] = {}
        self._last_poll = 0.0
        # host-domain bookkeeping: hosts currently whole-lost, the
        # attribution history, and lost workers whose individual event
        # is deferred while their host's fate resolves
        self._host_lost_since: Dict[str, float] = {}
        self._host_lost_log: List[Dict] = []
        self._unattributed: Dict[str, float] = {}
        # incarnation fencing: last boot id seen per worker (beats are
        # "<ts>:<boot-id>"; legacy plain-ts beats carry none)
        self._boot_ids: Dict[str, str] = {}
        self._lost_reason: Dict[str, str] = {}
        #: per-worker watch-start for DYNAMIC membership (autoscale):
        #: a replica added mid-run gets its startup grace from its add
        #: time, not from watchdog construction
        self._added_at: Dict[str, float] = {}

    # -- dynamic membership (closed-loop autoscaling) ------------------
    def add_workers(self, workers: Iterable[str]):
        """Start watching more workers (scale-up). Each gets the
        startup grace measured from NOW."""
        now = self._clock()
        for w in workers:
            if w not in self.workers:
                self.workers.append(w)
                self._added_at[w] = now
        self.workers.sort()

    def remove_workers(self, workers: Iterable[str]):
        """Stop watching workers (planned scale-down): their pending
        exit must not read as a loss. Clears all bookkeeping so a
        later re-add starts clean."""
        drop = set(workers)
        self.workers = [w for w in self.workers if w not in drop]
        for w in drop:
            for d in (self._ever_beat, self._lost_since,
                      self._boot_ids, self._lost_reason,
                      self._unattributed, self._added_at):
                d.pop(w, None)

    # ------------------------------------------------------------------
    def _status_of(self, worker: str) -> Optional[WorkerServerStatus]:
        try:
            return WorkerServerStatus(name_resolve.get(
                names.worker_status(self._exp, self._trial, worker)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    def _read_beat(self, worker: str):
        """The worker's published heartbeat as ``(ts, boot_id)``.
        Beats are ``"<ts>:<boot-id>"`` (worker_base.py); a legacy
        plain-timestamp beat yields ``boot_id=None``."""
        try:
            raw = str(name_resolve.get(names.worker_heartbeat(
                self._exp, self._trial, worker)))
            ts_s, _, boot = raw.partition(":")
            return float(ts_s), (boot or None)
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None, None

    def _relaunch_edge(self, worker: str, boot: Optional[str]) -> bool:
        """True when the worker's boot id CHANGED since last seen --
        the previous incarnation died and was relaunched faster than
        its beat could ever go stale. Without this fence the dead
        process is a silent message blackhole: requests PUB'd to it
        are gone, yet the successor's fresh beat hides the death."""
        if boot is None:
            return False
        prev = self._boot_ids.get(worker)
        self._boot_ids[worker] = boot
        return prev is not None and prev != boot

    def _verdict(self, worker: str, now: float) -> str:
        return self._verdict_with(worker, now,
                                  self._read_beat(worker)[0])

    def _verdict_with(self, worker: str, now: float,
                      ts: Optional[float]) -> str:
        if ts is not None:
            # any published beat -- fresh or stale -- proves the
            # worker existed; staleness then means loss, never PENDING
            self._ever_beat.setdefault(worker, ts)
            if now - ts <= self.timeout:
                self._ever_beat[worker] = ts
                return ALIVE
        # silent: either a terminal exit (accounted for), startup lag,
        # or a genuine loss. A PREEMPTED worker that stopped beating
        # exited its grace window gracefully -- accounted for, never
        # LOST (the elastic planner already migrated its work).
        status = self._status_of(worker)
        if status in (WorkerServerStatus.COMPLETED,
                      WorkerServerStatus.ERROR,
                      WorkerServerStatus.PREEMPTED):
            return DONE
        start = self._added_at.get(worker, self._start)
        if worker not in self._ever_beat and now - start <= max(
                self.grace, self.timeout):
            return PENDING
        return LOST

    def _host(self, worker: str) -> Optional[str]:
        if self._host_of is None:
            return None
        try:
            return self._host_of(worker)
        except Exception:  # noqa: BLE001 - mapping must not break
            # liveness accounting
            return None

    def _host_members(self, host: str) -> List[str]:
        return [w for w in self.workers if self._host(w) == host]

    def _emit_worker_lost(self, w: str, now: float):
        reason = self._lost_reason.get(w, "stale")
        metrics.inc("watchdog_lost_total", worker=w)
        flight.record("worker_lost", worker=w, reason=reason)
        if reason == "relaunched":
            logger.error(
                "Worker %s LOST (incarnation changed): relaunched "
                "faster than the %.1fs staleness timeout -- its "
                "predecessor's in-flight work is gone.", w,
                self.timeout)
        else:
            logger.error(
                "Worker %s LOST: no heartbeat for > %.1fs "
                "(last beat %s).", w, self.timeout,
                "%.1fs ago" % (now - self._ever_beat[w])
                if w in self._ever_beat else "never seen")

    def _attribute_losses(self, new_lost: List[str], now: float):
        """Emit loss events: whole-host losses as ONE ``host_lost``
        event; lone losses (or hosts that never fully fail within
        ``host_window``) as individual ``worker_lost`` events."""
        for w in new_lost:
            h = self._host(w)
            if h is None or len(self._host_members(h)) <= 1:
                self._emit_worker_lost(w, now)
            else:
                # hold the individual event while the host's fate
                # resolves (at most host_window seconds)
                self._unattributed[w] = now
        # host completion: every member lost, within one window
        hosts = {self._host(w) for w in self._unattributed}
        for h in sorted(hosts - {None} - set(self._host_lost_since)):
            members = self._host_members(h)
            ts = [self._lost_since.get(m) for m in members]
            if any(t is None for t in ts):
                continue
            if max(ts) - min(ts) > self.host_window:
                continue
            self._host_lost_since[h] = now
            self._host_lost_log.append(dict(
                host=h, workers=sorted(members), ts=now))
            metrics.inc("watchdog_host_lost_total", host=h)
            flight.record("host_lost", host=h,
                          workers=sorted(members))
            logger.error(
                "HOST %s LOST: all %d workers (%s) went stale within "
                "%.1fs -- attributing as one host failure.", h,
                len(members), sorted(members), self.host_window)
            for m in members:
                self._unattributed.pop(m, None)
            if self._on_host_lost is not None:
                try:
                    self._on_host_lost(h, sorted(members))
                except Exception as e:  # noqa: BLE001
                    logger.error("on_host_lost hook failed for %s: "
                                 "%r", h, e)
        # deferral expiry: the host never completed -- emit the
        # individual events after all
        for w, t0 in sorted(self._unattributed.items()):
            if now - t0 > self.host_window:
                del self._unattributed[w]
                self._emit_worker_lost(w, now)

    def check(self) -> Dict[str, str]:
        """Full liveness snapshot {worker: ALIVE|PENDING|LOST|DONE},
        updating loss bookkeeping."""
        now = self._clock()
        out = {}
        new_lost = []

        def _edge(w, reason):
            self._lost_since[w] = now
            self._lost_reason[w] = reason
            new_lost.append(w)
            if self._on_lost is not None:
                try:
                    self._on_lost(w)
                except Exception as e:  # noqa: BLE001 - the hook
                    # must not break liveness accounting
                    logger.error("on_lost hook failed for %s: %r",
                                 w, e)

        for w in self.workers:
            ts, boot = self._read_beat(w)
            relaunched = self._relaunch_edge(w, boot)
            v = self._verdict_with(w, now, ts)
            out[w] = v
            if v == LOST:
                if w not in self._lost_since:
                    _edge(w, "stale")
            elif w in self._lost_since:
                del self._lost_since[w]
                self._lost_reason.pop(w, None)
                self._unattributed.pop(w, None)
                h = self._host(w)
                if h is not None and h in self._host_lost_since:
                    # a member returned: the host as a whole is back
                    # in play (a second full loss re-attributes)
                    del self._host_lost_since[h]
                metrics.inc("watchdog_flap_recovered_total", worker=w)
                logger.warning("Worker %s heartbeat returned (flap).", w)
            elif relaunched:
                # incarnation fence: the predecessor died and was
                # replaced FASTER than its beat could go stale --
                # report a one-check loss edge (the master requeues
                # the dead incarnation's in-flight work and re-routes)
                # that flap-recovers on the next check
                _edge(w, "relaunched")
        self._attribute_losses(new_lost, now)
        counts = {v: 0 for v in (ALIVE, PENDING, LOST, DONE)}
        for v in out.values():
            counts[v] += 1
        for verdict, n in counts.items():
            metrics.set_gauge("watchdog_workers", n,
                              state=verdict.lower())
        return out

    def lost_hosts(self) -> List[str]:
        """Hosts currently attributed as whole-lost."""
        return sorted(self._host_lost_since)

    def host_lost_events(self) -> List[Dict]:
        """Attribution history: one entry per HOST_LOST verdict
        ({host, workers, ts}), surviving flap recoveries."""
        return [dict(e) for e in self._host_lost_log]

    def poll(self) -> List[str]:
        """Rate-limited edge-triggered check: workers that became LOST
        since the previous poll. Cheap to call every master loop."""
        now = self._clock()
        if now - self._last_poll < self.poll_interval:
            return []
        self._last_poll = now
        before = set(self._lost_since)
        self.check()
        return sorted(set(self._lost_since) - before)

    def is_alive(self, worker: str) -> bool:
        return self._verdict(worker, self._clock()) in (ALIVE, PENDING)

    def has_fresh_beat(self, worker: str) -> bool:
        """True when the worker's heartbeat is within ``timeout`` of
        now -- the rejoin signal for elastic re-expansion (a DONE /
        PREEMPTED verdict can coexist with a fresh beat while a
        relaunched incarnation spins up)."""
        ts, _boot = self._read_beat(worker)
        return ts is not None and self._clock() - ts <= self.timeout

    def preempt_notice(self, worker: str):
        """The worker's active preemption notice as ``(ts, grace)``
        wall-clock seconds, or None. Published by
        ``WorkerServer.publish_preempt_notice`` on SIGTERM/SIGUSR1 or
        an injected ``preempt`` fault; cleared by the worker's next
        incarnation at startup."""
        try:
            raw = name_resolve.get(names.worker_preempt(
                self._exp, self._trial, worker))
            ts_s, grace_s = str(raw).split(":", 1)
            return float(ts_s), float(grace_s)
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    def preempt_notices(self) -> Dict[str, tuple]:
        """All active preemption notices {worker: (ts, grace)}."""
        out = {}
        for w in self.workers:
            n = self.preempt_notice(w)
            if n is not None:
                out[w] = n
        return out

    def lost_workers(self) -> List[str]:
        return sorted(self._lost_since)

    def lost_longer_than(self, secs: float) -> List[str]:
        """Workers continuously LOST for more than ``secs`` (as
        observed by check/poll calls) -- the fatal-deadline input."""
        now = self._clock()
        return sorted(w for w, t in self._lost_since.items()
                      if now - t > secs)

    def raise_if_lost(self, workers: Optional[Iterable[str]] = None,
                      inflight: Optional[Sequence[str]] = None):
        """Convenience liveness gate for blocking waits (the
        ``check_liveness`` hook of ``gather_replies``): refresh the
        snapshot and raise WorkerLostError if any of ``workers``
        (default: all) is lost."""
        self.check()
        sel = set(workers) if workers is not None else set(self.workers)
        lost = sel & set(self._lost_since)
        if lost:
            raise WorkerLostError(lost, inflight=inflight)


class ExclusionBook:
    """``excluded_workers`` bookkeeping: each loss excludes the worker
    from dispatch for ``base * factor**(losses-1)`` seconds (capped,
    jittered), so a flapping worker is not re-picked the moment its
    heartbeat reappears.

    With ``host_of`` (``system/pod.py`` host domains) the bookkeeping
    keys on the HOST: all workers of a flapping host share one backoff
    entry, and the N near-simultaneous losses a host failure produces
    (within ``coalesce_secs`` of each other) count as ONE loss -- a
    preempted VM must not exponentially bury its own workers N deep.
    Forgiving any member forgives the host."""

    def __init__(self, base: float = 5.0, factor: float = 2.0,
                 max_delay: float = 120.0, jitter: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None,
                 host_of: Optional[
                     Callable[[str], Optional[str]]] = None,
                 coalesce_secs: float = 5.0):
        self.base, self.factor = base, factor
        self.max_delay, self.jitter = max_delay, jitter
        self._clock = clock
        self._rng = rng or random
        self._host_of = host_of
        self.coalesce_secs = coalesce_secs
        self._losses: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        self._last_loss: Dict[str, float] = {}

    def _key(self, worker: str) -> str:
        if self._host_of is not None:
            try:
                h = self._host_of(worker)
            except Exception:  # noqa: BLE001 - never break dispatch
                h = None
            if h is not None:
                return h
        return worker

    def exclude(self, worker: str) -> float:
        """Record one loss; returns the exclusion window length. A
        loss against an already-hit host within ``coalesce_secs`` is
        the SAME failure event: no extra loss count, shared window."""
        key = self._key(worker)
        now = self._clock()
        last = self._last_loss.get(key)
        if key != worker and last is not None \
                and now - last <= self.coalesce_secs:
            remaining = max(0.0, self._until.get(key, now) - now)
            logger.info(
                "Worker %s loss coalesced into host %s's existing "
                "exclusion (%.1fs left).", worker, key, remaining)
            return remaining
        n = self._losses.get(key, 0) + 1
        self._losses[key] = n
        self._last_loss[key] = now
        d = min(self.base * self.factor ** (n - 1), self.max_delay)
        d += self._rng.uniform(0.0, self.jitter * d)
        self._until[key] = now + d
        logger.warning("%s %s excluded from dispatch for %.1fs "
                       "(loss #%d).",
                       "Host" if key != worker else "Worker", key, d, n)
        return d

    def is_excluded(self, worker: str) -> bool:
        key = self._key(worker)
        until = self._until.get(key)
        if until is None:
            return False
        if self._clock() >= until:
            del self._until[key]  # window over; loss count persists
            return False
        return True

    def excluded(self) -> List[str]:
        """Currently-excluded keys (host ids for host-keyed entries,
        else worker names)."""
        return sorted(k for k in list(self._until) if self.is_excluded(k))

    def loss_count(self, worker: str) -> int:
        return self._losses.get(self._key(worker), 0)

    def forgive(self, worker: str):
        """Clear history (e.g. after a long stretch of good health).
        Host-keyed books forgive the whole host."""
        key = self._key(worker)
        self._losses.pop(key, None)
        self._until.pop(key, None)
        self._last_loss.pop(key, None)
