"""Closed-loop fleet autoscaling: suggestion -> action.

PR 9's :class:`~realhf_tpu.system.elastic.GrowAdvisor` only logged
"you should scale up"; this module closes the loop
(docs/serving.md "Autoscaling"). An :class:`AutoscaleController`
drives an :class:`~realhf_tpu.system.elastic.AutoscalePolicy` with
live fleet signals and acts on its decisions through a small
*actuator* interface, so the same controller runs:

- in the launcher (``apps.main.run_serve``): the actuator submits new
  ``GenServerWorker`` processes through the
  :class:`~realhf_tpu.system.pod.PodController` and retires replicas
  by commanding their graceful exit (drain -> bounce -> harvest ->
  lease release -> process reaped);
- in-process (``scripts/bench_serving.py`` bursty harness,
  ``scripts/chaos_drill.py`` churn schedules): the actuator spawns
  ``RolloutServer`` replicas on threads.

Scale-UP: spawn a replica under the next free name; it registers a
fresh lease + fencing epoch in the
:class:`~realhf_tpu.serving.fleet.FleetRegistry` and the
``FleetRouter`` discovers it on its next registry poll -- no router
restart, no client change. Scale-DOWN: the victim is FIRST marked
``retiring`` in the registry (the router immediately stops
dispatching there and will treat the departure as planned -- no
breaker trip, no failover storm), then told to drain: queued requests
bounce as ``draining``, in-flight sequences are harvested (or, past
the hard drain deadline, force-fenced with explicit terminals the
router shops to survivors), the lease is released, and the process is
reaped. No request is ever orphaned by a scale event.

The controller itself is single-threaded and non-blocking: call
:meth:`AutoscaleController.step` from the supervising loop. It spawns
NO threads of its own.
"""

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

from realhf_tpu.base import logging
from realhf_tpu.obs import flight, metrics
from realhf_tpu.system.elastic import AutoscalePolicy, AutoscaleSignals, \
    ScaleDecision

logger = logging.getLogger("autoscale", "system")


class ReplicaActuator:
    """What the controller needs from the environment to act. Duck
    typing is fine; this base class just documents the contract (and
    lets tests subclass)."""

    def spawn(self, name: str):
        """Begin bringing up one replica under ``name`` (async OK:
        the controller watches the fleet registry for its lease)."""
        raise NotImplementedError

    def retire(self, name: str):
        """Begin a graceful retire: drain (bounce queued, finish
        in-flight, release the lease) then shut the replica down.
        Must not block the caller for the full drain."""
        raise NotImplementedError

    def gone(self, name: str) -> bool:
        """True once the replica's process/thread has fully exited."""
        raise NotImplementedError

    def reap(self, name: str):
        """Force-stop a replica that failed to spawn or failed to
        retire within its deadline. Best effort, must not raise."""
        raise NotImplementedError


@dataclasses.dataclass
class ScaleEvent:
    """One controller action, kept for payloads/tests (flight events
    and metrics are the durable record)."""
    t: float
    action: str          # spawn | retire | retired | spawn_failed | ...
    replica: str
    n_replicas: int
    reason: str = ""


class AutoscaleController:
    """Drive policy decisions into fleet actions (module docstring).

    ``registry`` is the fleet's :class:`FleetRegistry`: the controller
    marks scale-down victims ``retiring`` there *before* telling them
    to drain (closing the router race), and uses the lease subtree to
    confirm a spawned replica came up.

    Replica naming: managed replicas are ``{prefix}/{index}``; new
    spawns take the next index above everything ever managed, so a
    name is never reused within a run (fencing epochs make reuse safe,
    but unique names keep flight records unambiguous).
    """

    def __init__(self, policy: AutoscalePolicy,
                 actuator: ReplicaActuator, registry, *,
                 initial: Sequence[str] = (),
                 name_prefix: str = "gen_server",
                 spawn_deadline_secs: float = 180.0,
                 retire_deadline_secs: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.actuator = actuator
        self.registry = registry
        self.name_prefix = name_prefix
        self.spawn_deadline_secs = spawn_deadline_secs
        self.retire_deadline_secs = retire_deadline_secs
        self._clock = clock
        self._replicas: List[str] = list(initial)
        self._booting: Dict[str, float] = {}
        self._retiring: Dict[str, float] = {}
        self._reaped: set = set()
        self._next_index = 1 + max(
            [self._index_of(n) for n in self._replicas] + [-1])
        self.events: List[ScaleEvent] = []

    @staticmethod
    def _index_of(name: str) -> int:
        tail = name.rsplit("/", 1)[-1]
        return int(tail) if tail.isdigit() else -1

    # -- views ---------------------------------------------------------
    @property
    def n_replicas(self) -> int:
        """Replicas the policy should size against: managed and not
        on their way out (booting ones count -- a decision was already
        spent on them)."""
        return len(self._replicas) - len(self._retiring)

    def replicas(self) -> List[str]:
        return list(self._replicas)

    def retiring(self) -> List[str]:
        return sorted(self._retiring)

    def forget(self, name: str):
        """A managed replica died outside the controller's doing (a
        tolerated fleet death): drop it from capacity accounting so
        the policy sizes against reality -- load that needed it will
        re-fire the scale-up trigger."""
        if name in self._replicas:
            self._replicas.remove(name)
        self._booting.pop(name, None)
        self._retiring.pop(name, None)
        self._reaped.discard(name)
        self._record("died", name)

    def busy(self) -> bool:
        """A scale action is still in flight (boot or drain): the
        supervising loop may want to hold further decisions."""
        return bool(self._booting or self._retiring)

    # -- one supervision tick ------------------------------------------
    def step(self, signals: AutoscaleSignals, **ctx) -> ScaleDecision:
        """Advance in-flight transitions, feed the policy one
        observation (``n_replicas`` is overwritten with the
        controller's own view), and act on its decision."""
        self._poll_transitions()
        signals = dataclasses.replace(signals,
                                      n_replicas=self.n_replicas)
        decision = self.policy.observe(signals, **ctx)
        if decision.action == "up":
            self._scale_up(decision, ctx)
        elif decision.action == "down":
            self._scale_down(decision, ctx)
        metrics.set_gauge("serving_autoscale_replicas",
                          self.n_replicas)
        return decision

    def _record(self, action: str, replica: str, reason: str = ""):
        self.events.append(ScaleEvent(
            t=self._clock(), action=action, replica=replica,
            n_replicas=self.n_replicas, reason=reason))

    def _poll_transitions(self):
        now = self._clock()
        live = set(self.registry.replicas()) \
            if self.registry is not None else None
        for name, t0 in sorted(self._booting.items()):
            if live is not None and name in live:
                del self._booting[name]
                self._record("up_live", name)
                flight.record("autoscale_replica_up", replica=name,
                              boot_secs=round(now - t0, 3))
                logger.info("Autoscale: replica %s is up (%.1fs).",
                            name, now - t0)
            elif now - t0 > self.spawn_deadline_secs:
                # the spawn never registered: write it off so the
                # policy can try again (capacity stays honest)
                del self._booting[name]
                if name in self._replicas:
                    self._replicas.remove(name)
                metrics.inc("serving_autoscale_spawn_failed_total")
                flight.record("autoscale_spawn_failed", replica=name,
                              deadline_secs=self.spawn_deadline_secs)
                logger.error(
                    "Autoscale: replica %s failed to register within "
                    "%.0fs; reaping.", name, self.spawn_deadline_secs)
                self._record("spawn_failed", name)
                self.actuator.reap(name)
        for name, t0 in sorted(self._retiring.items()):
            if self.actuator.gone(name):
                del self._retiring[name]
                self._reaped.discard(name)
                if name in self._replicas:
                    self._replicas.remove(name)
                self._record("retired", name)
                flight.record("autoscale_replica_retired",
                              replica=name,
                              drain_secs=round(now - t0, 3))
                logger.info("Autoscale: replica %s retired (%.1fs).",
                            name, now - t0)
            elif now - t0 > self.retire_deadline_secs \
                    and name not in self._reaped:
                # drain overstayed its welcome: force-stop once, keep
                # polling for the exit
                self._reaped.add(name)
                flight.record("autoscale_retire_forced", replica=name,
                              deadline_secs=self.retire_deadline_secs)
                logger.warning(
                    "Autoscale: replica %s still draining after "
                    "%.0fs; force-stopping.", name,
                    self.retire_deadline_secs)
                self.actuator.reap(name)
        if self.registry is not None \
                and hasattr(self.registry, "gc_retiring"):
            # sweep consumed retiring/ markers every tick so repeated
            # scale-down cycles never accumulate them when no router
            # observes the departure (FleetRegistry.gc_retiring)
            self.registry.gc_retiring()

    def _scale_up(self, decision: ScaleDecision, ctx: Dict):
        name = f"{self.name_prefix}/{self._next_index}"
        self._next_index += 1
        try:
            self.actuator.spawn(name)
        except Exception as e:  # noqa: BLE001 - a failed spawn must
            # not kill the supervising loop; the policy will re-fire
            metrics.inc("serving_autoscale_spawn_failed_total")
            flight.record("autoscale_spawn_failed", replica=name,
                          error=repr(e))
            logger.error("Autoscale: spawn of %s failed: %r", name, e)
            self._record("spawn_failed", name, reason=repr(e))
            return
        self._replicas.append(name)
        self._booting[name] = self._clock()
        self._record("spawn", name, reason=decision.reason)
        flight.record("autoscale_spawn", replica=name,
                      target=decision.target, reason=decision.reason,
                      **ctx)

    def _choose_victim(self) -> Optional[str]:
        """Newest-first (LIFO): the most recently added replica goes
        first -- it holds the least prefix-cache/affinity value, and a
        spike's extra capacity unwinds in reverse order. Replicas
        already booting or retiring are not candidates."""
        cands = [n for n in self._replicas
                 if n not in self._retiring and n not in self._booting]
        if not cands:
            return None
        return max(cands, key=self._index_of)

    def _scale_down(self, decision: ScaleDecision, ctx: Dict):
        victim = self._choose_victim()
        if victim is None:
            logger.info("Autoscale: down decision with no drainable "
                        "replica (all booting/retiring); holding.")
            return
        # ORDER MATTERS: mark retiring BEFORE the drain command, so
        # the router stops dispatching to the victim before its queue
        # starts bouncing (and classifies the departure as planned)
        if self.registry is not None:
            self.registry.mark_retiring(victim)
        try:
            self.actuator.retire(victim)
        except Exception as e:  # noqa: BLE001 - same contract as spawn
            flight.record("autoscale_retire_failed", replica=victim,
                          error=repr(e))
            logger.error("Autoscale: retire of %s failed: %r; "
                         "force-stopping.", victim, e)
            self.actuator.reap(victim)
        self._retiring[victim] = self._clock()
        self._record("retire", victim, reason=decision.reason)
        flight.record("autoscale_retire", replica=victim,
                      target=decision.target, reason=decision.reason,
                      **ctx)
