"""Model hosting shared by the inline runner and the model worker.

Owns, for a set of model roles on the local device fleet: the primary
engines (with optimizers for trainable roles), per-MFC weight replicas
on alternative layouts (reference ``resolve_replica_ids``,
experiments/common/utils.py:126), algorithm interfaces, and MFC
execution including the replica-refresh (param-realloc) and offload
hooks around each call (reference ``model_worker.handle_all_pre_hooks``
/ post hooks, model_worker.py:483-552).
"""

import dataclasses as _dc
import os
from typing import Dict, List, Optional

from realhf_tpu.api import data as data_api
from realhf_tpu.api import model as model_api
from realhf_tpu.api.config import ModelInterfaceType, ModelName
from realhf_tpu.api.dfg import MFCDef, OffloadHook, ParamReallocHook
from realhf_tpu.base import constants, logging, seeding
from realhf_tpu.engine.engine import Engine
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf import load_hf_checkpoint
from realhf_tpu.parallel.mesh import MeshContext, make_mesh
from realhf_tpu.parallel.realloc import ReplicaManager

logger = logging.getLogger("model_host", "benchmark")


# Auto streamed-load size cutoff (ModelSpec.streamed_load=None):
# checkpoints whose safetensors total exceeds this stream layer-by-
# layer instead of materializing on host first.
STREAMED_LOAD_AUTO_BYTES = 16e9


def _use_streamed_load(spec, multiproc: bool = False) -> bool:
    flag = getattr(spec, "streamed_load", None)
    if flag is not None:
        return bool(flag)
    # Auto mode sizes the checkpoint on the local filesystem. That is
    # safe on process-spanning meshes too: EVERY member reads the same
    # spec.path to load at all (shared FS by requirement), so the size
    # probe -- and with it the collective schedule -- agrees across
    # members. A member that cannot even stat the path would fail the
    # load itself, not just the probe.
    try:
        total = sum(
            os.path.getsize(os.path.join(spec.path, f))
            for f in os.listdir(spec.path) if f.endswith(".safetensors"))
    except OSError as e:
        if multiproc:
            # A silent eager fallback here could diverge from peers
            # that sized the path fine, mismatching the group's
            # collective load schedule -- fail loudly instead.
            raise RuntimeError(
                f"Could not size checkpoint {spec.path} for the auto "
                "streamed-load decision on a process-spanning mesh "
                f"({e}); set ModelSpec.streamed_load explicitly."
            ) from e
        logger.warning(
            "Could not size checkpoint %s for the auto streamed-load "
            "decision (%s); loading eagerly. Set "
            "ModelSpec.streamed_load=True if this model exceeds host "
            "RAM.", spec.path, e)
        return False
    if total > STREAMED_LOAD_AUTO_BYTES:
        logger.info(
            "Checkpoint %s is %.1f GB (> %.0f GB): loading streamed "
            "(set ModelSpec.streamed_load=False to force the eager "
            "path).", spec.path, total / 1e9,
            STREAMED_LOAD_AUTO_BYTES / 1e9)
        return True
    return False


def _agreed_streamed_load(spec, mesh, tag: str) -> bool:
    """The streamed-vs-eager verdict, AGREED across a process-spanning
    mesh: a divergent local verdict (e.g. one member's stale network-FS
    listing sizing the checkpoint differently) would mismatch the
    group's collective load schedules and hang. The mesh's lowest-rank
    process publishes its verdict under name_resolve; every other
    member adopts it instead of trusting its own filesystem view."""
    import jax

    flag = getattr(spec, "streamed_load", None)
    if flag is not None:
        # explicit flag: identical on every member by construction, no
        # rendezvous needed
        return bool(flag)
    multiproc = len({d.process_index for d in mesh.devices.flat}) > 1
    if not multiproc:
        return _use_streamed_load(spec)
    from realhf_tpu.base import name_resolve, names

    key = (names.trial_root(constants.experiment_name(),
                            constants.trial_name())
           + f"/streamed_load/{tag}")
    lead = min(d.process_index for d in mesh.devices.flat)
    if jax.process_index() == lead:
        verdict = _use_streamed_load(spec, multiproc=True)
        name_resolve.add(key, str(int(verdict)), replace=True)
        return verdict
    return bool(int(name_resolve.wait(key, timeout=300)))


def build_model(role: str, spec, tokenizer, total_steps: int,
                devices=None, params_override=None,
                cfg_override=None, init_seed=None,
                seed_role=None) -> model_api.Model:
    """Instantiate one model role on the local devices (reference
    ReaLModel instantiation in model_worker.__lazy_setup:294-337).

    ``seed_role``: role name to derive the random-init key from when
    it differs from ``role`` -- a CROSS-GROUP replica must initialize
    bit-identically to its role's primary living in another process,
    even though its display name carries the MFC suffix."""
    from realhf_tpu.parallel.mesh import default_devices

    # One mesh for both the (possibly streamed) load and the Engine:
    # the streamed loader places weights with this mesh's shardings,
    # and Engine.__init__'s device_put is then a no-op by identity.
    if devices is None:
        devices = default_devices()[:spec.parallel.world_size]
    mesh = make_mesh(spec.parallel, devices=devices)

    if params_override is not None:
        # Replica path: reuse the primary's live weights (device_put in
        # Engine.__init__ reshards them) instead of re-reading the
        # checkpoint.
        cfg, params = cfg_override, params_override
    elif spec.path and _agreed_streamed_load(spec, mesh, role):
        # Host-RAM-bounded: stream layer-by-layer straight onto the
        # mesh (needed for >host-RAM models; hf/registry.py).
        from realhf_tpu.models.hf import load_hf_checkpoint_streamed

        cfg, params = load_hf_checkpoint_streamed(
            spec.path, mesh, spec.hf_family,
            is_critic=spec.is_critic or spec.init_critic_from_actor,
            param_dtype="bfloat16" if spec.bf16 else None)
    elif spec.path:
        cfg, params = load_hf_checkpoint(
            spec.path, spec.hf_family,
            is_critic=spec.is_critic or spec.init_critic_from_actor)
    else:
        if spec.random_init_config is None:
            raise ValueError(
                f"Model role {role!r} has neither a checkpoint "
                "path nor a random_init_config; pass "
                f"`{role}.path=<hf-or-saved-checkpoint>` (CLI) or "
                "set random_init_config on its ModelSpec.")
        cfg = TransformerConfig(**spec.random_init_config,
                                is_critic=spec.is_critic)
        params = None
    if params_override is None:
        cfg.gradient_checkpointing = spec.gradient_checkpointing
        cfg.compute_dtype = "bfloat16" if spec.bf16 else "float32"
        if spec.bf16:
            # bf16 weights everywhere (reference bf16 training mode);
            # trainable engines keep an fp32 master copy inside the
            # ZeRO-sharded optimizer state (engine/optim.py
            # with_master_weights), frozen roles halve their footprint.
            cfg.param_dtype = "bfloat16"
    if params is None:
        # Model init must be identical on every process of a worker
        # group (the collective device_put verifies value equality), so
        # the key derives from the EXPERIMENT seed, never the ambient
        # per-worker seed.
        skey = seed_role or role
        key = (seeding.derive_key_from(init_seed, "model_init", skey)
               if init_seed is not None
               else seeding.derive_key("model_init", skey))
        params = T.init_params(cfg, key)

    ctx = MeshContext(ModelName(role, 0), mesh, spec.parallel)
    engine = Engine(cfg, ctx, params, optimizer=spec.optimizer,
                    total_train_steps=total_steps)
    if (params_override is None and spec.path
            and getattr(spec, "restore_optimizer_state", False)
            and engine.opt_state is not None):
        # RECOVERY only: restore saved Adam moments/master (exceeds
        # reference §5.4). Ordinary warm-starts from a checkpoint dir
        # must NOT inherit a previous trial's moments/LR step.
        from realhf_tpu.engine import opt_checkpoint
        opt_checkpoint.restore_engine_opt_state(engine, spec.path)
    return model_api.Model(ModelName(role, 0), engine, tokenizer,
                           hf_family=spec.hf_family)


class ModelHost:
    """All models of some roles + MFC execution with hooks.

    ``devices_fn(workers, parallel, device_ids) -> device list`` lets
    the distributed model worker place a mesh on a worker group's
    devices (multi-host model); None keeps the local default.
    ``leader_of_role`` marks whether THIS process is the role's group
    leader: non-leaders participate in every collective (save gather,
    eval forwards) but skip host-side writes and reply payloads.
    ``cross_group_nodes``: MFC names executing on a DIFFERENT worker
    group than their role's primary (reference per-MFC device subsets,
    quickstart/device_mesh.py:269). Their replica engines initialize
    from the same checkpoint/seed as the primary -- bit-identical
    start -- and are refreshed after train steps via the host
    data-plane parameter sync (``install_node_params``)."""

    def __init__(self, spec, roles: List[str], nodes: List[MFCDef],
                 tokenizer, total_steps: int, devices_fn=None,
                 leader_of_role: Optional[Dict[str, bool]] = None,
                 cross_group_nodes: Optional[set] = None):
        self.spec = spec
        self.roles = list(roles)
        self.nodes = {n.name: n for n in nodes}
        self.tokenizer = tokenizer
        self.devices_fn = devices_fn
        self.leader_of_role = leader_of_role or {}
        self.cross_group_nodes = set(cross_group_nodes or ())
        self.total_steps = total_steps
        # elastic adoption: nodes migrated here by the master while
        # their home worker is preempted/lost (system/elastic.py)
        self.adopted_nodes: set = set()

        def alloc_devices(alloc, workers):
            """Devices for a replica mesh: the worker-world slice in
            multihost mode, the LOCAL device subset when device_ids is
            set without a shared world (two single-process workers
            splitting one host's chips), default otherwise."""
            if devices_fn is not None:
                return devices_fn(workers, alloc.parallel,
                                  alloc.device_ids)
            if alloc.device_ids is not None:
                from realhf_tpu.parallel.mesh import default_devices
                local = default_devices()
                if any(i >= len(local) for i in alloc.device_ids):
                    raise ValueError(
                        f"device_ids {alloc.device_ids} out of range "
                        f"for {len(local)} local devices.")
                return [local[i] for i in alloc.device_ids]
            return None

        self.models: Dict[str, model_api.Model] = {}
        for role in self.roles:
            self.models[role] = build_model(
                role, spec.models[role], tokenizer, total_steps,
                devices=(devices_fn(spec.workers_of_role(role),
                                    spec.models[role].parallel, None)
                         if devices_fn else None),
                init_seed=spec.seed)

        # Replica engines for MFCs allocated on a different layout than
        # their role's primary. Replicas never own an optimizer;
        # weights flow from the primary via reallocation.
        self.replicas: Dict[str, model_api.Model] = {}
        self.replica_mgr = ReplicaManager()
        # node -> version of the primary weights currently installed
        # (cross-group sync protocol; 0 = initial checkpoint/seed)
        self.node_param_version: Dict[str, int] = {}
        # per-node execution records + HBM sample memo: initialized
        # HERE because execute() may run concurrently from
        # execute_level threads (lazy init would race on first use)
        self.exec_infos: Dict[str, dict] = {}
        self._hbm_memo: Dict[str, tuple] = {}
        # Same-role MFCs share one Engine (primary or replica refresh
        # path), so two concurrent execute() calls could race
        # ensure_on_device / a param-donating train step. One lock per
        # role serializes within the role while cross-role calls stay
        # threaded (execute_level's concurrency).
        import threading
        self._role_locks: Dict[str, threading.Lock] = {
            n.role: threading.Lock() for n in nodes}
        self._role_locks_guard = threading.Lock()
        for node in nodes:
            alloc = spec.alloc_of(node.name)
            if alloc is None:
                continue
            role = node.role
            if alloc.parallel.same_layout(
                    spec.models[role].parallel) \
                    and alloc.workers is None \
                    and alloc.device_ids is None:
                # redundant entry (same layout, same group): no-op,
                # never a replica -- accepted for generated configs
                # that list every MFC. A gen_tp_size ("g") override
                # does not change the weight layout but must still
                # reach the engine's decode view.
                self._install_gen_tp(self.models[role], alloc.parallel,
                                     node.name)
                continue
            if node.interface_type == ModelInterfaceType.TRAIN_STEP:
                raise ValueError(
                    f"MFC {node.name}: train MFCs must run on the "
                    "role's primary layout (replicas have no optimizer).")
            if node.name in self.cross_group_nodes:
                # Replica on OTHER devices than the primary (which may
                # not even live in this process). Initial weights come
                # from the same checkpoint / deterministic seed the
                # primary used, so no transfer is needed until the
                # primary trains.
                mspec = _dc.replace(spec.models[role],
                                    parallel=alloc.parallel,
                                    optimizer=None)
                exec_workers = spec.workers_of_node(node.name, role)
                self.replicas[node.name] = build_model(
                    f"{role}-{node.name}", mspec, tokenizer, total_steps,
                    devices=alloc_devices(alloc, exec_workers),
                    init_seed=spec.seed, seed_role=role)
                self.node_param_version[node.name] = 0
                logger.info(
                    "Created CROSS-GROUP replica for %s: %s on workers "
                    "%s (role %s).", node.name, alloc.parallel,
                    exec_workers, role)
                continue
            primary = self.models[role]
            if alloc.parallel.same_layout(primary.engine.ctx.parallel) \
                    and alloc.device_ids is None:
                self._install_gen_tp(primary, alloc.parallel, node.name)
                continue
            mspec = _dc.replace(spec.models[role], parallel=alloc.parallel,
                                optimizer=None)
            self.replicas[node.name] = build_model(
                f"{role}-{node.name}", mspec, tokenizer, total_steps,
                params_override=primary.engine.params,
                cfg_override=primary.config,
                devices=alloc_devices(
                    alloc, spec.workers_of_node(node.name, role)))
            logger.info("Created replica for %s: %s (primary %s)",
                        node.name, alloc.parallel,
                        primary.engine.ctx.parallel)

        self.interfaces = {
            n.name: model_api.make_interface(n.interface_impl)
            for n in nodes
        }

        if getattr(spec, "auto_offload", False):
            self._resolve_offload_hooks(nodes)

    @staticmethod
    def _install_gen_tp(model, par, node_name: str):
        """An MFC allocation that differs from the engine's layout only
        by gen_tp_size ("g", decode-view TP) is not a replica -- the
        weight layout is identical -- but the override must reach
        Engine.decode_engine, which reads ctx.parallel.gen_tp_size."""
        eng = model.engine
        cur = eng.ctx.parallel.gen_tp_size
        if not par.gen_tp_size or par.gen_tp_size == cur:
            return
        if cur and cur != par.gen_tp_size:
            logger.warning(
                "MFC %s sets gen_tp_size=%d over an engine already at "
                "gen_tp_size=%d; last writer wins.", node_name,
                par.gen_tp_size, cur)
        eng.set_gen_tp(par.gen_tp_size)

    @staticmethod
    def _resolve_offload_hooks(nodes: List[MFCDef]):
        """Attach OffloadHook post-hooks to the LAST MFC of every
        non-trainable role (reference resolve_rpc_hooks,
        experiments/common/utils.py:143): the role's weights live on
        host between steps, freeing HBM for training."""
        graph_nodes = [nodes[0]._G.nodes[x]["object"]
                       for x in nodes[0]._G.nodes] if nodes else []
        trainable_roles = {
            n.role for n in graph_nodes
            if n.interface_type == ModelInterfaceType.TRAIN_STEP}
        for node in nodes:
            if node.role in trainable_roles:
                continue
            if not node.is_dst_of_model_role:
                continue
            if any(isinstance(h, OffloadHook) for h in node._post_hooks):
                continue
            node.add_post_hook(OffloadHook())
            logger.info("Auto-resolved offload post-hook on %s (%s).",
                        node.name, node.role)

    # --- elastic degraded-mode adoption (system/elastic.py) -----------
    def adopt_node(self, node: MFCDef, parallel,
                   ckpt_path: Optional[str] = None) -> int:
        """Take over an MFC whose home worker was preempted/lost:
        build a replica engine on the degraded ``parallel`` layout and
        register the node for execution here. Returns the weight
        version now installed.

        Weight source, in preference order:

        - the role's PRIMARY lives in this process: the replica is
          seeded from its live params (``jax.device_put`` resharding
          onto the degraded mesh -- parallel/realloc.py); version =
          the primary's train step.
        - ``ckpt_path`` (a verified durable checkpoint dir): the
          emergency save of the preempted worker; version 0 (the
          cross-group sync refreshes forward if the role trains).
        - neither: the deterministic init seed -- bit-identical to the
          lost replica's own start; version 0, refreshed by the
          cross-group param sync exactly like a configure-time
          replica.
        """
        role = node.role
        self.nodes[node.name] = node
        if node.name not in self.interfaces:
            self.interfaces[node.name] = model_api.make_interface(
                node.interface_impl)
        self._role_lock(role)  # ensure the lock exists before exec
        primary = self.models.get(role)
        mspec = _dc.replace(self.spec.models[role], parallel=parallel,
                            optimizer=None)
        if primary is not None:
            self.replicas[node.name] = build_model(
                f"{role}-{node.name}", mspec, self.tokenizer,
                self.total_steps, params_override=primary.engine.params,
                cfg_override=primary.config)
            version = self.role_version(role)
        else:
            if ckpt_path is not None:
                mspec = _dc.replace(mspec, path=ckpt_path,
                                    random_init_config=None)
            self.replicas[node.name] = build_model(
                f"{role}-{node.name}", mspec, self.tokenizer,
                self.total_steps, init_seed=self.spec.seed,
                seed_role=role)
            self.node_param_version[node.name] = 0
            version = 0
        self.adopted_nodes.add(node.name)
        logger.info(
            "ADOPTED %s (role %s) on degraded layout %s (weights from "
            "%s, version %d).", node.name, role, parallel,
            "live primary" if primary is not None
            else (ckpt_path or "init seed"), version)
        return version

    def release_node(self, node_name: str) -> bool:
        """Drop an adopted node's replica (re-expansion: its original
        home rejoined). Frees the extra weight copy."""
        if node_name not in self.adopted_nodes:
            return False
        self.adopted_nodes.discard(node_name)
        self.node_param_version.pop(node_name, None)
        model = self.replicas.pop(node_name, None)
        self.interfaces.pop(node_name, None)
        self.nodes.pop(node_name, None)
        if model is not None:
            # drop engine references so the mesh arrays free promptly
            model.engine.params = None
        logger.info("RELEASED adopted node %s (home worker rejoined).",
                    node_name)
        return True

    # ------------------------------------------------------------------
    def engines_of_node(self, node: MFCDef):
        """(primary, exec model). Primary is None for a cross-group
        node whose role is not hosted in this process."""
        primary = self.models.get(node.role)
        model = self.replicas.get(node.name, primary)
        if model is None:
            raise ValueError(
                f"MFC {node.name}: neither a primary for role "
                f"{node.role} nor a replica lives in this process.")
        return primary, model

    # --- cross-group parameter sync (host data plane) -----------------
    def gather_role_params(self, role: str):
        """Sender side: host copy of the role's primary weights.
        COLLECTIVE on the primary's (possibly multi-process) mesh."""
        return self.models[role].engine.params_numpy()

    def install_node_params(self, node_name: str, host_params,
                            version: int, eta: float = 1.0):
        """Receiver side: land a fetched host weight copy on the
        cross-group replica's mesh (vocab repad + optional EMA merge
        handled by the reallocator)."""
        from realhf_tpu.parallel.realloc import reallocate
        model = self.replicas[node_name]
        model.engine.ensure_on_device()
        dt = reallocate(model.config, host_params, model.engine, eta=eta)
        self.replica_mgr.last_reshard_secs = dt
        self.node_param_version[node_name] = version
        logger.info("Installed params v%d on %s in %.3fs.", version,
                    node_name, dt)

    def install_node_params_streamed(self, node_name: str, n_chunks: int,
                                     fetch_chunk, version: int,
                                     eta: float = 1.0):
        """Receiver side, streamed: chunks land on the replica's mesh
        one at a time (parallel/realloc.py:install_param_chunks), so
        peak host memory is one chunk."""
        from realhf_tpu.parallel.realloc import install_param_chunks
        model = self.replicas[node_name]
        model.engine.ensure_on_device()
        dt, nbytes = install_param_chunks(model.config, model.engine,
                                          n_chunks, fetch_chunk, eta=eta)
        self.replica_mgr.last_reshard_secs = dt
        self.node_param_version[node_name] = version
        logger.info("Streamed params v%d onto %s: %d chunks, %.1f MB "
                    "in %.3fs (%.2f GB/s).", version, node_name,
                    n_chunks, nbytes / 1e6, dt,
                    nbytes / max(dt, 1e-9) / 1e9)

    def role_version(self, role: str) -> int:
        """The primary engine's train-step count (the version label
        stamped on outgoing param-sync streams)."""
        return self.models[role].version.global_step

    def node_version(self, node_name: str) -> int:
        return self.node_param_version.get(node_name, 0)

    def _role_lock(self, role: str):
        with self._role_locks_guard:
            if role not in self._role_locks:
                import threading
                self._role_locks[role] = threading.Lock()
            return self._role_locks[role]

    def execute(self, node_name: str, inp: data_api.SequenceSample):
        """Run one MFC: pre-hooks (reload offloaded weights, refresh
        replica), the interface call, post-hooks (offload). Same-role
        calls serialize on the role's lock (shared Engine); cross-role
        calls run concurrently (execute_level)."""
        node = self.nodes[node_name]
        with self._role_lock(node.role):
            return self._execute_locked(node_name, node, inp)

    def _execute_locked(self, node_name: str, node: MFCDef,
                        inp: data_api.SequenceSample):
        primary, model = self.engines_of_node(node)

        # pre-hooks -----------------------------------------------------
        if primary is not None:
            primary.engine.ensure_on_device()
        model.engine.ensure_on_device()
        eta = 1.0
        for h in node._pre_hooks:
            if isinstance(h, ParamReallocHook) and h.eta is not None:
                eta = h.eta
        if model is not primary and primary is not None \
                and node_name not in self.cross_group_nodes:
            # param-realloc pre-hook: refresh the replica's weights
            # from the trainable primary if it has stepped since.
            # (Cross-group replicas refresh via install_node_params
            # before execute is called.)
            self.replica_mgr.ensure_fresh(node.role, primary, model,
                                          eta=eta)

        if node.input_key_remap:
            inp = inp.select([k for k in inp.keys])
            inp.remap_keys_(node.input_key_remap)

        itf = self.interfaces[node_name]
        import time as _time

        from realhf_tpu.base import monitor
        from realhf_tpu.obs import tracing
        t_start = _time.time()
        # host-side span around the interface call (nests under the
        # worker's mfc:* request span in the merged timeline); the
        # TraceAnnotation inside mfc_profile_region covers the XLA view
        with tracing.span(f"compute:{node_name}", mfc=node_name,
                          role=node.role,
                          kind=node.interface_type.value):
            with monitor.mfc_profile_region(node_name):
                if node.interface_type == ModelInterfaceType.GENERATE:
                    out = itf.generate(model, inp, n_mbs=node.n_mbs)
                elif node.interface_type == ModelInterfaceType.INFERENCE:
                    out = itf.inference(model, inp, n_mbs=node.n_mbs)
                elif node.interface_type == ModelInterfaceType.TRAIN_STEP:
                    out = itf.train_step(model, inp, n_mbs=node.n_mbs)
                else:
                    raise NotImplementedError(node.interface_type)
        t_end = _time.time()
        # Per-MFC device stats (reference __log_gpu_stats,
        # model_worker.py:999-1094): wall span + HBM over this
        # process's mesh devices. JAX exposes no per-region peak
        # reset, so the table carries the pair: bytes in use right
        # after the call (attributable to what this MFC leaves
        # resident) and the process-lifetime allocator peak.
        # memory_stats() is a device query -- on a remote-attached
        # chip it costs a full relay round-trip (~0.1s) -- so by
        # default each MFC is SAMPLED ONCE, on its first (warmup)
        # execution; the reported peak is the peak as of that sample.
        # Set REALHF_TPU_HBM_STATS_EVERY_STEP=1 to re-query on every
        # execution (exact lifetime peaks, one round-trip per call).
        import jax

        every_step = os.environ.get(
            "REALHF_TPU_HBM_STATS_EVERY_STEP") == "1"
        if node_name in self._hbm_memo and not every_step:
            now, peak = self._hbm_memo[node_name]
        else:
            now, peak = self._hbm_memo.get(node_name, (0, 0))
            try:
                mine = jax.process_index()
                for d in {d for d in model.engine.mesh.devices.flat
                          if d.process_index == mine}:
                    stats = monitor.device_memory_stats(d)
                    now = max(now, stats.get("bytes_in_use", 0))
                    peak = max(peak, stats.get("peak_bytes_in_use", 0))
                # memoize only on success: a transient stats failure
                # must retry next execution, not freeze zeros forever
                self._hbm_memo[node_name] = (now, peak)
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass
        # ONE local dict assigned to both records: reading
        # self.last_exec_info back to fill exec_infos would let a
        # concurrent execute_level thread clobber it in between and
        # attribute the wrong node's secs/HBM to this node.
        info = dict(node=node_name, start=t_start, end=t_end,
                    secs=round(t_end - t_start, 4),
                    hbm_bytes_in_use=int(now),
                    proc_peak_hbm_bytes=int(peak))
        self.last_exec_info = info
        self.exec_infos[node_name] = info

        if isinstance(out, data_api.SequenceSample) and node.output_key_remap:
            out.remap_keys_(node.output_key_remap)

        # post-hooks ----------------------------------------------------
        if (node.interface_type == ModelInterfaceType.GENERATE
                and self.spec.models.get(node.role) is not None
                and self.spec.models[node.role]
                .drop_decode_view_after_rollout):
            freed = model.engine.decode_view_param_bytes()
            model.engine.drop_decode_view()
            if freed:
                logger.info(
                    "Dropped %s decode view after %s (freed %.2f GB "
                    "of mesh-wide weight copies; next rollout "
                    "reshards).", node.role, node_name, freed / 2 ** 30)
        for h in node._post_hooks:
            if isinstance(h, OffloadHook):
                model.engine.offload()
                if primary is not None and model is not primary:
                    # the role's primary holds a full weight copy too;
                    # leaving it resident would defeat the offload
                    primary.engine.offload()
                logger.info("Offloaded %s weights to host after %s.",
                            node.role, node_name)
        return out

    def execute_level(self, named_inputs, parallel: Optional[bool] = None):
        """Run a list of ``(node_name, inp)`` MFCs -- one topological
        level, mutually independent by construction -- CONCURRENTLY in
        threads, returning outputs in input order. On a single device
        the compute still serializes on the XLA stream; what overlaps
        is per-call host work (packing, dispatch, transfer syncs) --
        exactly what the distributed runtime overlaps across worker
        processes (the decoupled-allocation concurrency). jax dispatch
        is thread-safe, and two same-role nodes (which share one
        Engine) serialize on the role's lock inside execute(), so only
        genuinely independent cross-role work overlaps.
        ``parallel=False`` (or ``REALHF_TPU_PARALLEL_MFC=0``)
        serializes; ``REALHF_TPU_PARALLEL_MFC=1`` forces overlap.
        With neither set, overlap additionally requires >1 online
        CPU: concurrent XLA CPU executables carrying cross-module
        collectives rendezvous by spin-waiting across threads, and on
        a single-core host those spinners starve each other into a
        deadlock (observed as 'waiting for all participants to
        arrive at rendezvous' forever)."""
        if parallel is None:
            env = os.environ.get("REALHF_TPU_PARALLEL_MFC")
            if env is not None:
                parallel = env != "0"
            else:
                parallel = (os.cpu_count() or 1) > 1
        if len(named_inputs) == 1 or not parallel:
            return [self.execute(n, i) for n, i in named_inputs]
        from concurrent.futures import ThreadPoolExecutor

        from realhf_tpu.obs import tracing

        # pool threads have their own (empty) span stacks, so the
        # caller's context is captured here and re-attached per MFC --
        # the level's spans stay nested under the step span
        ctx = tracing.current_context()

        def run_one(n, i):
            with tracing.span(f"mfc:{n}", parent=ctx, mfc=n):
                return self.execute(n, i)

        with ThreadPoolExecutor(max_workers=len(named_inputs)) as ex:
            futs = [ex.submit(run_one, n, i) for n, i in named_inputs]
            return [f.result() for f in futs]

    # ------------------------------------------------------------------
    def save_role(self, role: str, train_node_name: str,
                  path: Optional[str] = None):
        """Checkpoint a role. ``path`` overrides the default
        ``run_save_path()/role`` target -- the durable-checkpoint
        manager points it at a staging directory that is checksummed
        and atomically committed after this returns
        (system/ckpt_manager.py)."""
        model = self.models[role]
        if path is None:
            path = os.path.join(constants.run_save_path(), role)
        if not getattr(self.interfaces[train_node_name], "enable_save",
                       True):
            # The leader's interface.save() returns without touching
            # the params; members must skip the collective path too or
            # they would block in a gather nobody else joins.
            return None
        # Streamed save on EVERY mesh (VERDICT r4 #5): the interface
        # streams one layer at a time from the device arrays
        # (interfaces/common.py save_checkpoint). On a multi-process
        # mesh each per-layer slice is a collective gather -- the save
        # runs on every group member in step, and only the leader
        # (writer=True) touches the filesystem. Peak host memory is
        # one layer + embeddings on every process, never the model.
        writer = self.leader_of_role.get(role, True)
        import inspect
        itf_save = self.interfaces[train_node_name].save
        save_err: Optional[BaseException] = None
        try:
            if "writer" in inspect.signature(itf_save).parameters:
                itf_save(model, path, writer=writer)
            else:
                # Externally registered interface predating the writer
                # kwarg: keep the old contract (pre-gathered host copy
                # on multi-process meshes, leader-only call).
                host_params = (model.engine.params_numpy()
                               if model.engine.multiproc else None)
                if writer:
                    itf_save(model, path, host_params=host_params)
        except Exception as e:  # noqa: BLE001 - re-raised below
            # The streamed save completes its collective schedule
            # before raising writer-side IO errors, but raising HERE
            # would still skip the writer's opt-state collectives
            # while members run theirs -- hold the error until every
            # collective phase of this save is done.
            save_err = e
        if model.engine.opt_state is not None:
            # EXCEEDS reference: Adam moments + fp32 master survive
            # recovery instead of re-warming from zero (§5.4). Same
            # streaming discipline: one leaf host-resident at a time,
            # collective per leaf on multi-process meshes (members
            # drain the iterator to keep collective counts aligned).
            from realhf_tpu.engine import opt_checkpoint
            leaf_iter = model.engine.iter_opt_state_numpy()
            if writer and save_err is None:
                try:
                    opt_checkpoint.save_opt_state_iter(path, leaf_iter)
                except Exception as e:  # noqa: BLE001 - raised below
                    # a writer-side IO failure mid-stream must not
                    # desync the members' per-leaf collective gathers
                    save_err = e
            # members -- and a writer that already failed -- drain the
            # iterator so per-leaf collective counts stay matched
            for _ in leaf_iter:
                pass
        if save_err is not None:
            raise save_err
        if not writer:
            return None
        logger.info("Saved %s to %s", role, path)
        return path

    def evaluate_role(self, role: str, train_node_name: str,
                      eval_dataloader) -> Optional[dict]:
        out = self.interfaces[train_node_name].evaluate(
            self.models[role], eval_dataloader)
        # non-leader members ran the (collective) eval forwards; only
        # the leader reports
        if not self.leader_of_role.get(role, True):
            return None
        return out
