"""Model hosting shared by the inline runner and the model worker.

Owns, for a set of model roles on the local device fleet: the primary
engines (with optimizers for trainable roles), per-MFC weight replicas
on alternative layouts (reference ``resolve_replica_ids``,
experiments/common/utils.py:126), algorithm interfaces, and MFC
execution including the replica-refresh (param-realloc) and offload
hooks around each call (reference ``model_worker.handle_all_pre_hooks``
/ post hooks, model_worker.py:483-552).
"""

import dataclasses as _dc
import os
from typing import Dict, List, Optional

from realhf_tpu.api import data as data_api
from realhf_tpu.api import model as model_api
from realhf_tpu.api.config import ModelInterfaceType, ModelName
from realhf_tpu.api.dfg import MFCDef, OffloadHook, ParamReallocHook
from realhf_tpu.base import constants, logging, seeding
from realhf_tpu.engine.engine import Engine
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf import load_hf_checkpoint
from realhf_tpu.parallel.mesh import MeshContext, make_mesh
from realhf_tpu.parallel.realloc import ReplicaManager

logger = logging.getLogger("model_host", "benchmark")


def build_model(role: str, spec, tokenizer, total_steps: int,
                devices=None, params_override=None,
                cfg_override=None, init_seed=None) -> model_api.Model:
    """Instantiate one model role on the local devices (reference
    ReaLModel instantiation in model_worker.__lazy_setup:294-337)."""
    from realhf_tpu.parallel.mesh import default_devices

    if params_override is not None:
        # Replica path: reuse the primary's live weights (device_put in
        # Engine.__init__ reshards them) instead of re-reading the
        # checkpoint.
        cfg, params = cfg_override, params_override
    elif spec.path:
        cfg, params = load_hf_checkpoint(
            spec.path, spec.hf_family,
            is_critic=spec.is_critic or spec.init_critic_from_actor)
    else:
        cfg = TransformerConfig(**spec.random_init_config,
                                is_critic=spec.is_critic)
        params = None
    if params_override is None:
        cfg.gradient_checkpointing = spec.gradient_checkpointing
        cfg.compute_dtype = "bfloat16" if spec.bf16 else "float32"
    if params is None:
        # Model init must be identical on every process of a worker
        # group (the collective device_put verifies value equality), so
        # the key derives from the EXPERIMENT seed, never the ambient
        # per-worker seed.
        key = (seeding.derive_key_from(init_seed, "model_init", role)
               if init_seed is not None
               else seeding.derive_key("model_init", role))
        params = T.init_params(cfg, key)

    if devices is None:
        devices = default_devices()[:spec.parallel.world_size]
    mesh = make_mesh(spec.parallel, devices=devices)
    ctx = MeshContext(ModelName(role, 0), mesh, spec.parallel)
    engine = Engine(cfg, ctx, params, optimizer=spec.optimizer,
                    total_train_steps=total_steps)
    return model_api.Model(ModelName(role, 0), engine, tokenizer,
                           hf_family=spec.hf_family)


class ModelHost:
    """All models of some roles + MFC execution with hooks.

    ``devices_fn(role, parallel) -> device list`` lets the distributed
    model worker place a role's mesh on its worker GROUP's devices
    (multi-host model); None keeps the local default. ``leader_of_role``
    marks whether THIS process is the role's group leader: non-leaders
    participate in every collective (save gather, eval forwards) but
    skip host-side writes and reply payloads."""

    def __init__(self, spec, roles: List[str], nodes: List[MFCDef],
                 tokenizer, total_steps: int, devices_fn=None,
                 leader_of_role: Optional[Dict[str, bool]] = None):
        self.spec = spec
        self.roles = list(roles)
        self.nodes = {n.name: n for n in nodes}
        self.tokenizer = tokenizer
        self.devices_fn = devices_fn
        self.leader_of_role = leader_of_role or {}

        self.models: Dict[str, model_api.Model] = {}
        for role in self.roles:
            self.models[role] = build_model(
                role, spec.models[role], tokenizer, total_steps,
                devices=(devices_fn(role, spec.models[role].parallel)
                         if devices_fn else None),
                init_seed=spec.seed)

        # Replica engines for MFCs allocated on a different layout than
        # their role's primary. Replicas never own an optimizer;
        # weights flow from the primary via reallocation.
        self.replicas: Dict[str, model_api.Model] = {}
        self.replica_mgr = ReplicaManager()
        for node in nodes:
            alloc = spec.allocations.get(node.name)
            if alloc is None:
                continue
            role = node.role
            primary = self.models[role]
            if alloc.same_layout(primary.engine.ctx.parallel):
                continue
            if node.interface_type == ModelInterfaceType.TRAIN_STEP:
                raise ValueError(
                    f"MFC {node.name}: train MFCs must run on the "
                    "role's primary layout (replicas have no optimizer).")
            mspec = _dc.replace(spec.models[role], parallel=alloc,
                                optimizer=None)
            self.replicas[node.name] = build_model(
                f"{role}-{node.name}", mspec, tokenizer, total_steps,
                params_override=primary.engine.params,
                cfg_override=primary.config,
                devices=(devices_fn(role, alloc) if devices_fn
                         else None))
            logger.info("Created replica for %s: %s (primary %s)",
                        node.name, alloc, primary.engine.ctx.parallel)

        self.interfaces = {
            n.name: model_api.make_interface(n.interface_impl)
            for n in nodes
        }

        if getattr(spec, "auto_offload", False):
            self._resolve_offload_hooks(nodes)

    @staticmethod
    def _resolve_offload_hooks(nodes: List[MFCDef]):
        """Attach OffloadHook post-hooks to the LAST MFC of every
        non-trainable role (reference resolve_rpc_hooks,
        experiments/common/utils.py:143): the role's weights live on
        host between steps, freeing HBM for training."""
        graph_nodes = [nodes[0]._G.nodes[x]["object"]
                       for x in nodes[0]._G.nodes] if nodes else []
        trainable_roles = {
            n.role for n in graph_nodes
            if n.interface_type == ModelInterfaceType.TRAIN_STEP}
        for node in nodes:
            if node.role in trainable_roles:
                continue
            if not node.is_dst_of_model_role:
                continue
            if any(isinstance(h, OffloadHook) for h in node._post_hooks):
                continue
            node.add_post_hook(OffloadHook())
            logger.info("Auto-resolved offload post-hook on %s (%s).",
                        node.name, node.role)

    # ------------------------------------------------------------------
    def engines_of_node(self, node: MFCDef):
        primary = self.models[node.role]
        model = self.replicas.get(node.name, primary)
        return primary, model

    def execute(self, node_name: str, inp: data_api.SequenceSample):
        """Run one MFC: pre-hooks (reload offloaded weights, refresh
        replica), the interface call, post-hooks (offload)."""
        node = self.nodes[node_name]
        primary, model = self.engines_of_node(node)

        # pre-hooks -----------------------------------------------------
        primary.engine.ensure_on_device()
        model.engine.ensure_on_device()
        eta = 1.0
        for h in node._pre_hooks:
            if isinstance(h, ParamReallocHook) and h.eta is not None:
                eta = h.eta
        if model is not primary:
            # param-realloc pre-hook: refresh the replica's weights
            # from the trainable primary if it has stepped since.
            self.replica_mgr.ensure_fresh(node.role, primary, model,
                                          eta=eta)

        if node.input_key_remap:
            inp = inp.select([k for k in inp.keys])
            inp.remap_keys_(node.input_key_remap)

        itf = self.interfaces[node_name]
        from realhf_tpu.base import monitor
        with monitor.mfc_profile_region(node_name):
            if node.interface_type == ModelInterfaceType.GENERATE:
                out = itf.generate(model, inp, n_mbs=node.n_mbs)
            elif node.interface_type == ModelInterfaceType.INFERENCE:
                out = itf.inference(model, inp, n_mbs=node.n_mbs)
            elif node.interface_type == ModelInterfaceType.TRAIN_STEP:
                out = itf.train_step(model, inp, n_mbs=node.n_mbs)
            else:
                raise NotImplementedError(node.interface_type)

        if isinstance(out, data_api.SequenceSample) and node.output_key_remap:
            out.remap_keys_(node.output_key_remap)

        # post-hooks ----------------------------------------------------
        for h in node._post_hooks:
            if isinstance(h, OffloadHook):
                model.engine.offload()
                if model is not primary:
                    # the role's primary holds a full weight copy too;
                    # leaving it resident would defeat the offload
                    primary.engine.offload()
                logger.info("Offloaded %s weights to host after %s.",
                            node.role, node_name)
        return out

    # ------------------------------------------------------------------
    def save_role(self, role: str, train_node_name: str):
        model = self.models[role]
        path = os.path.join(constants.run_save_path(), role)
        if not getattr(self.interfaces[train_node_name], "enable_save",
                       True):
            # The leader's interface.save() returns without touching
            # the params; members must skip the collective gather too
            # or they would block in an all-gather nobody else joins.
            return None
        if not self.leader_of_role.get(role, True):
            # Group member, not leader: params_numpy() is a COLLECTIVE
            # on a multi-process mesh -- participate in the gather the
            # leader's interface.save() runs, but write nothing.
            model.engine.params_numpy()
            return None
        self.interfaces[train_node_name].save(model, path)
        logger.info("Saved %s to %s", role, path)
        return path

    def evaluate_role(self, role: str, train_node_name: str,
                      eval_dataloader) -> Optional[dict]:
        out = self.interfaces[train_node_name].evaluate(
            self.models[role], eval_dataloader)
        # non-leader members ran the (collective) eval forwards; only
        # the leader reports
        if not self.leader_of_role.get(role, True):
            return None
        return out
