"""Durable sharded checkpoints: checksums, atomic commit, fallback.

The save path used to be "write files, hope": a preemption mid-save
left a half-written checkpoint indistinguishable from a complete one,
a flipped bit in a shard surfaced as a cryptic load error (or worse,
silently wrong weights), and every save blocked the train step for
the full serialization. This module is the durability layer under
``ModelHost.save_role`` and ``base/recover.py`` (RecoverInfo v3):

- **Shards + manifest.** A checkpoint is a directory of shard files
  (safetensors / npz / whatever the writer produced -- the manager is
  format-agnostic) plus ``manifest.json`` recording every shard's
  size and SHA-256. On multi-host runs each host leader writes its
  own shards under a host tag; the manifest unions them.
- **Atomic commit.** Shards are staged under a dot-prefixed temp
  directory on the same filesystem; every shard is fsynced, the
  manifest is fsynced, the directory is renamed into place, and only
  then is a ``COMMITTED`` marker created (fsynced, parent dir
  fsynced). A directory without the marker is by definition garbage
  -- a crash at ANY point leaves either the previous committed
  checkpoint or a partial that ``gc()`` sweeps.
- **Verified load with fallback.** ``latest_verified()`` walks
  committed checkpoints newest-first, re-hashing every shard; a
  corrupt shard (bit rot, torn write, ``corrupt_ckpt`` fault
  injection) rejects the whole checkpoint and falls back to the
  previous committed one, loudly.
- **Background saves.** ``save_async`` runs the writer callback in a
  daemon thread so the train loop never blocks on serialization;
  saves are single-flight (an overlapping request is rejected, not
  queued -- the next save interval retries with fresher weights).
- **Emergency save.** ``emergency_save`` is the preemption-notice
  path: wait out any in-flight background save, then save
  synchronously -- the last act before a PREEMPTED exit.

Fault injection: the manager reports ``ckpt_commit`` events to an
optional :class:`~realhf_tpu.base.fault_injection.FaultInjector`; a
matching ``corrupt_ckpt`` spec flips bytes in a shard of the
just-committed checkpoint (``base/fault_injection.py:flip_bytes``),
which the next verified load must catch by checksum.
"""

import dataclasses
import hashlib
import json
import os
import re
import shutil
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics as obs_metrics

logger = logging.getLogger("ckpt_manager")

MANIFEST = "manifest.json"
COMMIT_MARKER = "COMMITTED"
MANIFEST_VERSION = 1

_STEP_DIR_RE = re.compile(r"^step_(\d{8,})$")


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_file(path: str):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    name: str      # path relative to the checkpoint dir
    size: int
    sha256: str


@dataclasses.dataclass(frozen=True)
class CheckpointRecord:
    """One committed (or partial) checkpoint directory."""
    step: int
    path: str

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.path, MANIFEST)

    @property
    def committed(self) -> bool:
        return (os.path.isfile(os.path.join(self.path, COMMIT_MARKER))
                and os.path.isfile(self.manifest_path))

    def manifest(self) -> Dict:
        with open(self.manifest_path, "r") as f:
            return json.load(f)


class CheckpointWriter:
    """One staged checkpoint: write shard files under :attr:`path`
    (any layout, subdirectories welcome), then :meth:`commit` -- or
    :meth:`abort` to sweep the staging directory."""

    def __init__(self, manager: "CheckpointManager", step: int,
                 meta: Optional[Dict] = None, host: Optional[str] = None):
        self._mgr = manager
        self.step = int(step)
        self.meta = dict(meta or {})
        self.host = host
        self.path = os.path.join(
            manager.root, f".tmp-step_{self.step:08d}-{uuid.uuid4().hex[:8]}")
        os.makedirs(self.path, exist_ok=True)
        self._done = False

    def write_shard(self, name: str, data: bytes) -> str:
        """Convenience byte-blob shard (callers producing files
        directly just write under :attr:`path`)."""
        p = os.path.join(self.path, name)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        return p

    def _collect_shards(self) -> List[ShardInfo]:
        shards = []
        for dirpath, _dirnames, filenames in os.walk(self.path):
            for fn in sorted(filenames):
                if fn in (MANIFEST, COMMIT_MARKER):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, self.path)
                name = rel if self.host is None else \
                    os.path.join(self.host, rel)
                shards.append(ShardInfo(
                    name=name, size=os.path.getsize(full),
                    sha256=_sha256_file(full)))
        return sorted(shards, key=lambda s: s.name)

    def commit(self) -> CheckpointRecord:
        """fsync every shard, write+fsync the manifest, rename the
        directory into place, then create the COMMITTED marker. Only
        after the marker lands (and the parent dir is fsynced) does
        this checkpoint exist as far as loads are concerned."""
        if self._done:
            raise RuntimeError("CheckpointWriter already committed/aborted")
        t0 = time.monotonic()
        shards = self._collect_shards()
        for s in shards:
            local = s.name if self.host is None else \
                os.path.relpath(s.name, self.host)
            _fsync_file(os.path.join(self.path, local))
        manifest = dict(
            version=MANIFEST_VERSION, step=self.step,
            created=time.time(), host=self.host, meta=self.meta,
            shards=[dataclasses.asdict(s) for s in shards])
        mpath = os.path.join(self.path, MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(self._mgr.root, f"step_{self.step:08d}")
        if os.path.isdir(final):
            # a re-save of the same step replaces the old dir wholesale
            # (idempotent save retries); push the old one aside first
            # so the rename is atomic, then sweep it
            stale = final + f".stale-{uuid.uuid4().hex[:8]}"
            os.replace(final, stale)
            shutil.rmtree(stale, ignore_errors=True)
        os.replace(self.path, final)
        marker = os.path.join(final, COMMIT_MARKER)
        with open(marker, "w") as f:
            f.write(f"{time.time():.3f}\n")
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(final)
        _fsync_dir(self._mgr.root)
        self._done = True
        rec = CheckpointRecord(step=self.step, path=final)
        obs_metrics.observe("ckpt_commit_secs",
                            time.monotonic() - t0)
        obs_metrics.inc("ckpt_commits_total")
        logger.info("Committed checkpoint step %d: %d shards, %.1f MB "
                    "at %s.", self.step, len(shards),
                    sum(s.size for s in shards) / 1e6, final)
        self._mgr._on_commit(rec)
        return rec

    def abort(self):
        if not self._done:
            shutil.rmtree(self.path, ignore_errors=True)
            self._done = True


class CheckpointManager:
    """Durable checkpoints for one namespace (typically one model
    role) under ``root``. Thread-compatible: the background-save
    thread only touches the staging dir until commit, and commit's
    bookkeeping is lock-guarded."""

    def __init__(self, root: str, keep: int = 2,
                 injector=None, owner: str = "ckpt_manager"):
        self.root = root
        self.keep = max(1, int(keep))
        self._injector = injector
        self._owner = owner
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._bg_thread: Optional[threading.Thread] = None
        self._bg_error: Optional[BaseException] = None
        self._bg_staging: Optional[str] = None
        self._bg_record: Optional[CheckpointRecord] = None
        self.saves_skipped_inflight = 0

    # -- enumeration ---------------------------------------------------
    def records(self) -> List[CheckpointRecord]:
        """All step directories (committed or not), oldest first."""
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return []
        for d in entries:
            m = _STEP_DIR_RE.match(d)
            if m:
                out.append(CheckpointRecord(
                    step=int(m.group(1)),
                    path=os.path.join(self.root, d)))
        return sorted(out, key=lambda r: r.step)

    def latest_committed(self) -> Optional[CheckpointRecord]:
        recs = [r for r in self.records() if r.committed]
        return recs[-1] if recs else None

    # -- verification --------------------------------------------------
    def verify(self, rec: CheckpointRecord) -> Tuple[bool, List[str]]:
        """Re-hash every shard against the manifest. Returns
        (ok, problems); problems name the offending shard paths."""
        t0 = time.monotonic()
        try:
            return self._verify_timed(rec)
        finally:
            obs_metrics.observe("ckpt_verify_secs",
                                time.monotonic() - t0)

    def _verify_timed(self, rec: CheckpointRecord
                      ) -> Tuple[bool, List[str]]:
        problems: List[str] = []
        if not rec.committed:
            return False, [f"{rec.path}: no {COMMIT_MARKER} marker"]
        try:
            manifest = rec.manifest()
        except (OSError, ValueError) as e:
            return False, [f"{rec.manifest_path}: unreadable ({e})"]
        for s in manifest.get("shards", ()):
            p = os.path.join(rec.path, s["name"])
            if not os.path.isfile(p):
                problems.append(f"{p}: missing")
                continue
            size = os.path.getsize(p)
            if size != s["size"]:
                problems.append(
                    f"{p}: size {size} != manifest {s['size']}")
                continue
            digest = _sha256_file(p)
            if digest != s["sha256"]:
                problems.append(
                    f"{p}: sha256 {digest[:12]}... != manifest "
                    f"{s['sha256'][:12]}...")
        return not problems, problems

    def latest_verified(self) -> Optional[CheckpointRecord]:
        """Newest committed checkpoint whose every shard passes its
        checksum; corrupt ones are skipped (loudly) in favor of the
        previous committed manifest."""
        for rec in reversed([r for r in self.records() if r.committed]):
            ok, problems = self.verify(rec)
            if ok:
                return rec
            logger.error(
                "Checkpoint step %d at %s REJECTED by verification; "
                "falling back to the previous committed checkpoint. "
                "Problems: %s", rec.step, rec.path, "; ".join(problems))
        return None

    def resolve_manifest(self, manifest_path: str
                         ) -> Optional[CheckpointRecord]:
        """The record for a RecoverInfo-recorded manifest path IF it
        still verifies; otherwise the latest verified fallback."""
        d = os.path.dirname(os.path.abspath(manifest_path))
        m = _STEP_DIR_RE.match(os.path.basename(d))
        if m:
            rec = CheckpointRecord(step=int(m.group(1)), path=d)
            ok, problems = self.verify(rec)
            if ok:
                return rec
            logger.error(
                "Recorded checkpoint manifest %s fails verification "
                "(%s); falling back.", manifest_path,
                "; ".join(problems))
        return self.latest_verified()

    # -- garbage collection --------------------------------------------
    def gc(self, keep: Optional[int] = None) -> List[str]:
        """Sweep (a) partial/uncommitted checkpoint dirs -- staging
        leftovers and marker-less step dirs -- and (b) committed
        checkpoints beyond the newest ``keep``. Returns removed
        paths. Never touches the staging dir of an in-flight
        background save."""
        keep = self.keep if keep is None else max(1, int(keep))
        removed: List[str] = []
        with self._lock:
            live_staging = self._bg_staging
        try:
            entries = os.listdir(self.root)
        except OSError:
            return removed
        for d in entries:
            full = os.path.join(self.root, d)
            if d.startswith(".tmp-step_") and full != live_staging:
                shutil.rmtree(full, ignore_errors=True)
                removed.append(full)
        committed, partial = [], []
        for rec in self.records():
            (committed if rec.committed else partial).append(rec)
        for rec in partial:
            shutil.rmtree(rec.path, ignore_errors=True)
            removed.append(rec.path)
        for rec in committed[:-keep]:
            shutil.rmtree(rec.path, ignore_errors=True)
            removed.append(rec.path)
        if removed:
            logger.info("Checkpoint GC removed %d dirs: %s",
                        len(removed),
                        [os.path.basename(p) for p in removed])
        return removed

    # -- saving --------------------------------------------------------
    def begin(self, step: int, meta: Optional[Dict] = None,
              host: Optional[str] = None) -> CheckpointWriter:
        return CheckpointWriter(self, step, meta=meta, host=host)

    def save(self, step: int,
             produce: Callable[[CheckpointWriter], None],
             meta: Optional[Dict] = None) -> CheckpointRecord:
        """Synchronous save: stage via ``produce(writer)`` (which
        writes shard files under ``writer.path``), then commit + GC."""
        w = self.begin(step, meta=meta)
        try:
            produce(w)
            rec = w.commit()
        except BaseException:
            w.abort()
            raise
        self.gc()
        return rec

    def save_async(self, step: int,
                   produce: Callable[[CheckpointWriter], None],
                   meta: Optional[Dict] = None) -> bool:
        """Background save; returns False (and counts the skip) when a
        previous background save is still in flight -- the caller's
        next save interval simply retries with fresher state. The
        producer callback must snapshot device state to host ITSELF
        (on its own thread) or be handed an already-materialized
        snapshot; the manager never blocks the caller."""
        with self._lock:
            if self._bg_thread is not None and self._bg_thread.is_alive():
                self.saves_skipped_inflight += 1
                logger.warning(
                    "Skipping background checkpoint at step %d: "
                    "previous save still in flight.", step)
                return False
            self._bg_error = None
            t = threading.Thread(
                target=self._bg_save, args=(step, produce, meta),
                name=f"ckpt_save[{os.path.basename(self.root)}]",
                daemon=True)
            self._bg_thread = t
        t.start()
        return True

    def _bg_save(self, step, produce, meta):
        try:
            w = self.begin(step, meta=meta)
            with self._lock:
                self._bg_staging = w.path
            try:
                produce(w)
                w.commit()
            except BaseException:
                w.abort()
                raise
            self.gc()
        except BaseException as e:  # noqa: BLE001 - surfaced via wait()
            logger.error("Background checkpoint save at step %d "
                         "failed: %s", step, e, exc_info=True)
            with self._lock:
                self._bg_error = e
        finally:
            with self._lock:
                self._bg_staging = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join any in-flight background save. Returns True when idle;
        re-raises a background failure (once)."""
        with self._lock:
            t = self._bg_thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                return False
        with self._lock:
            if self._bg_thread is t:
                self._bg_thread = None
            err, self._bg_error = self._bg_error, None
        if err is not None:
            raise err
        return True

    def emergency_save(self, step: int,
                       produce: Callable[[CheckpointWriter], None],
                       meta: Optional[Dict] = None,
                       deadline: Optional[float] = None
                       ) -> Optional[CheckpointRecord]:
        """Preemption-notice path: wait out an in-flight background
        save (it may already carry this state), then save
        synchronously. ``deadline`` (monotonic) bounds the wait; a
        deadline overrun returns None rather than risking a torn
        write racing the in-flight save."""
        budget = None if deadline is None else \
            max(0.0, deadline - time.monotonic())
        try:
            idle = self.wait(timeout=budget)
        except BaseException as e:  # noqa: BLE001 - bg failure: retry now
            logger.warning("Emergency save proceeding after background "
                           "save failure: %s", e)
            idle = True
        if not idle:
            logger.error("Emergency save at step %d ABANDONED: "
                         "background save still running at the "
                         "preemption deadline.", step)
            return None
        latest = self.latest_committed()
        if latest is not None and latest.step >= step:
            logger.info("Emergency save at step %d unnecessary: step "
                        "%d already committed.", step, latest.step)
            return latest
        meta = dict(meta or {}, emergency=True)
        return self.save(step, produce, meta=meta)

    # -- commit hook (fault injection) ---------------------------------
    def _on_commit(self, rec: CheckpointRecord):
        with self._lock:
            self._bg_record = rec
        if self._injector is None:
            return
        fault = self._injector.on_event(self._owner, "ckpt_commit")
        if fault is not None and fault.kind == "corrupt_ckpt":
            from realhf_tpu.base.fault_injection import flip_bytes
            shards = rec.manifest().get("shards", ())
            if shards:
                target = os.path.join(rec.path, shards[0]["name"])
                logger.error("Fault injection: corrupting shard %s of "
                             "the just-committed checkpoint.", target)
                flip_bytes(target)

    @property
    def last_committed_record(self) -> Optional[CheckpointRecord]:
        with self._lock:
            return self._bg_record
